"""Multi-tenant LoRA adapter serving: device bank + hot-swap registry
(docs/serving.md "Multi-tenant LoRA").

Thousands of fine-tunes sharing one base-model fleet is the scenario
that makes scale-out economical: adapters produced by the training path
(models/lora.py) are served per-request without dedicating a replica —
or even a decode slot — per tenant. Three pieces:

- :class:`AdapterBank` — the device-resident working set: per-target
  stacked low-rank factors ``[n_slots, L, in, r]`` / ``[n_slots, L, r,
  out]`` / ``[n_slots, L]`` gathered by a per-row adapter index inside
  the batched forwards (llm.py ``_forward_with_cache``, llm_batch.py
  ``_decode_rowwise``, paged.py ``_decode_rowwise_paged``). Slot 0 is
  the base model (all-zero factors = zero delta), so padding rows and
  adapterless requests ride the same compiled program. Shapes are
  static: loading an adapter is an ``.at[slot].set`` content update,
  never a recompile.
- :class:`AdapterRegistry` — named adapters hot-loaded from the
  artifact store/datastore (or an in-memory dict / callables), a
  host-side LRU of deserialized trees in front of the device bank, and
  refcounts pinning a resident adapter while ANY request uses it.
  Capacity is ``mlconf.serving.llm.adapters.max_live_adapters``; typed
  404/429 failures (:class:`UnknownAdapterError`,
  :class:`AdapterCapacityError`) keep a bad tenant id or a full working
  set a fast per-request error, never an engine failure. Load/evict
  fire the ``llm.adapter_load`` chaos point.
- :class:`TenantRateLimiter` — a token bucket per adapter id in front
  of the shared admission queue, so one flooding tenant is shed with a
  typed 429 (:class:`AdapterRateLimitError`) instead of starving every
  other tenant's queue budget.

Adapter identity is the NAME: the prefix cache and the fleet routing
key are namespaced by it (serving/prefix.py), so KV computed under
adapter A is never reused for adapter B. Names are treated as immutable
versions (like artifact keys) — re-publishing different weights under
the same name would serve stale prefix KV and must use a new name.
"""

from __future__ import annotations

import io
import threading
import time
from collections import OrderedDict
from typing import Callable, Optional, Sequence

from ..chaos import FaultPoints, fire
from ..models.lora import (
    DEFAULT_TARGETS,
    LoraShapeError,
    lora_rank,
    validate_lora,
)
from .resilience import AdmissionRejected, ResilienceError


# -- errors ------------------------------------------------------------------
class AdapterError(ResilienceError):
    """Base for adapter-registry failures (per-request, never fatal to
    the engine)."""


class UnknownAdapterError(AdapterError):
    """The request names an adapter no source can provide — a client
    error (404), rejected at submit() before any queueing."""

    status_code = 404


class AdapterCapacityError(AdapterError, AdmissionRejected):
    """Every device bank slot is pinned by in-flight requests of OTHER
    adapters — retry later (429), or raise
    ``mlconf.serving.llm.adapters.max_live_adapters``."""

    status_code = 429


class AdapterRateLimitError(AdmissionRejected):
    """The tenant's token bucket is empty — per-adapter admission
    fairness shed this request (429) so one flooding tenant cannot
    starve the shared queue."""

    status_code = 429


# -- artifact (de)serialization ----------------------------------------------
def save_adapter(target_path: str, lora: dict):
    """Serialize an adapter tree to one ``.npz`` at ``target_path``
    (datastore url or local path) — the artifact the registry hot-loads.
    Keys are ``<target>/<factor>``, e.g. ``wq/lora_a``."""
    import numpy as np

    validate_lora(lora)
    flat = {}
    for target, adapter in lora.items():
        for key in ("lora_a", "lora_b", "scaling"):
            flat[f"{target}/{key}"] = np.asarray(adapter[key])
    buf = io.BytesIO()
    np.savez(buf, **flat)
    if "://" in target_path:
        from ..datastore import store_manager

        store_manager.object(url=target_path).put(buf.getvalue())
    else:
        with open(target_path, "wb") as fp:
            fp.write(buf.getvalue())


def load_adapter(path: str) -> dict:
    """Inverse of :func:`save_adapter`: read an ``.npz`` adapter artifact
    from the datastore (``store://``/``s3://``/... urls ride DataItem,
    composing with the ``datastore.read`` chaos point) or a local path,
    back into the ``{target: {lora_a, lora_b, scaling}}`` tree."""
    import numpy as np

    if "://" in path:
        from ..datastore import store_manager

        data = store_manager.object(url=path).get()
    else:
        with open(path, "rb") as fp:
            data = fp.read()
    blob = np.load(io.BytesIO(data))
    lora: dict = {}
    for key in blob.files:
        target, factor = key.rsplit("/", 1)
        lora.setdefault(target, {})[factor] = blob[key]
    return lora


# -- device bank -------------------------------------------------------------
class AdapterBank:
    """Stacked per-target LoRA factors on device, indexed by bank slot.

    ``tensors[target] = {"lora_a": [S, L, in, r], "lora_b": [S, L, r,
    out], "scaling": [S, L]}`` with S = 1 + max_live (slot 0 = base,
    all zeros). The batched forwards gather rows by a per-request /
    per-decode-row slot index, so every batch row applies its own
    (A, B) delta inside ONE compiled program.
    """

    def __init__(self, config, max_live: int, rank: int,
                 targets: Sequence[str] = DEFAULT_TARGETS):
        import jax.numpy as jnp

        from ..models.lora import _PROJ_DIMS

        if max_live < 1:
            raise ValueError(f"max_live must be >= 1, got {max_live}")
        if rank < 1:
            raise ValueError(f"rank must be >= 1, got {rank}")
        self.config = config
        self.max_live = int(max_live)
        self.n_slots = self.max_live + 1
        self.rank = int(rank)
        self.targets = tuple(targets)
        tensors = {}
        for target in self.targets:
            if target not in _PROJ_DIMS:
                raise LoraShapeError(f"unknown lora target '{target}'")
            d_in, d_out = _PROJ_DIMS[target](config)
            tensors[target] = {
                "lora_a": jnp.zeros(
                    (self.n_slots, config.n_layers, d_in, rank),
                    jnp.float32),
                "lora_b": jnp.zeros(
                    (self.n_slots, config.n_layers, rank, d_out),
                    jnp.float32),
                "scaling": jnp.zeros((self.n_slots, config.n_layers),
                                     jnp.float32),
            }
        self.tensors = tensors

    def load_slot(self, slot: int, lora: dict):
        """Write one adapter's factors into bank slot ``slot`` (content
        update — shapes are static, nothing recompiles). Validates
        rank/targets/shape agreement first (LoraShapeError on drift)."""
        import jax.numpy as jnp

        if not 1 <= slot < self.n_slots:
            raise ValueError(f"bank slot {slot} out of range "
                             f"[1, {self.n_slots})")
        validate_lora(lora, config=self.config, rank=self.rank,
                      targets=self.targets)
        tensors = {t: dict(parts) for t, parts in self.tensors.items()}
        for target in self.targets:
            adapter = lora.get(target)
            for key in ("lora_a", "lora_b", "scaling"):
                if adapter is None:
                    # an adapter may train fewer targets than the bank
                    # carries — absent targets contribute a zero delta
                    row = jnp.zeros_like(tensors[target][key][slot])
                else:
                    row = jnp.asarray(adapter[key], jnp.float32)
                tensors[target][key] = tensors[target][key].at[slot].set(row)
        self.tensors = tensors


class _Resident:
    __slots__ = ("slot", "refcount", "loaded", "last_used")

    def __init__(self, slot: int):
        self.slot = slot
        self.refcount = 0
        self.loaded = False
        self.last_used = 0


class AdapterRegistry:
    """Named adapters behind a bounded device working set.

    ``sources`` maps adapter name -> one of: a ready adapter tree
    (dict), a datastore/local path string (loaded via
    :func:`load_adapter`), or a zero-arg callable returning the tree.
    Deserialized trees sit in a host-side LRU (``host_cache`` entries)
    so an evicted-then-reused adapter re-lands in the bank without
    another artifact fetch.

    Thread-safe. ``pin``/``unpin`` bracket a request's lifetime (the
    engines attach unpin as a future done-callback, so every completion
    path — result, shed, expiry, stop — releases exactly once);
    ``ensure_loaded`` runs on the engine's scheduler thread (the single
    device owner) and performs the actual bank write.
    """

    def __init__(self, config, sources: Optional[dict] = None,
                 max_live: Optional[int] = None,
                 rank: Optional[int] = None,
                 targets: Optional[Sequence[str]] = None,
                 host_cache: Optional[int] = None,
                 now_fn: Callable[[], float] = time.monotonic):
        from ..config import mlconf

        conf = mlconf.serving.llm.adapters
        self.sources = dict(sources or {})
        if max_live is None:
            max_live = int(conf.max_live_adapters)
        if host_cache is None:
            host_cache = int(conf.host_cache)
        self._now = now_fn
        self._lock = threading.RLock()
        # serializes device-bank writes: a registry SHARED across
        # engines sees ensure_loaded from several scheduler threads,
        # and load_slot's read-modify-write must not lose updates
        self._bank_lock = threading.Lock()
        self._host_cache: OrderedDict[str, dict] = OrderedDict()
        self._host_cache_max = max(1, int(host_cache))
        self._residents: dict[str, _Resident] = {}
        self._tick = 0
        self.stats = {"adapter_loads": 0, "adapter_evictions": 0,
                      "adapter_load_errors": 0,
                      "adapter_rejected_capacity": 0,
                      "adapter_rejected_unknown": 0}
        if rank is None or targets is None:
            inferred = self._infer_shape()
            rank = rank if rank is not None else inferred[0]
            targets = targets if targets is not None else inferred[1]
        self.bank = AdapterBank(config, max_live, rank, targets)
        self._free_slots = list(range(1, self.bank.n_slots))
        # optional DRAFT-model adapter registry (in-engine speculative
        # decoding, docs/serving.md "Speculative decoding"): per-tenant
        # draft adapters live in their own bank sized for the draft
        # config. None = every tenant drafts with the base draft model
        # (verify still runs under the tenant's TARGET adapter, so the
        # stream is the adapter's exact greedy output either way).
        self.draft: Optional["AdapterRegistry"] = None

    def attach_draft(self, draft_config, sources: Optional[dict] = None,
                     rank: Optional[int] = None,
                     targets: Optional[Sequence[str]] = None,
                     max_live: Optional[int] = None) -> "AdapterRegistry":
        """Attach per-tenant DRAFT adapters: a second registry whose bank
        is shaped for the draft model. Tenant names should match the
        target registry's so the engine can resolve a slot by the
        request's adapter name; tenants absent here draft with the base
        draft model (acceptance-rate cost only, never correctness)."""
        self.draft = AdapterRegistry(draft_config, sources=sources,
                                     max_live=max_live, rank=rank,
                                     targets=targets, now_fn=self._now)
        return self.draft

    def _infer_shape(self) -> tuple[int, tuple]:
        """Rank/targets from the first eagerly-available source (lazy
        path/callable sources force one load — the bank's static shapes
        must exist before traffic)."""
        for name in self.sources:
            lora = self._load_params(name)
            return lora_rank(lora), tuple(lora.keys())
        raise ValueError(
            "cannot size the adapter bank: no sources to infer "
            "rank/targets from — pass rank= (and targets=) explicitly")

    # -- source lifecycle (continuous tuning, docs/continuous_tuning.md) -----
    def add_source(self, name: str, source):
        """Publish a new named adapter at runtime (tree | artifact path |
        callable) — the canary hot-load path. Names are immutable
        versions: refusing to overwrite an existing source keeps the
        prefix-cache identity contract honest (publish under a NEW
        versioned name instead)."""
        with self._lock:
            existing = self.sources.get(name)
            if existing is not None and existing is not source:
                raise ValueError(
                    f"adapter '{name}' already has a source — adapter "
                    f"names are immutable versions; publish new weights "
                    f"under a new versioned name")
            self.sources[name] = source

    def retire(self, name: str, keep_source: bool = False):
        """Take an adapter out of service: drop its source (unless
        ``keep_source``) and host-cache entry, and free its bank slot if
        no in-flight request pins it. A still-pinned resident keeps
        serving its in-flight requests and becomes LRU-evictable once
        the pins drain — retire never fails live traffic."""
        from ..obs import retire_adapter_phases

        with self._lock:
            if not keep_source:
                self.sources.pop(name, None)
            self._host_cache.pop(name, None)
            resident = self._residents.get(name)
            retired_resident = resident is not None \
                and resident.refcount == 0
            if retired_resident:
                del self._residents[name]
                slot = resident.slot
                self._free_slots.append(slot)
                self.stats["adapter_evictions"] += 1
        if not keep_source:
            # a fully-retired identity (canary rollback, promotion's
            # displaced version) releases its per-phase histogram
            # series too — version churn must not exhaust the
            # mlt_request_phase_seconds label-set cap (obs/reqledger.py)
            retire_adapter_phases(name)
        if retired_resident:
            fire(FaultPoints.llm_adapter_load, op="evict", adapter=name,
                 slot=slot)

    # -- host-side loading ---------------------------------------------------
    def known(self, name: str) -> bool:
        with self._lock:
            return name in self.sources or name in self._host_cache

    def check_known(self, name: str):
        """Typed 404 for an unknown name (counted) — the submit-path
        gate that must run BEFORE any rate-limit bucket is touched."""
        if not self.known(name):
            with self._lock:
                self.stats["adapter_rejected_unknown"] += 1
            raise UnknownAdapterError(f"unknown adapter '{name}'")

    def _load_params(self, name: str) -> dict:
        with self._lock:
            cached = self._host_cache.get(name)
            if cached is not None:
                self._host_cache.move_to_end(name)
                return cached
            source = self.sources.get(name)
        if source is None:
            raise UnknownAdapterError(f"unknown adapter '{name}'")
        if callable(source):
            lora = source()
        elif isinstance(source, str):
            lora = load_adapter(source)
        else:
            lora = source
        with self._lock:
            self._host_cache[name] = lora
            self._host_cache.move_to_end(name)
            while len(self._host_cache) > self._host_cache_max:
                self._host_cache.popitem(last=False)
        return lora

    # -- device residency ----------------------------------------------------
    def pinned_counts(self) -> dict:
        """{adapter: in-flight refcount} snapshot (per-tenant queue-depth
        telemetry)."""
        with self._lock:
            return {name: r.refcount for name, r in self._residents.items()
                    if r.refcount > 0}

    def live(self) -> int:
        """Adapters currently loaded in the device bank."""
        with self._lock:
            return sum(1 for r in self._residents.values() if r.loaded)

    def resident_names(self) -> list:
        with self._lock:
            return sorted(self._residents)

    def pin(self, name: str):
        """Reserve a bank slot for ``name`` and take one in-flight
        reference. Raises :class:`UnknownAdapterError` (404) or, when
        every slot is pinned by other adapters' in-flight requests,
        :class:`AdapterCapacityError` (429). Never touches the device —
        bookkeeping only, safe from any submit thread."""
        if not name:
            return
        with self._lock:
            self._tick += 1
            resident = self._residents.get(name)
            if resident is not None:
                resident.refcount += 1
                resident.last_used = self._tick
                return
            if not self.known(name):
                self.stats["adapter_rejected_unknown"] += 1
                raise UnknownAdapterError(f"unknown adapter '{name}'")
            if self._free_slots:
                slot = self._free_slots.pop()
            else:
                victim = min(
                    (r for r in self._residents.values()
                     if r.refcount == 0),
                    key=lambda r: r.last_used, default=None)
                if victim is None:
                    self.stats["adapter_rejected_capacity"] += 1
                    raise AdapterCapacityError(
                        f"all {self.bank.max_live} adapter slots are "
                        f"pinned by in-flight requests — cannot load "
                        f"'{name}' (raise max_live_adapters or retry)")
                victim_name = next(n for n, r in self._residents.items()
                                   if r is victim)
                del self._residents[victim_name]
                slot = victim.slot
                self.stats["adapter_evictions"] += 1
                try:
                    fire(FaultPoints.llm_adapter_load, op="evict",
                         adapter=victim_name, slot=slot)
                except BaseException:
                    # an armed error must not leak the freed slot
                    self._free_slots.append(slot)
                    raise
            resident = _Resident(slot)
            resident.refcount = 1
            resident.last_used = self._tick
            self._residents[name] = resident

    def unpin(self, name: str):
        if not name:
            return
        with self._lock:
            resident = self._residents.get(name)
            if resident is not None and resident.refcount > 0:
                resident.refcount -= 1

    def ensure_loaded(self, name: str) -> int:
        """Materialize a pinned adapter in the device bank; returns its
        bank slot. Called on the scheduler thread at admission (the
        single device owner). A failed load marks the slot free again
        and raises — failing ONE request, never the engine."""
        if not name:
            return 0
        with self._lock:
            self._tick += 1
            resident = self._residents.get(name)
            if resident is None:
                raise UnknownAdapterError(
                    f"adapter '{name}' is not pinned (internal ordering "
                    f"bug: pin() must precede ensure_loaded())")
            resident.last_used = self._tick
            if resident.loaded:
                return resident.slot
            slot = resident.slot
        try:
            fire(FaultPoints.llm_adapter_load, op="load", adapter=name,
                 slot=slot)
            lora = self._load_params(name)
            with self._bank_lock:
                # re-validate slot ownership before the write: the
                # fetch above ran without locks, and with a SHARED
                # registry the resident can lose its pins (engine stop
                # fails its futures) and be evicted-and-reassigned
                # meanwhile — a stale write here would overwrite the
                # new tenant's factors while its resident still reads
                # loaded=True. (A live request's pin prevents eviction,
                # so this only trips under teardown/contention.)
                with self._lock:
                    current = self._residents.get(name)
                    if current is not resident or current.slot != slot:
                        raise AdapterCapacityError(
                            f"adapter '{name}' lost its bank slot "
                            f"during load (evicted under contention) — "
                            f"retry")
                self.bank.load_slot(slot, lora)
        except Exception:
            # keep the resident (slot stays reserved, loaded=False):
            # OTHER requests may hold pins on it, and their admissions
            # simply retry the load — a transient fetch failure fails
            # one request, not every concurrently-pinned one. With all
            # pins released the unloaded resident is refcount-0 and LRU
            # eviction reclaims the slot normally.
            with self._lock:
                self.stats["adapter_load_errors"] += 1
            raise
        with self._lock:
            resident.loaded = True
            self.stats["adapter_loads"] += 1
        return resident.slot

    def slot_of(self, name: str) -> int:
        if not name:
            return 0
        with self._lock:
            resident = self._residents.get(name)
            if resident is None or not resident.loaded:
                raise UnknownAdapterError(
                    f"adapter '{name}' is not device-resident")
            return resident.slot


# -- per-tenant admission fairness -------------------------------------------
class TenantRateLimiter:
    """One token bucket per adapter id (the base model, adapter "", is a
    tenant too). ``rate`` tokens/second refill up to ``burst``; an empty
    bucket sheds with :class:`AdapterRateLimitError` BEFORE the shared
    queue, so a flooding tenant consumes its own budget, not the fleet's
    queue capacity."""

    def __init__(self, rate: float, burst: float,
                 now_fn: Callable[[], float] = time.monotonic):
        if rate <= 0:
            raise ValueError(f"rate must be > 0, got {rate}")
        if burst < 1:
            raise ValueError(f"burst must be >= 1, got {burst}")
        self.rate = float(rate)
        self.burst = float(burst)
        self._now = now_fn
        self._lock = threading.Lock()
        self._buckets: dict[str, list] = {}   # tenant -> [tokens, last_t]

    def try_acquire(self, tenant: str) -> bool:
        now = self._now()
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = [self.burst, now]
                self._buckets[tenant] = bucket
            tokens = min(self.burst,
                         bucket[0] + (now - bucket[1]) * self.rate)
            bucket[1] = now
            if tokens < 1.0:
                bucket[0] = tokens
                return False
            bucket[0] = tokens - 1.0
            return True

    def check(self, tenant: str):
        if not self.try_acquire(tenant):
            raise AdapterRateLimitError(
                f"tenant '{tenant or '<base>'}' is over its admission "
                f"rate ({self.rate}/s, burst {self.burst}) — shed to "
                f"protect the shared queue")
