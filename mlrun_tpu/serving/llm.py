"""TPU LLM inference engine: XLA-compiled prefill + decode with a KV cache.

This is the serving-side counterpart of models/llama.py, built for the
<200ms p50 TTFT target (BASELINE.md): weight-resident params, compile-cache
warmup at load, prefill bucketed to power-of-two lengths (bounded compile
count), decode as a jitted single-token step with donated cache. The
reference has no model inference engine at all — its V2ModelServer calls
user predict() (mlrun/serving/v2_serving.py); here predict() runs this
engine on TPU.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..models.llama import LlamaConfig, Params
from ..ops.norms import rms_norm
from ..ops.rotary import apply_rope, rope_table
from ..utils import logger


def init_kv_cache(config: LlamaConfig, batch: int, max_len: int,
                  dtype=None, kv_dtype: str = "native") -> dict:
    """KV cache pytree. ``kv_dtype="int8"`` stores k/v per-vector symmetric
    int8 (scale over head_dim, kept f32 per [layer, batch, pos, kv_head]) —
    half the HBM residency of bf16, so twice the slots x context per chip.
    Dequantization happens at attention time; see _quantize_kv."""
    if kv_dtype not in ("native", "int8"):
        raise ValueError(
            f"unknown kv_dtype '{kv_dtype}' (native | int8)")
    dtype = dtype or config.dtype
    shape = (config.n_layers, batch, max_len, config.n_kv_heads,
             config.head_dim)
    if kv_dtype == "int8":
        scale_shape = shape[:-1]
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(scale_shape, jnp.float32),
            "v_scale": jnp.zeros(scale_shape, jnp.float32),
            "pos": jnp.zeros((batch,), jnp.int32),
        }
    return {
        "k": jnp.zeros(shape, dtype),
        "v": jnp.zeros(shape, dtype),
        "pos": jnp.zeros((batch,), jnp.int32),
    }


def _quantize_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """[..., D] -> (int8 values, f32 scale over the last dim)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = amax / 127.0
    q = jnp.round(x.astype(jnp.float32)
                  / jnp.maximum(scale[..., None], 1e-8)).astype(jnp.int8)
    return q, scale


def _dequantize_kv(q: jax.Array, scale: jax.Array, dtype) -> jax.Array:
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)


def _cached_attention(config, q, k_cache, v_cache, q_positions, cache_len):
    """q: [B, S, H, D]; caches: [B, M, HKV, D]. Causal over positions."""
    n_rep = config.n_heads // config.n_kv_heads
    b, m = k_cache.shape[0], k_cache.shape[1]
    if n_rep > 1:
        k_cache = jnp.repeat(k_cache, n_rep, axis=2)
        v_cache = jnp.repeat(v_cache, n_rep, axis=2)
    scale = config.head_dim ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k_cache,
                        preferred_element_type=jnp.float32) * scale
    k_pos = jnp.arange(m)[None, :]  # [1, M]
    mask = (k_pos[None] <= q_positions[:, :, None])  # [B, S, M]
    logits = jnp.where(mask[:, None], logits, -2.0**30)
    weights = jax.nn.softmax(logits, axis=-1).astype(v_cache.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v_cache)


def _cached_attention_lse(config, q, k_cache, v_cache, q_positions, k_lo):
    """Bounded dense cached attention returning (o, lse): like
    :func:`_cached_attention` but kv rows below ``k_lo`` are masked out
    — on a paged prefix-cache hit those positions live in shared pool
    pages and are attended by the paged prefill kernel; the two partial
    softmax states are then LSE-merged
    (ops/paged_attention.merge_softmax_states). o is [B, S, H, D] f32,
    lse [B, H, S] f32 (the flash kernels' lse layout). This is the
    s == 1 replay form of the hit path — a 1-row flash instance gains
    nothing and is a shape class TPU lowering never otherwise sees."""
    n_rep = config.n_heads // config.n_kv_heads
    m = k_cache.shape[1]
    if n_rep > 1:
        k_cache = jnp.repeat(k_cache, n_rep, axis=2)
        v_cache = jnp.repeat(v_cache, n_rep, axis=2)
    scale = config.head_dim ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k_cache.astype(jnp.float32),
                        preferred_element_type=jnp.float32) * scale
    k_pos = jnp.arange(m)[None, :]  # [1, M]
    mask = (k_pos[None] <= q_positions[:, :, None]) \
        & (k_pos[None] >= k_lo)     # [B, S, M]
    logits = jnp.where(mask[:, None], logits, -2.0**30)
    m_max = jnp.max(logits, axis=-1)                      # [B, H, S]
    weight = jnp.exp(logits - m_max[..., None])
    denom = jnp.maximum(jnp.sum(weight, axis=-1), 1e-30)
    o = jnp.einsum("bhqk,bkhd->bqhd", weight / denom[..., None],
                   v_cache.astype(jnp.float32))
    return o, m_max + jnp.log(denom)


def _lora_delta(h_in, lora_target, layer, adapter_ids):
    """Per-row low-rank delta for one projection: each batch row gathers
    its OWN (A, B, scaling) from the stacked adapter bank
    (serving/adapters.py AdapterBank) by its adapter slot index. Rows on
    slot 0 (base model / padding) hit all-zero factors — a zero delta —
    so every tenant mix runs the same compiled program. Accumulated in
    f32 like the base einsum, so adding the delta pre-cast matches
    ``merge_lora``-merged weights to accumulation-order rounding."""
    a = lora_target["lora_a"][adapter_ids, layer]       # [B, in, r]
    bb = lora_target["lora_b"][adapter_ids, layer]      # [B, r, out]
    scaling = lora_target["scaling"][adapter_ids, layer]  # [B]
    delta = jnp.einsum("bse,ber->bsr", h_in, a,
                       preferred_element_type=jnp.float32)
    delta = jnp.einsum("bsr,brh->bsh", delta, bb,
                       preferred_element_type=jnp.float32)
    return delta * scaling[:, None, None]


def _forward_with_cache(config: LlamaConfig, params: Params,
                        tokens: jax.Array, cache: dict,
                        lora: Optional[Params] = None,
                        adapter_ids: Optional[jax.Array] = None,
                        prefix_kv: Optional[dict] = None,
                        all_logits: bool = False,
                        attn_impl: str = "dense",
                        page_size: int = 0):
    """Run tokens starting at cache['pos']; returns (logits_last, new_cache).
    ``all_logits=True`` returns [B, S, vocab] logits for every input
    position instead of just the last (speculative verification needs the
    target's distribution after each proposed token — serving/speculative.py).

    ``lora``/``adapter_ids`` enable batched multi-tenant LoRA
    (docs/serving.md "Multi-tenant LoRA"): ``lora`` is the stacked
    adapter bank (``{target: {lora_a: [S, L, in, r], ...}}``) and
    ``adapter_ids`` [B] selects each row's bank slot (0 = base model).

    ``attn_impl="flash"`` runs the attention over the cache through the
    offset-aware flash kernel (ops.attention.flash_attention_cached,
    interpret mode off-TPU) instead of the dense masked softmax — the
    engines' prefill hot path (docs/serving.md "Attention kernels").

    ``prefix_kv`` is the paged engine's prefix-hit form (batch=1): a
    dict of the pool's per-layer pages — ``{"k": [L, P+1, ps, Hkv, D],
    "v": ..., "page_ids": [pages_per_slot] int32, "base": int32
    scalar[, "k_scale"/"v_scale": [L, P+1, ps, Hkv] f32 on int8
    pools]}``. Cache rows below ``base`` are zeros — the cached prefix
    KV is attended IN PLACE through the page ids by the multi-row paged
    prefill kernel and LSE-merged with the local attention over the
    suffix rows, so a prefix hit never gathers the cached KV densely
    (``page_size`` must then be the pool's static page size)."""
    b, s = tokens.shape
    max_len = cache["k"].shape[2]
    start = cache["pos"]  # [B]
    positions = start[:, None] + jnp.arange(s)[None, :]  # [B, S]
    x = params["embedding"][tokens].astype(config.dtype)
    # rope per batch row (positions differ per row only after mixed prefill;
    # keep a single table using row 0 — engine keeps pos uniform per batch)
    cos, sin = rope_table(positions[0], config.head_dim, config.rope_theta)

    def body(x_in, layer_idx_and_params):
        layer, lp = layer_idx_and_params
        h = rms_norm(x_in, lp["attn_norm_scale"], config.norm_eps)

        def proj(h_in, w, t=None):
            out = jnp.einsum("bse,eh->bsh", h_in, w,
                             preferred_element_type=jnp.float32)
            if lora is not None and t is not None and t in lora:
                out = out + _lora_delta(h_in, lora[t], layer, adapter_ids)
            return out.astype(x_in.dtype)

        q = proj(h, lp["wq"], "wq").reshape(b, s, config.n_heads,
                                            config.head_dim)
        k = proj(h, lp["wk"], "wk").reshape(b, s, config.n_kv_heads,
                                            config.head_dim)
        v = proj(h, lp["wv"], "wv").reshape(b, s, config.n_kv_heads,
                                            config.head_dim)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        quantized = "k_scale" in cache
        if quantized:
            kq, ks = _quantize_kv(k)
            vq, vs = _quantize_kv(v)
            k_cache = jax.lax.dynamic_update_slice(
                cache["k"][layer], kq, (0, start[0], 0, 0))
            v_cache = jax.lax.dynamic_update_slice(
                cache["v"][layer], vq, (0, start[0], 0, 0))
            k_scale = jax.lax.dynamic_update_slice(
                cache["k_scale"][layer], ks, (0, start[0], 0))
            v_scale = jax.lax.dynamic_update_slice(
                cache["v_scale"][layer], vs, (0, start[0], 0))
            k_attn = _dequantize_kv(k_cache, k_scale, config.dtype)
            v_attn = _dequantize_kv(v_cache, v_scale, config.dtype)
            scales = (k_scale, v_scale)
        else:
            # write k,v into the cache at start..start+s (uniform start)
            k_cache = jax.lax.dynamic_update_slice(
                cache["k"][layer], k.astype(cache["k"].dtype),
                (0, start[0], 0, 0))
            v_cache = jax.lax.dynamic_update_slice(
                cache["v"][layer], v.astype(cache["v"].dtype),
                (0, start[0], 0, 0))
            k_attn, v_attn = k_cache, v_cache
            scales = None
        if prefix_kv is not None:
            # paged prefix-hit suffix prefill: local rows (>= base) via
            # bounded flash (s > 1) or the bounded dense form (the
            # 1-token last-position replay), the cached prefix via the
            # multi-row paged prefill kernel reading pool pages in
            # place — partial softmax states LSE-merged
            # (docs/serving.md "Attention kernels")
            from ..ops.attention import (
                _flash_fwd_v2_cached_bounded,
                _repeat_kv,
            )
            from ..ops.paged_attention import (
                merge_softmax_states,
                paged_prefix_part,
            )

            n_rep = config.n_heads // config.n_kv_heads
            base = prefix_kv["base"]
            if attn_impl == "flash" and s > 1:
                o_loc, lse_loc = _flash_fwd_v2_cached_bounded(
                    q, _repeat_kv(k_attn, n_rep),
                    _repeat_kv(v_attn, n_rep), start[0], base)
            else:
                o_loc, lse_loc = _cached_attention_lse(
                    config, q, k_attn, v_attn, positions, base)
            o_pre, lse_pre = paged_prefix_part(
                q, prefix_kv["k"][layer], prefix_kv["v"][layer],
                prefix_kv["page_ids"], base, page_size=page_size,
                k_scale=(prefix_kv["k_scale"][layer]
                         if "k_scale" in prefix_kv else None),
                v_scale=(prefix_kv["v_scale"][layer]
                         if "v_scale" in prefix_kv else None))
            attn = merge_softmax_states(o_pre, lse_pre, o_loc,
                                        lse_loc).astype(x_in.dtype)
        elif attn_impl == "flash" and s > 1:
            from ..ops.attention import _repeat_kv, flash_attention_cached

            n_rep = config.n_heads // config.n_kv_heads
            # positions are uniform per batch row on the prefill path
            # (mixed-start batches never reach here — see rope note above).
            # 1-token dispatches (last-prompt-token replay, warmup) stay
            # dense: a block_q=1 kernel instance gains nothing and is a
            # shape class TPU lowering never otherwise sees
            attn = flash_attention_cached(
                q, _repeat_kv(k_attn, n_rep), _repeat_kv(v_attn, n_rep),
                start[0])
        else:
            attn = _cached_attention(config, q, k_attn, v_attn, positions,
                                     max_len)
        attn = attn.reshape(b, s, config.qkv_dim)
        x_mid = x_in + proj(attn, lp["wo"], "wo")
        h2 = rms_norm(x_mid, lp["mlp_norm_scale"], config.norm_eps)
        gate = proj(h2, lp["w_gate"], "w_gate")
        up = proj(h2, lp["w_up"], "w_up")
        out = x_mid + proj(jax.nn.silu(gate) * up, lp["w_down"], "w_down")
        return out, (k_cache, v_cache, scales)

    # python loop over layers: compiled once per bucket; exposes per-layer
    # cache updates without scan-carry gymnastics
    new_k, new_v, new_ks, new_vs = [], [], [], []
    for layer in range(config.n_layers):
        lp = jax.tree_util.tree_map(lambda a: a[layer], params["layers"])
        x, (k_cache, v_cache, scales) = body(x, (layer, lp))
        new_k.append(k_cache)
        new_v.append(v_cache)
        if scales is not None:
            new_ks.append(scales[0])
            new_vs.append(scales[1])

    x = rms_norm(x, params["final_norm_scale"], config.norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embedding"].T
    logits = jnp.einsum("bse,ev->bsv", x if all_logits else x[:, -1:],
                        head, preferred_element_type=jnp.float32)
    new_cache = {
        "k": jnp.stack(new_k),
        "v": jnp.stack(new_v),
        "pos": cache["pos"] + s,
    }
    if new_ks:
        new_cache["k_scale"] = jnp.stack(new_ks)
        new_cache["v_scale"] = jnp.stack(new_vs)
    return (logits if all_logits else logits[:, 0]), new_cache


class LLMEngine:
    """Compiled prefill/decode around a Llama param tree."""

    def __init__(self, config: LlamaConfig, params: Params,
                 max_len: int = 2048, batch: int = 1,
                 prefill_buckets: tuple = (128, 512, 1024),
                 temperature: float = 0.0, kv_dtype: str = "native",
                 top_k: int = 0, top_p: float = 1.0, seed: int = 0,
                 attention_impl: str | None = None,
                 adapters=None, max_live_adapters: int | None = None):
        from ..config import mlconf
        from ..ops.attention import resolve_prefill_impl

        self.config = config
        self.params = params
        self.max_len = max_len
        self.batch = batch
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.kv_dtype = kv_dtype
        self._rng = jax.random.PRNGKey(seed)
        self.prefill_buckets = tuple(
            b for b in sorted(prefill_buckets) if b <= max_len) or (max_len,)
        if attention_impl is None:
            attention_impl = str(
                mlconf.serving.llm.get("attention_impl", "auto"))
        self.attention_impl = attention_impl
        # flash prefill; decode stays dense — a 1-token q gains nothing
        # from blockwise streaming and the masked softmax is one fused op
        self.prefill_impl = resolve_prefill_impl(attention_impl)
        # multi-tenant LoRA (docs/serving.md "Multi-tenant LoRA"):
        # named adapters resolved per request/row through the registry
        from .adapters import AdapterRegistry

        if adapters is None:
            self._adapters = None
        elif isinstance(adapters, AdapterRegistry):
            self._adapters = adapters
        else:
            self._adapters = AdapterRegistry(config, sources=adapters,
                                             max_live=max_live_adapters)

        self._prefill = jax.jit(
            functools.partial(_forward_with_cache, config,
                              attn_impl=self.prefill_impl))
        self._decode = jax.jit(
            functools.partial(_forward_with_cache, config),
            donate_argnums=(2,))

        # fused greedy decode: N tokens per dispatch via lax.scan
        def decode_n(params, first_token, cache, n, lora=None,
                     adapter_ids=None):
            def body(carry, _):
                token, cache_in = carry
                logits, cache_out = _forward_with_cache(
                    config, params, token, cache_in, lora=lora,
                    adapter_ids=adapter_ids)
                next_token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (next_token[:, None], cache_out), next_token

            (_, cache), tokens = jax.lax.scan(
                body, (first_token, cache), None, length=n)
            return tokens, cache  # tokens: [n, B]

        self._decode_n = jax.jit(decode_n, static_argnums=(3,),
                                 donate_argnums=(2,))
        self.decode_chunk = 32

    def _lora_kwargs(self, slots=None) -> dict:
        """jit kwargs threading the adapter bank + per-row slot indices
        into the forward; empty (and compile-identical to the
        pre-adapter programs) when no registry is attached. ``slots`` is
        one bank slot per batch row (int or [batch] array); default all
        rows on the base slot 0."""
        if self._adapters is None:
            return {}
        import numpy as np

        if slots is None:
            ids = np.zeros((self.batch,), np.int32)
        else:
            ids = np.broadcast_to(
                np.asarray(slots, np.int32), (self.batch,)).copy()
        return {"lora": self._adapters.bank.tensors,
                "adapter_ids": jnp.asarray(ids)}

    def warmup(self):
        """Compile every prefill bucket + the decode step ahead of traffic."""
        started = time.perf_counter()
        kw = self._lora_kwargs()
        for bucket in self.prefill_buckets:
            cache = init_kv_cache(self.config, self.batch, self.max_len,
                              kv_dtype=self.kv_dtype)
            tokens = jnp.zeros((self.batch, bucket), jnp.int32)
            logits, cache = self._prefill(self.params, tokens, cache, **kw)
            step_tok = jnp.zeros((self.batch, 1), jnp.int32)
            logits, cache = self._decode(self.params, step_tok, cache, **kw)
            step_tok = jnp.zeros((self.batch, 1), jnp.int32)
            tokens_out, cache = self._decode_n(self.params, step_tok, cache,
                                               self.decode_chunk, **kw)
            float(jnp.sum(logits))  # host fetch = real sync on the relay
        logger.info("llm engine warm", buckets=list(self.prefill_buckets),
                    warmup_s=round(time.perf_counter() - started, 2))

    def _bucket_for(self, length: int) -> int:
        for bucket in self.prefill_buckets:
            if length <= bucket:
                return bucket
        return self.max_len

    def generate(self, prompt_tokens, max_new_tokens: int = 64,
                 eos_id: int | None = None,
                 adapter: str = "",
                 request_key=None) -> tuple[list[int], dict]:
        """Greedy/temperature generation for a single prompt (batch=1 row
        replicated); returns (tokens, timing stats). ``adapter`` names a
        registry adapter applied to every row (404s typed when
        unknown); a tenant id with canary-loop state resolves to its
        effective versioned id first (serving/canary.py)."""
        import numpy as np

        prompt = np.asarray(prompt_tokens, dtype=np.int32).reshape(1, -1)
        prompt_len = prompt.shape[1]
        if prompt_len + max_new_tokens > self.max_len:
            from .resilience import PromptTooLongError

            raise PromptTooLongError(
                f"prompt_len {prompt_len} + max_new_tokens "
                f"{max_new_tokens} exceeds max_len {self.max_len}")
        split_tenant = split_side = ""
        if adapter:
            from .canary import get_canary_router, split_key_for

            router = get_canary_router()
            if router is not None:
                resolved, side = router.resolve(
                    adapter, split_key_for(prompt_tokens, request_key))
                if side:
                    split_tenant, split_side = adapter, side
                adapter = resolved
        if adapter and self._adapters is None:
            from .adapters import UnknownAdapterError

            raise UnknownAdapterError(
                f"engine has no adapter registry (adapter='{adapter}')")
        bucket = self._bucket_for(prompt_len)
        padded = np.zeros((self.batch, bucket), np.int32)
        padded[:, :prompt_len] = prompt

        t0 = time.perf_counter()
        kw = {}
        if self._adapters is not None:
            self._adapters.pin(adapter)
        try:
            if self._adapters is not None:
                slot = self._adapters.ensure_loaded(adapter)
                kw = self._lora_kwargs(slot)
            out_tokens, ttft, t1 = self._generate_inner(
                prompt, prompt_len, bucket, padded, max_new_tokens,
                eos_id, t0, kw)
        finally:
            if self._adapters is not None:
                self._adapters.unpin(adapter)
        decode_time = time.perf_counter() - t1
        stats = {
            "ttft_s": ttft,
            "decode_tokens_per_sec": (len(out_tokens) - 1) / decode_time
            if decode_time > 0 and len(out_tokens) > 1 else 0.0,
            "prompt_len": prompt_len,
            "generated": len(out_tokens),
        }
        if split_side:
            # metered on SUCCESS only (a typed rejection above never
            # reaches here) — the split-fraction telemetry counts
            # served requests
            from ..obs import CANARY_REQUESTS

            CANARY_REQUESTS.inc(adapter=split_tenant, side=split_side)
        from .samples import emit_sample, sampling_enabled

        if sampling_enabled():
            emit_sample(adapter=adapter, tokens=list(out_tokens),
                        prompt_len=prompt_len, generated=len(out_tokens),
                        ttft_s=ttft,
                        total_s=time.perf_counter() - t0,
                        logit_margin=float("nan"),
                        engine=type(self).__name__, replica="")
        return out_tokens, stats

    # -- adapter source lifecycle (docs/continuous_tuning.md) ----------------
    def add_adapter_source(self, name: str, source):
        if self._adapters is None:
            raise ValueError(
                "engine has no adapter registry (build it with "
                "adapters=... to hot-load canaries)")
        self._adapters.add_source(name, source)

    def retire_adapter(self, name: str, keep_source: bool = False):
        if self._adapters is not None:
            self._adapters.retire(name, keep_source=keep_source)

    def _generate_inner(self, prompt, prompt_len, bucket, padded,
                        max_new_tokens, eos_id, t0, kw):
        import numpy as np

        cache = init_kv_cache(self.config, self.batch, self.max_len,
                              kv_dtype=self.kv_dtype)
        logits, cache = self._prefill(self.params, jnp.asarray(padded),
                                      cache, **kw)
        # bucket padding advanced pos past prompt; rewind to prompt_len
        cache["pos"] = jnp.full((self.batch,), prompt_len, jnp.int32)
        # logits at the last *real* prompt position were computed only if
        # prompt_len == bucket; otherwise take them from a 1-token replay of
        # the last prompt token (cheap decode step)
        if prompt_len != bucket:
            cache["pos"] = jnp.full((self.batch,), prompt_len - 1, jnp.int32)
            last = jnp.asarray(prompt[:, -1:].repeat(self.batch, 0))
            logits, cache = self._decode(self.params, last, cache, **kw)
        next_token = self._sample(logits)
        jax.block_until_ready(next_token)
        ttft = time.perf_counter() - t0

        out_tokens = [int(np.asarray(next_token)[0])]
        t1 = time.perf_counter()
        remaining = max_new_tokens - 1
        if self.temperature and self.temperature > 0:
            # sampled decode: per-token loop (carry randomness on host)
            for _ in range(remaining):
                if eos_id is not None and out_tokens[-1] == eos_id:
                    break
                step = jnp.full((self.batch, 1), out_tokens[-1], jnp.int32)
                logits, cache = self._decode(self.params, step, cache, **kw)
                next_token = self._sample(logits)
                out_tokens.append(int(np.asarray(next_token)[0]))
        else:
            # greedy: fused multi-token scan per dispatch. Always run the
            # full compiled chunk (ONE program, compiled at warmup) and
            # truncate host-side — a variable tail would recompile per
            # distinct length on the serving path.
            while remaining > 0:
                if eos_id is not None and out_tokens[-1] == eos_id:
                    break
                if prompt_len + len(out_tokens) + self.decode_chunk \
                        > self.max_len:
                    break  # cache capacity: full chunk wouldn't fit
                step = jnp.full((self.batch, 1), out_tokens[-1], jnp.int32)
                tokens, cache = self._decode_n(self.params, step, cache,
                                               self.decode_chunk, **kw)
                chunk = np.asarray(tokens)[:, 0].tolist()[:remaining]
                if eos_id is not None and eos_id in chunk:
                    chunk = chunk[: chunk.index(eos_id) + 1]
                out_tokens.extend(int(t) for t in chunk)
                remaining -= len(chunk)
        return out_tokens, ttft, t1

    def generate_batch(self, prompts: list, max_new_tokens: int = 64,
                       eos_id: int | None = None,
                       adapters: list | None = None) -> tuple[list, dict]:
        """Batched greedy generation for EQUAL-LENGTH prompts (one fused
        decode scan serves the whole batch). Mixed lengths fall back to a
        per-prompt loop — exact per-row positions/pad masking in the cache
        is R2 work.

        ``adapters`` gives one registry adapter name per prompt ("" =
        base): each batch row applies its OWN low-rank delta inside the
        shared dispatch (docs/serving.md "Multi-tenant LoRA"); padding
        rows ride the base slot.

        Engine must be built with batch >= len(prompts).
        """
        import numpy as np

        n = len(prompts)
        if n == 0:
            return [], {"ttft_s": 0.0, "decode_tokens_per_sec": 0.0,
                        "batch": 0}
        if n > self.batch:
            raise ValueError(
                f"{n} prompts exceed engine batch size {self.batch}")
        if adapters is not None and len(adapters) != n:
            raise ValueError(
                f"adapters has {len(adapters)} entries for {n} prompts")
        row_adapters = list(adapters or [""] * n)
        if any(row_adapters) and self._adapters is None:
            from .adapters import UnknownAdapterError

            raise UnknownAdapterError(
                "engine has no adapter registry "
                f"(adapters={sorted(set(filter(None, row_adapters)))})")
        lengths = {len(p) for p in prompts}
        # sampled decoding carries host-side randomness — use the per-prompt
        # path so semantics match generate() exactly
        if len(lengths) > 1 or (self.temperature and self.temperature > 0):
            outs = []
            started = time.perf_counter()
            first_ttft = None
            for prompt, row_adapter in zip(prompts, row_adapters):
                tokens, stats = self.generate(prompt, max_new_tokens,
                                              eos_id, adapter=row_adapter)
                outs.append(tokens)
                first_ttft = first_ttft if first_ttft is not None \
                    else stats["ttft_s"]
            wall = time.perf_counter() - started
            generated = sum(len(o) for o in outs)
            return outs, {
                "ttft_s": first_ttft or 0.0,
                # true aggregate: total tokens over total wall time
                "decode_tokens_per_sec": generated / wall if wall > 0
                else 0.0,
                "batch": n,
            }

        prompt_len = lengths.pop()
        bucket = self._bucket_for(prompt_len)
        padded = np.zeros((self.batch, bucket), np.int32)
        for i, prompt in enumerate(prompts):
            padded[i, :prompt_len] = prompt

        t0 = time.perf_counter()
        kw = {}
        pinned = []
        try:
            if self._adapters is not None:
                # pin every row's adapter for the whole batched dispatch;
                # padding rows (>= n) stay on the base slot 0
                slots = np.zeros((self.batch,), np.int32)
                for i, row_adapter in enumerate(row_adapters):
                    self._adapters.pin(row_adapter)
                    pinned.append(row_adapter)
                    slots[i] = self._adapters.ensure_loaded(row_adapter)
                kw = self._lora_kwargs(slots)
            out, ttft, t1, generated = self._generate_batch_inner(
                n, prompt_len, bucket, padded, max_new_tokens, eos_id,
                t0, kw)
        finally:
            if self._adapters is not None:
                for row_adapter in pinned:
                    self._adapters.unpin(row_adapter)
        decode_time = time.perf_counter() - t1
        stats = {
            "ttft_s": ttft,
            "decode_tokens_per_sec": generated / decode_time
            if decode_time > 0 and generated else 0.0,
            "batch": n,
        }
        return out, stats

    def _generate_batch_inner(self, n, prompt_len, bucket, padded,
                              max_new_tokens, eos_id, t0, kw):
        import numpy as np

        cache = init_kv_cache(self.config, self.batch, self.max_len,
                              kv_dtype=self.kv_dtype)
        logits, cache = self._prefill(self.params, jnp.asarray(padded),
                                      cache, **kw)
        if prompt_len != bucket:
            cache["pos"] = jnp.full((self.batch,), prompt_len - 1, jnp.int32)
            last = jnp.asarray(padded[:, prompt_len - 1:prompt_len])
            logits, cache = self._decode(self.params, last, cache, **kw)
        else:
            cache["pos"] = jnp.full((self.batch,), prompt_len, jnp.int32)
        next_token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        out = [[int(t)] for t in np.asarray(next_token)[:n]]
        ttft = time.perf_counter() - t0

        t1 = time.perf_counter()
        remaining = max_new_tokens - 1
        generated_so_far = 1
        step = next_token[:, None]
        while remaining > 0:
            if eos_id is not None and all(
                    o and o[-1] == eos_id for o in out[:n]):
                break  # every row finished — skip further decode dispatches
            # same capacity guard as generate(): pos starts at prompt_len
            if prompt_len + generated_so_far + self.decode_chunk \
                    > self.max_len:
                break
            tokens, cache = self._decode_n(self.params, step, cache,
                                           self.decode_chunk, **kw)
            chunk = np.asarray(tokens)  # [chunk, B]
            take = min(self.decode_chunk, remaining)
            for i in range(n):
                row = chunk[:take, i].tolist()
                if eos_id is not None and eos_id in row:
                    row = row[: row.index(eos_id) + 1]
                if not out[i] or (eos_id is None
                                  or out[i][-1] != eos_id):
                    out[i].extend(int(t) for t in row)
            step = tokens[-1][:, None]
            remaining -= take
            generated_so_far += self.decode_chunk  # cache rows consumed
        generated = sum(len(o) for o in out) - n
        return out, ttft, t1, generated

    def _sample(self, logits):
        if self.temperature and self.temperature > 0:
            from .sampling import sample_logits

            b = logits.shape[0]
            self._rng, sub = jax.random.split(self._rng)
            return sample_logits(
                logits, sub,
                jnp.full((b,), self.temperature, jnp.float32),
                jnp.full((b,), self.top_k, jnp.int32),
                jnp.full((b,), self.top_p, jnp.float32))
        return jnp.argmax(logits, axis=-1)


class LLMModelServer:
    """Serving-graph step: tokenization on host, generation on TPU.

    class args: model_preset|model_path, tokenizer, max_len, warmup...
    """

    def __new__(cls, *args, **kwargs):
        from .v2_serving import V2ModelServer

        class _Server(V2ModelServer):
            def __init__(self, *a, model_preset: str = "tiny",
                         tokenizer: str | None = None, max_len: int = 1024,
                         max_new_tokens: int = 64, hf_model: str | None = None,
                         temperature: float = 0.0, warmup: bool = True,
                         continuous_batching: bool = False, slots: int = 4,
                         kv_dtype: str = "native", top_k: int = 0,
                         top_p: float = 1.0, paged: bool = False,
                         page_size: int = 128,
                         n_pages: int | None = None,
                         max_queue_size: int = 0, max_wait: float = 0.0,
                         degradation: dict | None = None,
                         prefill_chunk: int | None = None,
                         prefix_cache: bool | None = None,
                         attention_impl: str | None = None,
                         replicas: int = 0,
                         prefill_replicas: int = 0,
                         routing: str | None = None,
                         adapters: dict | None = None,
                         max_live_adapters: int | None = None,
                         adapter_rate: float | None = None,
                         adapter_burst: float | None = None,
                         request_ledger: bool | None = None,
                         speculative: dict | bool | None = None, **kw):
                super().__init__(*a, **kw)
                self.model_preset = model_preset
                self.tokenizer_id = tokenizer
                self.max_len = max_len
                self.max_new_tokens = max_new_tokens
                self.hf_model = hf_model
                self.temperature = temperature
                self._warmup = warmup
                self.continuous_batching = continuous_batching
                self.slots = slots
                self.kv_dtype = kv_dtype
                self.top_k = top_k
                self.top_p = top_p
                self.paged = paged
                self.page_size = page_size
                self.n_pages = n_pages
                # overload knobs forwarded to the batching engines
                # (docs/serving_resilience.md)
                self.max_queue_size = max_queue_size
                self.max_wait = max_wait
                self.degradation = degradation
                # prefill/prefix-cache knobs (docs/serving.md "Prefill &
                # prefix cache"); None = mlconf.serving.llm defaults
                self.prefill_chunk = prefill_chunk
                self.prefix_cache = prefix_cache
                # attention kernel dispatch (docs/serving.md "Attention
                # kernels"): auto | flash | kernel | reference
                self.attention_impl = attention_impl
                # engine fleet (docs/serving.md "Engine fleet"):
                # replicas >= 2 builds an EngineFleet instead of one
                # engine; prefill_replicas > 0 additionally splits
                # prefill and decode into separate pools with KV handoff
                self.replicas = replicas
                self.prefill_replicas = prefill_replicas
                self.routing = routing
                # multi-tenant LoRA (docs/serving.md "Multi-tenant
                # LoRA"): named adapter sources (tree | artifact path |
                # callable), device working-set bound, and the
                # per-tenant admission token bucket
                self.adapters = adapters
                self.max_live_adapters = max_live_adapters
                self.adapter_rate = adapter_rate
                self.adapter_burst = adapter_burst
                # per-request phase ledger (docs/observability.md
                # "Request attribution"); None = mlconf default (on)
                self.request_ledger = request_ledger
                # in-engine speculative decoding (docs/serving.md
                # "Speculative decoding"): True / {"k": ..., "draft":
                # preset} enables a resident draft model; None = the
                # mlconf.serving.llm.speculative defaults decide
                self.speculative = speculative
                self._tokenizer = None
                self.engine = None
                # predict→postprocess handover for the opt-in "timing"
                # field: thread-local, because concurrent requests share
                # this server instance and do_event runs the whole
                # pre/predict/post chain on one thread — an instance
                # attribute would hand one request's timing to another
                import threading as _threading

                self._timing_out = _threading.local()

            def load(self):
                from ..frameworks.jax.auto_trainer import MODEL_PRESETS
                from ..models import init_params

                if self.hf_model:
                    from ..frameworks.huggingface import (
                        load_hf_weights_into_llama,
                    )

                    config, params = load_hf_weights_into_llama(self.hf_model)
                else:
                    config = MODEL_PRESETS[self.model_preset]()
                    params = init_params(config, jax.random.PRNGKey(0))
                if self.tokenizer_id:
                    from transformers import AutoTokenizer

                    self._tokenizer = AutoTokenizer.from_pretrained(
                        self.tokenizer_id)
                # resolve the speculative class arg to the engines'
                # draft-carrying dict: True / {"draft": preset} builds
                # the named draft preset resident alongside the target
                # (seeded differently — a real deployment loads trained
                # draft weights the same way)
                spec_conf = None
                if self.continuous_batching:
                    from ..config import mlconf

                    node = mlconf.serving.llm.get("speculative")
                    spec_conf = dict(node.to_dict()) if node is not None \
                        else {}
                    spec_arg = self.speculative
                    if isinstance(spec_arg, bool):
                        spec_arg = {"enabled": spec_arg}
                    if isinstance(spec_arg, dict):
                        spec_conf.update(spec_arg)
                        spec_conf.setdefault("enabled", True)
                    if (spec_conf.get("enabled")
                            and spec_conf.get("draft")
                            and "draft_config" not in spec_conf):
                        draft_config = MODEL_PRESETS[spec_conf["draft"]]()
                        spec_conf["draft_config"] = draft_config
                        spec_conf["draft_params"] = init_params(
                            draft_config, jax.random.PRNGKey(1))
                    if not (spec_conf.get("enabled")
                            and spec_conf.get("draft_config") is not None):
                        spec_conf = None
                if self.continuous_batching:
                    # slot-based scheduler: concurrent requests interleave
                    # on one decode batch; per-request sampling settings
                    # ride the shared dispatch (serving/sampling.py)
                    def build_engine(role="unified"):
                        if self.paged:
                            # paged KV pool: oversubscribable long-prompt
                            # serving (serving/paged.py)
                            from .paged import PagedContinuousBatchingEngine

                            return PagedContinuousBatchingEngine(
                                config, params, max_len=self.max_len,
                                slots=self.slots, kv_dtype=self.kv_dtype,
                                page_size=self.page_size,
                                n_pages=self.n_pages,
                                max_queue_size=self.max_queue_size,
                                max_wait=self.max_wait,
                                degradation=self.degradation,
                                prefill_chunk=self.prefill_chunk,
                                prefix_cache=self.prefix_cache,
                                attention_impl=self.attention_impl,
                                adapters=self.adapters,
                                max_live_adapters=self.max_live_adapters,
                                adapter_rate=self.adapter_rate,
                                adapter_burst=self.adapter_burst,
                                request_ledger=self.request_ledger,
                                speculative=spec_conf)
                        from .llm_batch import ContinuousBatchingEngine

                        return ContinuousBatchingEngine(
                            config, params, max_len=self.max_len,
                            slots=self.slots, kv_dtype=self.kv_dtype,
                            max_queue_size=self.max_queue_size,
                            max_wait=self.max_wait,
                            degradation=self.degradation,
                            prefill_chunk=self.prefill_chunk,
                            attention_impl=self.attention_impl,
                            adapters=self.adapters,
                            max_live_adapters=self.max_live_adapters,
                            adapter_rate=self.adapter_rate,
                            adapter_burst=self.adapter_burst,
                            request_ledger=self.request_ledger,
                            speculative=spec_conf)

                    if self.replicas >= 2 or self.prefill_replicas:
                        # replica fleet: prefix-affinity routing across
                        # N engines, optional prefill/decode pools with
                        # KV handoff (docs/serving.md "Engine fleet")
                        from .fleet import EngineFleet

                        self.engine = EngineFleet(
                            build_engine,
                            replicas=max(1, self.replicas),
                            prefill_replicas=self.prefill_replicas,
                            routing=self.routing)
                    else:
                        self.engine = build_engine()
                    if self._warmup:
                        self.engine.warmup()
                    self.engine.start()
                else:
                    if self.paged:
                        raise ValueError(
                            "paged=True needs continuous_batching=True "
                            "(the paged pool backs the slot scheduler)")
                    self.engine = LLMEngine(
                        config, params, max_len=self.max_len,
                        temperature=self.temperature,
                        top_k=self.top_k, top_p=self.top_p,
                        kv_dtype=self.kv_dtype,
                        attention_impl=self.attention_impl,
                        adapters=self.adapters,
                        max_live_adapters=self.max_live_adapters)
                    if self._warmup:
                        self.engine.warmup()
                self.model = self.engine

            def predict(self, request):
                inputs = request["inputs"]
                # v2 body tenant id: {"inputs": [...], "adapter": "t1"}
                # threads through submit()/generate() to the batched
                # multi-LoRA decode (docs/serving.md "Multi-tenant
                # LoRA"); unknown names 404 typed, capacity/fairness 429.
                # An optional "request_key" (session/user id) pins the
                # canary hash split's side for this client
                # (docs/continuous_tuning.md) — absent, the prompt
                # tokens decide deterministically.
                adapter = request.get("adapter", "") or ""
                request_key = request.get("request_key") or None
                # opt-in per-request forensics: {"timing": true} in the
                # v2 body returns each input's phase-ledger breakdown
                # (obs/reqledger.py) in the response envelope — the
                # debug field behind "where did this request's time go".
                # Clear the handover slot up front: a predict() that
                # raised after filling it must not leak one request's
                # timing (trace ids included) onto this thread's next
                # request.
                self._timing_out.value = None
                want_timing = bool(request.get("timing"))
                id_lists = []
                for item in inputs:
                    if isinstance(item, str):
                        if self._tokenizer is None:
                            raise ValueError(
                                "string inputs need a tokenizer= class arg")
                        id_lists.append(self._tokenizer(item)["input_ids"])
                    else:
                        id_lists.append(list(item))

                if self.continuous_batching:
                    # submit everything, then collect — requests share the
                    # decode batch instead of running serially. Bounded
                    # wait: a dead scheduler fails the futures rather than
                    # wedging the worker.
                    futures = [self.engine.submit(
                        ids, max_new_tokens=self.max_new_tokens,
                        temperature=self.temperature,
                        top_k=self.top_k, top_p=self.top_p,
                        adapter=adapter, request_key=request_key)
                        for ids in id_lists]
                    results = [f.result(timeout=600) for f in futures]
                    if results:
                        self.set_metric(
                            "ttft_s",
                            min(s["ttft_s"] for _, s in results))
                        generated = sum(s["generated"] for _, s in results)
                        wall = max(s["total_s"] for _, s in results)
                        if wall > 0:
                            self.set_metric("decode_tps", generated / wall)
                    engine_stats = self.engine.stats
                    for key in ("ttft_p50_s", "ttft_p95_s", "itl_p50_s",
                                "itl_p95_s", "prefix_hit_rate",
                                "prefix_cached_tokens", "prefix_evictions",
                                "prefill_chunks"):
                        if key in engine_stats:
                            self.set_metric(key, engine_stats[key])
                    if want_timing:
                        self._timing_out.value = [s.get("timing")
                                                  for _, s in results]
                    out_tokens = [tokens for tokens, _ in results]
                else:
                    out_tokens = []
                    for ids in id_lists:
                        tokens, stats = self.engine.generate(
                            ids, max_new_tokens=self.max_new_tokens,
                            adapter=adapter, request_key=request_key)
                        self.set_metric("ttft_s", stats["ttft_s"])
                        self.set_metric("decode_tps",
                                        stats["decode_tokens_per_sec"])
                        out_tokens.append(tokens)

                outputs = []
                for item, tokens in zip(inputs, out_tokens):
                    if self._tokenizer is not None and isinstance(item, str):
                        outputs.append(self._tokenizer.decode(tokens))
                    else:
                        outputs.append(tokens)
                return outputs

            def postprocess(self, response):
                # the opt-in "timing" debug field rides the v2 envelope
                # next to "outputs" (one entry per input, aligned):
                # phase-attributed wall + trace id, straight from the
                # engine's request ledger
                timings = getattr(self._timing_out, "value", None)
                self._timing_out.value = None
                if timings and any(t is not None for t in timings):
                    response["timing"] = timings
                return response

        return _Server(*args, **kwargs)
