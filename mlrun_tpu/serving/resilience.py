"""Serving-path resilience primitives: admission control, circuit
breaking, deadline propagation, and the LLM degradation ladder.

The serving graph (states.py) and the LLM engines (llm_batch.py/paged.py)
run on TPU replicas that live on preemptible pod-slices and serve heavy
fan-in traffic. Under overload or a failing dependency the right answer
is a *fast* failure — a 429/503/504 in microseconds — never a hung future
or a tight retry loop burning TPU time. This module is the shared toolbox:

- :class:`AdmissionController` — token-bucket rate limit + concurrency
  ceiling, checked before a step executes.
- :class:`CircuitBreaker` — closed → open → half-open state machine with
  consecutive-failure and failure-rate trips, one instance per configured
  step.
- deadline propagation — events carry an absolute ``deadline`` (parsed
  from the ``X-MLT-Deadline`` / ``X-MLT-Timeout`` headers by
  ``GraphServer.run``); every step calls :func:`check_deadline` before
  executing and remote calls clamp their HTTP timeout to the remaining
  budget.
- :class:`DegradationLadder` — maps engine pressure (queue depth,
  KV-page exhaustion) to a level: 0 normal, 1 degraded (speculative
  decoding off, ``max_new_tokens`` clamped), 2 shedding.

Everything here is pure host-side Python (no jax imports): the breaker
and admission decisions must cost nanoseconds, and the module must be
importable below every serving layer. All classes accept an injectable
``clock`` so chaos tests run against a fake clock with zero sleeps.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

from ..obs.flight import record as flight_record
from ..utils import logger

# headers GraphServer.run understands (case-insensitive):
#   X-MLT-Timeout:  remaining budget in seconds (relative)
#   X-MLT-Deadline: absolute unix-epoch seconds (wall clock)
TIMEOUT_HEADER = "x-mlt-timeout"
DEADLINE_HEADER = "x-mlt-deadline"


# -- errors ------------------------------------------------------------------
class ResilienceError(RuntimeError):
    """Base for fast-failure rejections. ``status_code`` maps the error to
    an HTTP response class in ``GraphServer.run`` / the ASGI gateway;
    ``retry_after_s`` (optional) is the server's backoff hint — it rides
    the error envelope and the ``Retry-After`` header so upstream
    ``RemoteStep``/router clients back off on schedule instead of
    retrying blind."""

    status_code = 503

    def __init__(self, message: str = "",
                 retry_after_s: float | None = None):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class AdmissionRejected(ResilienceError):
    """Rate/concurrency admission denied — retry later. 429-class
    rejections always carry a ``Retry-After`` hint: admission pressure is
    transient by definition, so a client backing off on the fleet's own
    schedule (:func:`retry_after_hint`) is strictly better than one
    retrying blind. Subclasses (queue-full, adapter capacity/rate-limit)
    inherit the default through this one constructor."""

    status_code = 429

    def __init__(self, message: str = "",
                 retry_after_s: float | None = None):
        if retry_after_s is None:
            retry_after_s = retry_after_hint()
        super().__init__(message, retry_after_s=retry_after_s)


class QueueFullError(AdmissionRejected):
    """A bounded queue shed the newest event (reject-newest policy)."""

    status_code = 429


class PromptTooLongError(ResilienceError, ValueError):
    """prompt + max_new_tokens exceed the engine's max_len — a client
    error rejected at submit() before any queueing or prefill, instead of
    undefined padding/truncation past the largest bucket. Subclasses
    ValueError so pre-typed callers keep working."""

    status_code = 400


class ModelNotReadyError(ResilienceError):
    """The model behind a serving step failed to load or has not
    finished loading — the request can be retried on another replica
    (503-class), unlike a user-payload error."""

    status_code = 503


class DeadlineExceeded(ResilienceError):
    """The event's deadline expired before/while executing a step."""

    status_code = 504


class CircuitOpenError(ResilienceError):
    """The step's circuit breaker is open — dependency presumed down."""

    status_code = 503


class EngineStoppedError(ResilienceError):
    """The LLM engine stopped/crashed; pending requests fail promptly
    instead of hanging until their own timeout."""

    status_code = 503


class ServerDrainingError(ResilienceError):
    """The replica is draining (preemption) and not admitting events."""

    status_code = 503


class ReplicaUnavailableError(ResilienceError):
    """Every fleet replica eligible for a request is stopped, draining,
    or already failed it — the router exhausted its re-dispatch budget
    (serving/fleet.py)."""

    status_code = 503


class ReplicaPreemptedError(ServerDrainingError):
    """The pod hosting a replica was preempted mid-request. 503-class
    via :class:`ServerDrainingError` so ``fleet.redispatchable()`` holds;
    when the dying replica managed to export the decode state, ``handoff``
    carries the int8 :class:`~.llm_batch.KVHandoff` so the fleet resumes
    the request on a survivor via ``submit_prefilled`` instead of
    re-prefilling from scratch."""

    def __init__(self, message: str = "", handoff=None,
                 retry_after_s: float | None = None):
        super().__init__(message, retry_after_s=retry_after_s)
        self.handoff = handoff


def retry_after_hint(attempt: int = 0) -> float:
    """Backoff hint (seconds) for 503-class rejections, derived from the
    same ``mlconf.serving.fleet`` schedule the fleet router uses for its
    own re-dispatch waits — so a client honoring ``Retry-After`` lands
    just after the fleet would have retried internally. Jitter is zero:
    the hint must be stable across replicas for the same attempt."""
    from ..common.retry import RetryPolicy, compute_backoff
    from ..config import mlconf

    conf = mlconf.serving.fleet
    policy = RetryPolicy(
        max_retries=int(conf.max_dispatch_attempts),
        backoff=float(conf.backoff),
        backoff_factor=2.0,
        backoff_max=1.0,
        jitter=0.0,
    )
    return compute_backoff(attempt, policy, seed="retry-after")


# -- deadline propagation ----------------------------------------------------
def deadline_from_headers(headers: dict | None,
                          clock: Callable[[], float] = time.monotonic
                          ) -> Optional[float]:
    """Parse an absolute deadline (on the ``clock`` timebase) from request
    headers. ``X-MLT-Timeout`` (relative seconds) wins over
    ``X-MLT-Deadline`` (absolute epoch seconds) when both are present."""
    if not headers:
        return None
    lowered = {str(k).lower(): v for k, v in headers.items()}
    timeout = lowered.get(TIMEOUT_HEADER)
    if timeout is not None:
        try:
            return clock() + float(timeout)
        except (TypeError, ValueError):
            # fall through: a valid absolute-deadline header must still
            # be honored when the relative one is garbage
            logger.warning("ignoring malformed timeout header",
                           value=timeout)
    epoch = lowered.get(DEADLINE_HEADER)
    if epoch is not None:
        try:
            return clock() + (float(epoch) - time.time())
        except (TypeError, ValueError):
            logger.warning("ignoring malformed deadline header", value=epoch)
    return None


def deadline_remaining(event,
                       clock: Callable[[], float] = time.monotonic
                       ) -> Optional[float]:
    """Seconds of budget left on the event, or None when no deadline."""
    deadline = getattr(event, "deadline", None)
    if deadline is None:
        return None
    return deadline - clock()


def check_deadline(event, step_name: str = "",
                   clock: Callable[[], float] = time.monotonic):
    """Raise :class:`DeadlineExceeded` when the event's budget is spent —
    called by every step before executing so an expired request stops
    burning TPU time at the first graph hop after expiry."""
    remaining = deadline_remaining(event, clock)
    if remaining is not None and remaining <= 0:
        raise DeadlineExceeded(
            f"deadline exceeded before step '{step_name}' "
            f"({-remaining:.3f}s past budget)")


# -- admission control -------------------------------------------------------
class AdmissionController:
    """Token-bucket rate limit plus a concurrency ceiling.

    ``rate`` is sustained requests/second refilled continuously up to
    ``burst`` tokens; ``max_concurrent`` caps in-flight executions. Either
    may be omitted. ``try_acquire`` is non-blocking by design — the caller
    rejects with :class:`AdmissionRejected` rather than queueing, so an
    overloaded step answers in microseconds.
    """

    SPEC_KEYS = {"rate", "burst", "max_concurrent"}

    def __init__(self, rate: float | None = None, burst: float | None = None,
                 max_concurrent: int | None = None,
                 clock: Callable[[], float] | None = None):
        if rate is not None and rate <= 0:
            raise ValueError(f"admission rate must be > 0, got {rate}")
        if max_concurrent is not None and max_concurrent <= 0:
            raise ValueError(
                f"max_concurrent must be > 0, got {max_concurrent}")
        self.rate = float(rate) if rate is not None else None
        # the bucket must hold at least one whole token, or a sub-1.0
        # rate/burst (e.g. rate=0.5 rps) would reject 100% of traffic
        self.burst = max(1.0, float(burst if burst is not None
                                    else (rate or 1)))
        self.max_concurrent = (
            int(max_concurrent) if max_concurrent is not None else None)
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._tokens = self.burst
        self._last = self._clock()
        self._inflight = 0
        self.rejected = 0

    @property
    def inflight(self) -> int:
        return self._inflight

    def try_acquire(self) -> bool:
        with self._lock:
            if self.max_concurrent is not None \
                    and self._inflight >= self.max_concurrent:
                self.rejected += 1
                return False
            if self.rate is not None:
                now = self._clock()
                self._tokens = min(
                    self.burst, self._tokens + (now - self._last) * self.rate)
                self._last = now
                if self._tokens < 1.0:
                    self.rejected += 1
                    return False
                self._tokens -= 1.0
            self._inflight += 1
            return True

    def release(self):
        with self._lock:
            self._inflight = max(0, self._inflight - 1)


# -- circuit breaker ---------------------------------------------------------
class CircuitBreaker:
    """Closed → open → half-open state machine, one instance per step.

    Trips open on ``failure_threshold`` consecutive failures OR when the
    failure rate over the last ``window`` outcomes reaches
    ``failure_rate_threshold`` (only once the window is full, so a single
    early failure cannot trip a 100%-rate breaker). After
    ``recovery_timeout`` seconds open, the next ``allow()`` transitions to
    half-open and admits up to ``half_open_max_calls`` concurrent probes;
    ``success_threshold`` probe successes close the breaker, any probe
    failure re-opens it.
    """

    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"

    SPEC_KEYS = {"failure_threshold", "failure_rate_threshold", "window",
                 "recovery_timeout", "half_open_max_calls",
                 "success_threshold"}

    def __init__(self, name: str = "", failure_threshold: int = 5,
                 failure_rate_threshold: float | None = None,
                 window: int = 20, recovery_timeout: float = 30.0,
                 half_open_max_calls: int = 1, success_threshold: int = 1,
                 clock: Callable[[], float] | None = None):
        if failure_threshold <= 0:
            raise ValueError("failure_threshold must be > 0")
        if failure_rate_threshold is not None \
                and not 0 < failure_rate_threshold <= 1:
            raise ValueError("failure_rate_threshold must be in (0, 1]")
        if recovery_timeout < 0:
            raise ValueError("recovery_timeout must be >= 0")
        self.name = name
        self.failure_threshold = int(failure_threshold)
        self.failure_rate_threshold = failure_rate_threshold
        self.window = int(window)
        self.recovery_timeout = float(recovery_timeout)
        self.half_open_max_calls = int(half_open_max_calls)
        self.success_threshold = int(success_threshold)
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive_failures = 0
        self._outcomes: deque = deque(maxlen=self.window)
        self._opened_at = 0.0
        self._probes = 0
        self._probe_successes = 0
        # observability counters (surfaced in context metrics / logs)
        self.rejected = 0
        self.opened_total = 0

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _trip_open(self):
        self._state = self.OPEN
        self._opened_at = self._clock()
        self.opened_total += 1
        # breaker trips are flight-recorder events: a post-mortem needs
        # the trip sequence leading into an outage, not just the count
        flight_record("breaker.open", breaker=self.name,
                      consecutive_failures=self._consecutive_failures,
                      opened_total=self.opened_total)
        logger.warning("circuit breaker opened", breaker=self.name,
                       consecutive_failures=self._consecutive_failures,
                       opened_total=self.opened_total)

    def allow(self):
        """Admit one call or raise :class:`CircuitOpenError`."""
        with self._lock:
            if self._state == self.OPEN:
                if self._clock() - self._opened_at >= self.recovery_timeout:
                    self._state = self.HALF_OPEN
                    self._probes = 0
                    self._probe_successes = 0
                    logger.info("circuit breaker half-open",
                                breaker=self.name)
                else:
                    self.rejected += 1
                    retry_in = self.recovery_timeout - (
                        self._clock() - self._opened_at)
                    raise CircuitOpenError(
                        f"circuit '{self.name}' is open "
                        f"(retry in {max(0.0, retry_in):.2f}s)")
            if self._state == self.HALF_OPEN:
                if self._probes >= self.half_open_max_calls:
                    self.rejected += 1
                    raise CircuitOpenError(
                        f"circuit '{self.name}' is half-open and probe "
                        f"slots are taken")
                self._probes += 1

    def record_success(self):
        with self._lock:
            if self._state == self.HALF_OPEN:
                self._probes = max(0, self._probes - 1)
                self._probe_successes += 1
                if self._probe_successes >= self.success_threshold:
                    self._state = self.CLOSED
                    self._consecutive_failures = 0
                    self._outcomes.clear()
                    flight_record("breaker.closed", breaker=self.name)
                    logger.info("circuit breaker closed (recovered)",
                                breaker=self.name)
            else:
                self._consecutive_failures = 0
                self._outcomes.append(1)

    def record_failure(self):
        with self._lock:
            if self._state == self.HALF_OPEN:
                self._probes = max(0, self._probes - 1)
                self._trip_open()
                return
            if self._state != self.CLOSED:
                return  # in-flight stragglers after the trip
            self._consecutive_failures += 1
            self._outcomes.append(0)
            rate_tripped = (
                self.failure_rate_threshold is not None
                and len(self._outcomes) == self.window
                and (self._outcomes.count(0) / self.window
                     >= self.failure_rate_threshold))
            if self._consecutive_failures >= self.failure_threshold \
                    or rate_tripped:
                self._trip_open()


# -- step-level wrapper ------------------------------------------------------
class StepResilience:
    """Admission controller + circuit breaker bound to one graph step,
    built from the step's validated ``resilience`` spec dict::

        step.with_resilience(
            circuit_breaker={"failure_threshold": 3,
                             "recovery_timeout": 5.0},
            admission={"max_concurrent": 8, "rate": 100, "burst": 20},
        )
    """

    SPEC_KEYS = {"circuit_breaker", "admission"}

    def __init__(self, name: str = "",
                 breaker: CircuitBreaker | None = None,
                 admission: AdmissionController | None = None):
        self.name = name
        self.breaker = breaker
        self.admission = admission

    @classmethod
    def from_spec(cls, spec: dict | None, name: str = "",
                  clock: Callable[[], float] | None = None
                  ) -> Optional["StepResilience"]:
        if not spec:
            return None
        validate_resilience_spec(spec, name)
        breaker = None
        if spec.get("circuit_breaker"):
            breaker = CircuitBreaker(name=name, clock=clock,
                                     **spec["circuit_breaker"])
        admission = None
        if spec.get("admission"):
            admission = AdmissionController(clock=clock, **spec["admission"])
        return cls(name=name, breaker=breaker, admission=admission)

    def call(self, fn: Callable, context=None):
        """Run ``fn`` under admission + breaker; surfaces every shed/trip
        decision through the context metrics."""
        if self.admission is not None and not self.admission.try_acquire():
            _incr(context, f"step.{self.name}.admission_rejected")
            raise AdmissionRejected(
                f"step '{self.name}' rejected by admission control "
                f"(rate/concurrency limit)")
        try:
            try:
                self.breaker.allow() if self.breaker is not None else None
            except CircuitOpenError:
                _incr(context, f"step.{self.name}.breaker_rejected")
                raise
            try:
                result = fn()
            except Exception:
                if self.breaker is not None:
                    self.breaker.record_failure()
                    _incr(context, f"step.{self.name}.breaker_failures")
                raise
            if self.breaker is not None:
                self.breaker.record_success()
            return result
        finally:
            if self.admission is not None:
                self.admission.release()


def validate_resilience_spec(spec: dict, name: str = ""):
    """Schema check for a step's ``resilience`` dict — unknown keys fail
    at graph-init time, not at 3am when the knob silently never applied."""
    if not isinstance(spec, dict):
        raise ValueError(
            f"step '{name}': resilience spec must be a dict, "
            f"got {type(spec).__name__}")
    unknown = set(spec) - StepResilience.SPEC_KEYS
    if unknown:
        raise ValueError(
            f"step '{name}': unknown resilience keys {sorted(unknown)} "
            f"(allowed: {sorted(StepResilience.SPEC_KEYS)})")
    breaker = spec.get("circuit_breaker") or {}
    unknown = set(breaker) - CircuitBreaker.SPEC_KEYS
    if unknown:
        raise ValueError(
            f"step '{name}': unknown circuit_breaker keys "
            f"{sorted(unknown)} (allowed: "
            f"{sorted(CircuitBreaker.SPEC_KEYS)})")
    admission = spec.get("admission") or {}
    unknown = set(admission) - AdmissionController.SPEC_KEYS
    if unknown:
        raise ValueError(
            f"step '{name}': unknown admission keys {sorted(unknown)} "
            f"(allowed: {sorted(AdmissionController.SPEC_KEYS)})")


def _incr(context, name: str, value: int = 1):
    incr = getattr(context, "incr", None)
    if callable(incr):
        incr(name, value)


# -- degradation ladder ------------------------------------------------------
class DegradationLadder:
    """Maps engine pressure to a degradation level for the LLM path.

    Levels (each includes the previous):
      0 — normal operation.
      1 — degraded: disable speculative decoding, clamp ``max_new_tokens``
          to ``max_new_tokens`` (requests still complete, just cheaper).
      2 — shedding: the engine's bounded queue rejects new work.

    Pressure signals: decode queue depth (vs ``queue_depth``) and, on the
    paged engine, the free-KV-page fraction (vs ``min_free_page_frac``).
    """

    SPEC_KEYS = {"queue_depth", "max_new_tokens", "min_free_page_frac"}

    def __init__(self, queue_depth: int | None = None,
                 max_new_tokens: int | None = None,
                 min_free_page_frac: float | None = None):
        if queue_depth is not None and queue_depth <= 0:
            raise ValueError("degradation queue_depth must be > 0")
        if max_new_tokens is not None and max_new_tokens <= 0:
            raise ValueError("degradation max_new_tokens must be > 0")
        if min_free_page_frac is not None \
                and not 0 <= min_free_page_frac <= 1:
            raise ValueError("min_free_page_frac must be in [0, 1]")
        self.queue_depth = queue_depth
        self.max_new_tokens = max_new_tokens
        self.min_free_page_frac = min_free_page_frac

    @classmethod
    def from_spec(cls, spec: dict | None) -> Optional["DegradationLadder"]:
        if not spec:
            return None
        unknown = set(spec) - cls.SPEC_KEYS
        if unknown:
            raise ValueError(
                f"unknown degradation keys {sorted(unknown)} "
                f"(allowed: {sorted(cls.SPEC_KEYS)})")
        return cls(**spec)

    def level(self, queue_depth: int, max_queue_size: int = 0,
              free_page_frac: float | None = None) -> int:
        if max_queue_size and queue_depth >= max_queue_size:
            return 2
        if self.queue_depth is not None and queue_depth >= self.queue_depth:
            return 1
        if self.min_free_page_frac is not None \
                and free_page_frac is not None \
                and free_page_frac < self.min_free_page_frac:
            return 1
        return 0

    def clamp_max_new(self, max_new_tokens: int, level: int) -> int:
        if level >= 1 and self.max_new_tokens is not None:
            return min(max_new_tokens, self.max_new_tokens)
        return max_new_tokens
