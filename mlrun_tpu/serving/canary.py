"""Canary traffic splitting + adapter version aliasing
(docs/continuous_tuning.md).

Adapter names are immutable versions (docs/serving.md "Multi-tenant
LoRA"): re-publishing different weights under the same name would serve
stale prefix KV. The continuous-tuning loop therefore never mutates a
tenant's adapter in place — it publishes each retrain under a fresh
VERSIONED id (``<tenant>@v<n>``) and this router maps client-facing
tenant ids onto effective adapter ids at the submit boundary:

- **alias**: ``tenant -> versioned id`` — what "stable" currently means
  for the tenant. Promotion re-points the alias; clients keep submitting
  the bare tenant id and never see versions.
- **split**: while a canary is under evaluation, a deterministic hash of
  ``(tenant, request key)`` sends ``fraction`` of the tenant's traffic
  to the canary id instead. The same request key ALWAYS lands on the
  same side — across processes, restarts and replicas (sha256, never
  ``hash()``).

Because the effective adapter id is resolved BEFORE the prefix cache,
the fleet routing key, and the engine's adapter bank see the request,
canary traffic is a distinct identity end to end: its KV pages live
under the canary's radix root, its routing key hashes differently, and
its bank slot holds the canary factors — canary KV can never serve
stable traffic (and vice versa) by construction.

Resolution is idempotent: a versioned id (anything containing ``@``)
carries no router state, so a request resolved at the model-server layer
passes through the fleet and engine layers unchanged. ``@`` is reserved:
client tenant ids must not contain it.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Optional

from ..obs import CANARY_REQUESTS

VERSION_SEP = "@"


def split_key_for(prompt_tokens, explicit=None) -> str:
    """The request key the hash split buckets on: an explicit client key
    (session/user id — keeps one conversation on one side) or, absent
    that, a stable digest of the prompt tokens (same prompt, same
    side)."""
    if explicit:
        return str(explicit)
    return hashlib.sha256(
        ",".join(str(int(t)) for t in prompt_tokens).encode()
    ).hexdigest()[:16]


class CanarySplit:
    """One tenant's active canary: the versioned canary id and the
    traffic fraction it receives."""

    __slots__ = ("tenant", "canary", "fraction")

    def __init__(self, tenant: str, canary: str, fraction: float):
        self.tenant = tenant
        self.canary = canary
        self.fraction = float(fraction)


class CanaryRouter:
    """Thread-safe alias + split table consulted by every submit path
    (fleet, engines, the graph router). Dark cost is one dict lookup per
    request with an adapter; requests without router state pass through
    untouched and unmetered."""

    def __init__(self):
        self._lock = threading.Lock()
        self._aliases: dict[str, str] = {}
        self._splits: dict[str, CanarySplit] = {}

    # -- state ---------------------------------------------------------------
    @staticmethod
    def _check_tenant(tenant: str):
        if not tenant or VERSION_SEP in tenant:
            raise ValueError(
                f"'{tenant}' is not a client tenant id ('{VERSION_SEP}' "
                f"is reserved for loop-managed versioned adapters)")

    def stable_id(self, tenant: str) -> str:
        """The versioned id the tenant's stable traffic currently
        resolves to (the tenant id itself before any promotion)."""
        with self._lock:
            return self._aliases.get(tenant, tenant)

    def set_alias(self, tenant: str, versioned: str):
        self._check_tenant(tenant)
        with self._lock:
            self._aliases[tenant] = versioned

    def split(self, tenant: str) -> Optional[CanarySplit]:
        with self._lock:
            return self._splits.get(tenant)

    def active_splits(self) -> dict:
        with self._lock:
            return dict(self._splits)

    def set_split(self, tenant: str, canary: str, fraction: float):
        self._check_tenant(tenant)
        if canary == tenant:
            raise ValueError("canary id must differ from the tenant id")
        if not 0.0 < float(fraction) < 1.0:
            raise ValueError(
                f"canary fraction must be in (0, 1), got {fraction}")
        with self._lock:
            self._splits[tenant] = CanarySplit(tenant, canary, fraction)

    def clear_split(self, tenant: str):
        with self._lock:
            self._splits.pop(tenant, None)

    def promote(self, tenant: str) -> str:
        """Re-point the tenant's stable id at the active canary and end
        the split; returns the promoted versioned id."""
        with self._lock:
            split = self._splits.pop(tenant, None)
            if split is None:
                raise ValueError(f"tenant '{tenant}' has no active canary")
            self._aliases[tenant] = split.canary
            return split.canary

    def export_state(self) -> dict:
        """Serializable view of the whole routing table (aliases +
        splits) — what a restarted continuous-tuning controller rebuilds
        from its intent journal. ``bucket()`` is a pure sha256 of
        ``(tenant, request key)``, so once the canary id and fraction
        are restored the split is hash-identical by construction: every
        request key resolves to the same side it did before the crash."""
        with self._lock:
            return {
                "aliases": dict(self._aliases),
                "splits": {t: {"canary": s.canary, "fraction": s.fraction}
                           for t, s in self._splits.items()},
            }

    def restore_state(self, state: dict):
        """Install an :meth:`export_state` view, validating every entry
        through the normal setters."""
        for tenant, versioned in (state.get("aliases") or {}).items():
            self.set_alias(tenant, versioned)
        for tenant, split in (state.get("splits") or {}).items():
            self.set_split(tenant, split["canary"], split["fraction"])

    @staticmethod
    def is_managed(name: str) -> bool:
        """True for loop-managed versioned/canary ids (never client
        tenant ids) — e.g. the monitor's drift state machine skips
        them."""
        return VERSION_SEP in (name or "")

    # -- resolution ----------------------------------------------------------
    @staticmethod
    def bucket(tenant: str, split_key: str) -> float:
        """Deterministic [0, 1) bucket for (tenant, request key); a key's
        bucket is fixed, so raising the fraction only ADDS keys to the
        canary side, never reshuffles existing assignments."""
        digest = hashlib.sha256(
            f"{tenant}|{split_key}".encode()).digest()
        return int.from_bytes(digest[:8], "big") / float(1 << 64)

    def resolve(self, adapter: str, split_key: str,
                count: bool = False) -> tuple[str, str]:
        """Map a client adapter id to its effective versioned id:
        ``(effective, side)`` with side ``"canary"``/``"stable"`` when
        router state applied, ``""`` when the name passed through
        untouched. ``count=True`` meters the decision on
        ``mlt_canary_requests_total`` — submit boundaries pass it,
        routing-key computations don't."""
        if not adapter:
            return adapter, ""
        with self._lock:
            split = self._splits.get(adapter)
            stable = self._aliases.get(adapter, adapter)
        if split is None and stable == adapter:
            return adapter, ""
        side, effective = "stable", stable
        if split is not None and \
                self.bucket(adapter, split_key) < split.fraction:
            side, effective = "canary", split.canary
        if count and split is not None:
            # metered only while a split is LIVE — post-promotion alias
            # resolution is plain steady-state traffic, and counting it
            # "stable" forever would dilute every later experiment's
            # canary/(canary+stable) fraction
            CANARY_REQUESTS.inc(adapter=adapter, side=side)
        return effective, side


# process-wide router consulted by the submit paths; None = the loop is
# not running and every request passes through at one attribute read
_router: Optional[CanaryRouter] = None


def get_canary_router() -> Optional[CanaryRouter]:
    return _router


def set_canary_router(router: Optional[CanaryRouter]):
    global _router
    _router = router


def resolve_adapter(adapter: str, prompt_tokens, request_key=None,
                    count: bool = False) -> str:
    """One-stop resolution for submit paths: consult the process router
    (if any) with the request's split key. Returns the effective adapter
    id — identical to the input when the loop is dark."""
    if not adapter:
        return adapter
    router = _router
    if router is None:
        return adapter
    effective, _ = router.resolve(
        adapter, split_key_for(prompt_tokens, request_key), count=count)
    return effective
