"""HTTP gateway hosting a serving graph — the Nuclio-replica replacement.

Reference analog: Nuclio wraps GraphServer via v2_serving_init/handler
(mlrun/serving/server.py:315,387). Here an aiohttp app does the same: the
graph is built from SERVING_SPEC_ENV (or a passed spec/function), events run
through GraphServer.run; TPU model steps execute XLA-compiled callables in a
dedicated executor thread so the event loop stays responsive.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import json
import os
import time
from typing import Optional

from aiohttp import web

from ..config import mlconf
from ..obs import (
    CONTENT_TYPE,
    OPENMETRICS_CONTENT_TYPE,
    PROBE_REQUESTS,
    REGISTRY,
    configure_from_mlconf,
    wants_openmetrics,
)
from ..utils import logger
from .server import GraphContext, GraphServer, MockEvent, Response


def build_serving_app(server: GraphServer) -> web.Application:
    app = web.Application(client_max_size=64 * 1024 * 1024)
    # single executor thread: TPU compute serializes anyway; keeps
    # compiled-fn calls off the event loop
    executor = concurrent.futures.ThreadPoolExecutor(max_workers=1)
    app["server"] = server
    app["latencies"] = []

    async def handle(request: web.Request):
        started = time.perf_counter()
        body = None
        if request.can_read_body:
            raw = await request.read()
            content_type = request.headers.get("Content-Type", "")
            if "json" in content_type or (raw[:1] in (b"{", b"[")):
                try:
                    body = json.loads(raw)
                except ValueError:
                    body = raw
            else:
                body = raw
        event = MockEvent(body=body, path=request.path,
                          method=request.method,
                          headers=dict(request.headers))
        loop = asyncio.get_event_loop()
        result = await loop.run_in_executor(
            executor, lambda: server.run(event, get_body=True))
        elapsed = time.perf_counter() - started
        app["latencies"].append(elapsed)
        if len(app["latencies"]) > 10000:
            del app["latencies"][:5000]
        headers = None
        if isinstance(result, Response):
            payload = result.body
            status = result.status_code
            # thread server-set response headers through (e.g. the 503
            # drain rejection's Retry-After backoff hint)
            headers = {str(k): str(v)
                       for k, v in (result.headers or {}).items()} or None
        else:
            payload = result
            status = 200
        if isinstance(payload, (bytes, str)):
            return web.Response(
                body=payload if isinstance(payload, bytes)
                else payload.encode(), status=status, headers=headers)
        return web.json_response(payload, status=status, headers=headers,
                                 dumps=lambda d: json.dumps(d, default=str))

    # probe/scrape endpoints count themselves on one dedicated low-cost
    # counter and NEVER allocate spans (they answer before GraphServer.run,
    # the only span producer) — load-balancer probes and Prometheus
    # scrapers must not pollute request telemetry
    def _probe(path: str):
        PROBE_REQUESTS.inc(path=path)

    async def stats(request):
        _probe("/__stats__")
        lat = sorted(app["latencies"])
        n = len(lat)
        return web.json_response({
            "requests": n,
            "p50_ms": round(lat[n // 2] * 1000, 2) if n else None,
            "p99_ms": round(lat[int(n * 0.99)] * 1000, 2) if n else None,
        })

    # -- resilience endpoints (docs/serving_resilience.md) -------------------
    async def healthz(request):
        # liveness: 200 while the process serves, even mid-drain
        _probe("/healthz")
        return web.json_response(server.healthz())

    async def readyz(request):
        # readiness: flips 503 the moment drain starts so the load
        # balancer stops routing before in-flight events finish — and
        # stays 503 while the replica warms (ready means warm; the
        # fleet's ring join gates on this). The 503 carries a
        # Retry-After hint so the prober backs off on schedule.
        _probe("/readyz")
        payload = server.readyz()
        if payload["ready"]:
            return web.json_response(payload)
        from .resilience import retry_after_hint

        return web.json_response(
            payload, status=503,
            headers={"Retry-After": f"{retry_after_hint():.3f}"})

    async def drain(request):
        # operational drain hook (the preemption path uses
        # GraphServer.drain_on_preemption instead)
        loop = asyncio.get_event_loop()
        drained = await loop.run_in_executor(None, server.drain)
        return web.json_response({"drained": drained,
                                  "inflight": server.inflight})

    async def metrics(request):
        # Prometheus text exposition of the process-wide registry
        # (docs/observability.md) — engine, resilience, step-latency and
        # request series for this replica. An Accept header naming
        # application/openmetrics-text negotiates the OpenMetrics
        # variant, whose histogram buckets carry trace-id exemplars
        _probe("/metrics")
        if not bool(mlconf.observability.metrics_enabled):
            return web.Response(status=404, text="metrics exposition is "
                                "disabled (mlconf.observability)")
        om = wants_openmetrics(request.headers.get("Accept"))
        return web.Response(
            body=REGISTRY.render(openmetrics=om).encode(),
            headers={"Content-Type": (OPENMETRICS_CONTENT_TYPE if om
                                      else CONTENT_TYPE)})

    # -- debug endpoints (docs/observability.md "Flight recorder & debug
    # endpoints") — live reads of the black-box ring and on-demand
    # profiling of whatever hot loop runs in this process; the handler
    # cores (parsing, validation, path-safety) are shared with the
    # service API in obs/debug.py
    async def debug_flight(request):
        from ..obs.debug import flight_snapshot

        _probe("/debug/flight")
        try:
            payload = flight_snapshot(request.query.get("kind", ""),
                                      request.query.get("limit", 0))
        except ValueError as exc:
            return web.json_response({"error": str(exc)}, status=400)
        return web.json_response(
            payload, dumps=lambda d: json.dumps(d, default=str))

    async def debug_trace(request):
        # alert → culprit request → phase breakdown in one hop: an
        # exemplar's trace id resolves here into one waterfall with the
        # blocking critical path (docs/observability.md "Request
        # attribution, exemplars & trace assembly"). Fan-out to peer
        # replicas happens in the shared core with per-replica timeouts
        # (a dead replica degrades the waterfall, never 504s it);
        # ?local=1 answers from this process's ring only (the leaf read
        # peers serve each other).
        from ..obs.debug import trace_snapshot

        _probe("/debug/trace")
        local_only = request.query.get("local", "") in ("1", "true")
        loop = asyncio.get_event_loop()
        try:
            payload = await loop.run_in_executor(None, lambda: (
                trace_snapshot(request.match_info["trace_id"],
                               local_only=local_only)))
        except ValueError as exc:
            return web.json_response({"error": str(exc)}, status=400)
        return web.json_response(
            payload, dumps=lambda d: json.dumps(d, default=str))

    async def debug_profile_get(request):
        from ..utils.profiler import profile_status

        _probe("/debug/profile")
        return web.json_response(profile_status())

    async def debug_profile_post(request):
        # arm utils/profiler for the next N steps/seconds on the live
        # trainer or engine ticking in this process; the XLA trace is
        # registered as an artifact when the bound is hit — a production
        # hot loop gets profiled without a restart
        from ..obs.debug import profile_request

        body = {}
        if request.can_read_body:
            try:
                body = await request.json()
            except ValueError:
                return web.json_response({"error": "body must be JSON"},
                                         status=400)
        try:
            out = profile_request(body)
        except ValueError as exc:
            return web.json_response({"error": str(exc)}, status=400)
        return web.json_response(out)

    app.router.add_get("/healthz", healthz)
    app.router.add_get("/readyz", readyz)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/debug/flight", debug_flight)
    app.router.add_get("/debug/trace/{trace_id}", debug_trace)
    app.router.add_get("/debug/profile", debug_profile_get)
    app.router.add_post("/debug/profile", debug_profile_post)
    app.router.add_post("/__drain__", drain)
    app.router.add_get("/__stats__", stats)
    app.router.add_route("*", "/{tail:.*}", handle)
    return app


def server_from_env(namespace: dict | None = None) -> GraphServer:
    spec_env = os.environ.get("SERVING_SPEC_ENV", "")
    if not spec_env:
        raise ValueError("SERVING_SPEC_ENV is not set")
    spec = json.loads(spec_env)
    server = GraphServer.from_dict(spec)
    context = GraphContext(server=server)
    # embedded user code (the reference bakes fn.with_code / code_to_function
    # source into the image; here MLT_EXEC_CODE carries it into the gateway
    # process and graph classes resolve from its namespace)
    full_namespace = dict(namespace or {})
    code = os.environ.get(mlconf.exec_code_env, "")
    if code:
        import base64

        module_ns: dict = {}
        exec(compile(base64.b64decode(code).decode(),  # noqa: S102
                     "<serving-code>", "exec"), module_ns)
        full_namespace = {**module_ns, **full_namespace}
    server.init_states(context, full_namespace)
    return server


def serve(function=None, spec: dict | None = None, host: str = "0.0.0.0",
          port: int = 8080, namespace: dict | None = None):
    """Start the gateway for a ServingRuntime object, a serialized spec, or
    the SERVING_SPEC_ENV contract."""
    configure_from_mlconf()  # span JSONL path / ring size for this replica
    if function is not None:
        server = function.to_mock_server(namespace=namespace)
        server.context.is_mock = False
    elif spec is not None:
        server = GraphServer.from_dict(spec)
        server.init_states(GraphContext(server=server), namespace or {})
    else:
        server = server_from_env(namespace)
    # preemptible replica: SIGTERM latches the guard, the watcher drains
    # in-flight events and flips /readyz before the grace period ends
    from ..training.preemption import PreemptionGuard

    guard = PreemptionGuard().install()
    server.drain_on_preemption(guard)
    # ready-means-warm: /readyz answers 503 until the warmup pass
    # (engine compile-or-cache-load + adapter prefetch) finishes in the
    # background — the pod serves probes immediately but takes traffic
    # only warm (docs/serving.md "Engine fleet")
    server.begin_warmup()
    import threading

    threading.Thread(target=server.warmup, name="serving-warmup",
                     daemon=True).start()
    logger.info("serving graph gateway starting", host=host, port=port)
    # handle_signals=False: run_app would otherwise re-register SIGTERM
    # (loop.add_signal_handler -> GracefulExit) over the guard's handler
    # and tear the server down before drain ever ran. With the guard
    # owning SIGTERM, the first signal drains (readyz flips, in-flight
    # finishes) and the second escalates to the default terminate.
    web.run_app(build_serving_app(server), host=host, port=port, print=None,
                handle_signals=False)
