"""Serving-graph step DAG (reference analog: mlrun/serving/states.py:102
BaseStep, :398 TaskStep, :671 RouterStep, :801 QueueStep, :892 FlowStep,
:1405 RootFlowStep — fresh implementation).

The reference builds a storey async flow (states.py:1622); here the graph runs
on a built-in engine (``mlrun_tpu.serving.flow_engine``): sync in-process for
request/response topologies, asyncio for queue-decoupled flows. Model steps
run XLA-compiled callables — see ``mlrun_tpu.serving.v2_serving``.
"""

from __future__ import annotations

import copy
import importlib
import inspect
import time
import traceback
from typing import Any, Callable, Optional, Union

from ..chaos import FaultPoints, fire
from ..model import ModelObj
from ..obs import STEP_LATENCY
from ..utils import get_in, logger, update_in
from .resilience import (
    DeadlineExceeded,
    QueueFullError,
    StepResilience,
    check_deadline,
    validate_resilience_spec,
)

callable_prefix = "_"
path_splitter = "/"


class GraphError(Exception):
    pass


def get_class(class_name: str, namespace: dict | None = None):
    """Resolve 'module.sub.Class' or a bare name from the namespace."""
    if isinstance(class_name, type):
        return class_name
    namespace = namespace or {}
    if class_name in namespace:
        return namespace[class_name]
    if "." in class_name:
        module_path, name = class_name.rsplit(".", 1)
        module = importlib.import_module(module_path)
        return getattr(module, name)
    # well-known serving classes
    from . import routers, v2_serving

    for module in (v2_serving, routers):
        if hasattr(module, class_name):
            return getattr(module, class_name)
    raise GraphError(f"class '{class_name}' not found in namespace")


def get_function(handler: Union[str, Callable], namespace: dict | None = None):
    if callable(handler):
        return handler
    namespace = namespace or {}
    if handler in namespace:
        return namespace[handler]
    if "." in handler:
        module_path, name = handler.rsplit(".", 1)
        module = importlib.import_module(module_path)
        return getattr(module, name)
    raise GraphError(f"handler '{handler}' not found in namespace")


class BaseStep(ModelObj):
    kind = "BaseStep"
    _dict_fields = ["kind", "name", "class_name", "class_args", "handler",
                    "after", "function", "comment", "shape", "full_event",
                    "input_path", "result_path", "on_error", "responder",
                    "resilience"]

    def __init__(self, name: str | None = None, after: list | None = None,
                 shape: str | None = None):
        self.name = name
        self.after = after or []
        self.shape = shape
        self.comment = None
        self.class_name = None
        self.class_args = {}
        self.handler = None
        self.function = None
        self.full_event = False
        self.input_path = None
        self.result_path = None
        self.on_error = None
        self.responder = False
        self.resilience = None
        self._resilience: Optional[StepResilience] = None
        self._parent: Optional["FlowStep"] = None
        self._next: list[str] = []

    @property
    def next_steps(self) -> list[str]:
        return self._next

    def set_parent(self, parent: "FlowStep"):
        self._parent = parent

    def after_step(self, *after):
        self.after = [a if isinstance(a, str) else a.name for a in after]
        return self

    def error_handler(self, name: str):
        self.on_error = name
        return self

    def with_resilience(self, circuit_breaker: dict | None = None,
                        admission: dict | None = None):
        """Attach an admission controller and/or circuit breaker to this
        step (validated at graph init — see serving/resilience.py)."""
        spec = {}
        if circuit_breaker is not None:
            spec["circuit_breaker"] = circuit_breaker
        if admission is not None:
            spec["admission"] = admission
        validate_resilience_spec(spec, self.name or "")
        self.resilience = spec or None
        return self

    def _init_resilience(self, clock=None):
        try:
            self._resilience = StepResilience.from_spec(
                self.resilience, name=self.name or "", clock=clock)
        except ValueError as exc:
            raise GraphError(str(exc)) from exc

    def respond(self):
        self.responder = True
        return self

    def to(self, class_name=None, name: str | None = None, handler=None,
           model_path: str | None = None, function: str | None = None,
           full_event: bool | None = None, input_path: str | None = None,
           result_path: str | None = None, **class_args) -> "BaseStep":
        """Chain a new downstream step and return it."""
        if self._parent is None:
            raise GraphError(
                f"step '{self.name}' is not attached to a flow graph")
        step = self._parent.add_step(
            class_name=class_name, name=name, handler=handler,
            model_path=model_path, function=function, after=[self.name],
            full_event=full_event, input_path=input_path,
            result_path=result_path, **class_args)
        self._next.append(step.name)
        return step

    def init_object(self, context, namespace: dict, mode: str = "sync"):
        pass

    def run(self, event, *args, **kwargs):
        return event

    def _extract_input(self, event):
        if self.full_event:
            return event
        if self.input_path:
            return get_in(event.body, self.input_path)
        return event.body

    def _apply_result(self, event, result):
        if self.full_event:
            return result if result is not None else event
        if self.result_path:
            if not isinstance(event.body, dict):
                raise GraphError(
                    f"step '{self.name}' has result_path="
                    f"'{self.result_path}' but the event body is "
                    f"{type(event.body).__name__}, not a dict")
            update_in(event.body, self.result_path, result)
        else:
            event.body = result
        return event


class TaskStep(BaseStep):
    """A step running a class instance or a handler fn (states.py:398)."""

    kind = "task"

    def __init__(self, class_name=None, class_args: dict | None = None,
                 handler=None, name: str | None = None, after: list | None = None,
                 full_event: bool | None = None, function=None,
                 input_path: str | None = None, result_path: str | None = None):
        super().__init__(name, after)
        self.class_name = (
            class_name if isinstance(class_name, (str, type(None)))
            else class_name.__name__)
        self._class_object = class_name if isinstance(class_name, type) else None
        self.class_args = class_args or {}
        self.handler = handler
        self.function = function
        self.full_event = bool(full_event)
        self.input_path = input_path
        self.result_path = result_path
        self._object = None
        self._handler_fn: Optional[Callable] = None
        self.context = None

    def init_object(self, context, namespace: dict, mode: str = "sync"):
        self.context = context
        self._init_resilience()
        if self.class_name or self._class_object:
            cls = self._class_object or get_class(self.class_name, namespace)
            # NOTE: no deepcopy — routers receive live route step objects
            args = dict(self.class_args)
            init_sig = inspect.signature(cls.__init__)
            kwargs = {}
            if "context" in init_sig.parameters:
                kwargs["context"] = context
            if "name" in init_sig.parameters:
                kwargs["name"] = self.name
            self._object = cls(**kwargs, **args)
            if hasattr(self._object, "post_init"):
                self._object.post_init(mode)
            handler_name = self.handler or "do"
            if not hasattr(self._object, handler_name) and hasattr(
                    self._object, "do_event"):
                handler_name = "do_event"
            self._handler_fn = getattr(self._object, handler_name)
        elif self.handler:
            self._handler_fn = get_function(self.handler, namespace)
        else:
            self._handler_fn = lambda x: x

    @property
    def object(self):
        return self._object

    def run(self, event, *args, **kwargs):
        if self._handler_fn is None:
            raise GraphError(f"step '{self.name}' was not initialized")
        check_deadline(event, self.name)
        fire(FaultPoints.serving_step, step=self.name, event=event)
        if self._resilience is not None:
            return self._resilience.call(lambda: self._execute(event),
                                         context=self.context)
        return self._execute(event)

    def _execute(self, event):
        needs_event = self.full_event or getattr(
            self._object, "_needs_event", False) or (
            self._object is not None
            and getattr(self._handler_fn, "__name__", "") in ("do_event",))
        if needs_event:
            result = self._handler_fn(event)
            return result if result is not None else event
        data = self._extract_input(event)
        result = self._handler_fn(data)
        return self._apply_result(event, result)


class ErrorStep(TaskStep):
    kind = "error_step"


class RouterStep(TaskStep):
    """Step holding routes and dispatching events to them (states.py:671)."""

    kind = "router"
    _dict_fields = BaseStep._dict_fields + ["routes"]

    def __init__(self, class_name=None, class_args=None, handler=None,
                 name=None, after=None, routes: dict | None = None):
        super().__init__(class_name or "ModelRouter", class_args, handler,
                         name, after)
        self.routes: dict[str, TaskStep] = routes or {}

    def to_dict(self, exclude=None):
        # routes hold live step objects — serialize them (the in-process
        # mock-server path never JSON-round-trips, so only the gateway
        # deploy path exercises this)
        out = super().to_dict(exclude=(exclude or []) + ["routes"])
        out["routes"] = {key: route.to_dict()
                         for key, route in self.routes.items()}
        return out

    def add_route(self, key: str, route: "TaskStep | None" = None,
                  class_name=None, handler=None, function=None,
                  **class_args) -> TaskStep:
        if route is None:
            route = TaskStep(class_name, class_args, handler, name=key,
                             function=function)
        route.name = key
        route.set_parent(self._parent)
        self.routes[key] = route
        return route

    def add_replica_routes(self, count: int, class_name=None,
                           key_prefix: str = "replica",
                           **class_args) -> list["TaskStep"]:
        """Declare ``count`` identical replica routes
        (``<key_prefix>-0`` … ``<key_prefix>-N-1``) — the fleet topology
        behind ``PrefixAffinityRouter``, where every route is an
        interchangeable model replica rather than a distinct model."""
        if count < 1:
            raise GraphError(
                f"router '{self.name}': replica count must be >= 1, "
                f"got {count}")
        return [self.add_route(f"{key_prefix}-{i}", class_name=class_name,
                               **dict(class_args))
                for i in range(count)]

    def clear_children(self, routes: list[str] | None = None):
        if routes is None:
            self.routes = {}
        else:
            for key in routes:
                self.routes.pop(key, None)

    def init_object(self, context, namespace: dict, mode: str = "sync"):
        self.class_args = dict(self.class_args)
        self.class_args["routes"] = self.routes
        super().init_object(context, namespace, mode)
        for route in self.routes.values():
            route.init_object(context, namespace, mode)

    def run(self, event, *args, **kwargs):
        check_deadline(event, self.name)
        fire(FaultPoints.serving_step, step=self.name, event=event)

        def _dispatch():
            result = self._handler_fn(event)
            return result if result is not None else event

        if self._resilience is not None:
            return self._resilience.call(_dispatch, context=self.context)
        return _dispatch()


class QueueStep(BaseStep):
    """Stream/queue boundary (states.py:801). With a stream path the event is
    pushed to the stream (monitoring pipeline); downstream steps in the same
    process consume asynchronously via the flow engine."""

    kind = "queue"
    _dict_fields = BaseStep._dict_fields + [
        "path", "shards", "retention_in_hours", "max_queue_size", "max_wait"]

    def __init__(self, name=None, path: str = "", after=None, shards=None,
                 retention_in_hours=None, max_queue_size: int | None = None,
                 max_wait: float | None = None, **options):
        super().__init__(name, after)
        self.path = path
        self.shards = shards
        self.retention_in_hours = retention_in_hours
        self.max_queue_size = max_queue_size
        self.max_wait = max_wait
        self.options = options
        self._stream = None
        self._queue = None
        self._workers = None
        self._pending = 0
        self._lock = None
        self._shed = 0
        self._errors = 0

    def _validate_bounds(self):
        if self.max_queue_size is not None:
            if not isinstance(self.max_queue_size, int) \
                    or self.max_queue_size <= 0:
                raise GraphError(
                    f"queue '{self.name}': max_queue_size must be a "
                    f"positive int, got {self.max_queue_size!r}")
        if self.max_wait is not None:
            if not isinstance(self.max_wait, (int, float)) \
                    or self.max_wait <= 0:
                raise GraphError(
                    f"queue '{self.name}': max_wait must be a positive "
                    f"number of seconds, got {self.max_wait!r}")

    def init_object(self, context, namespace, mode="sync"):
        self._validate_bounds()
        self.context = context
        if self.path:
            from .streams import get_stream_pusher

            self._stream = get_stream_pusher(self.path, **self.options)
        if mode == "async" and self._parent is not None:
            import queue as queue_mod
            import threading

            self._queue = queue_mod.Queue()
            self._lock = threading.Lock()
            self._workers = [
                threading.Thread(target=self._consume, daemon=True)
                for _ in range(int(self.shards or 1))
            ]
            for worker in self._workers:
                worker.start()

    def _consume(self):
        """Worker loop: pop events, run the downstream subgraph
        (the storey async-flow replacement, reference states.py:1622-1710)."""
        import time as time_mod

        while True:
            event, enqueued = self._queue.get()
            try:
                waited = time_mod.monotonic() - enqueued
                if self.max_wait is not None and waited > self.max_wait:
                    # queue-time budget spent: shed instead of burning
                    # TPU time on a request the caller has given up on
                    self._record_shed("max_wait", waited=round(waited, 3))
                    continue
                try:
                    check_deadline(event, self.name)
                except DeadlineExceeded:
                    self._record_shed("deadline", waited=round(waited, 3))
                    continue
                self._parent._run_downstream(self, event)
            except Exception as exc:  # noqa: BLE001 - async branch errors
                self._handle_async_error(event, exc)
            finally:
                with self._lock:
                    self._pending -= 1
                self._queue.task_done()

    def _record_shed(self, reason: str, **fields):
        self._shed += 1
        logger.warning("queue shed event", step=self.name, reason=reason,
                       shed_total=self._shed, **fields)
        incr = getattr(self.context, "incr", None)
        if callable(incr):
            incr(f"queue.{self.name}.shed")

    def _handle_async_error(self, event, exc: Exception):
        """Async-branch failure: count it on the server, surface it in
        metrics, and route through ``on_error`` when one is set (the old
        behavior logged and swallowed, hiding every async failure)."""
        self._errors += 1
        server = getattr(self.context, "server", None)
        if server is not None and hasattr(server, "record_step_error"):
            server.record_step_error(self.name)
        incr = getattr(self.context, "incr", None)
        if callable(incr):
            incr(f"queue.{self.name}.errors")
        handler = None
        if self.on_error and self._parent is not None:
            handler = self._parent._steps.get(self.on_error)
        if handler is not None:
            error_event = copy.copy(event)
            error_event.error = str(exc)
            try:
                # observed like any step: the error handler is often
                # the slowest hop of a failing request and must show
                # in the latency histogram and the span tree
                self._parent._observed_run(handler, error_event)
                return
            except Exception as handler_exc:  # noqa: BLE001
                logger.error("queue on_error handler failed",
                             step=self.name, handler=self.on_error,
                             error=str(handler_exc))
        logger.error("async queue branch failed", step=self.name,
                     error=str(exc))

    def run(self, event, *args, **kwargs):
        if self._stream is not None:
            body = event.body if not self.full_event else event.__dict__
            self._stream.push(body)
        if self._queue is not None:
            import time as time_mod

            check_deadline(event, self.name)
            fire(FaultPoints.serving_queue, step=self.name, event=event)
            if self.max_queue_size is not None \
                    and self._queue.qsize() >= self.max_queue_size:
                # reject-newest load shedding: answer in microseconds
                # instead of growing an unbounded backlog
                self._record_shed("queue_full",
                                  max_queue_size=self.max_queue_size)
                raise QueueFullError(
                    f"queue '{self.name}' is full "
                    f"(max_queue_size={self.max_queue_size})")
            with self._lock:
                self._pending += 1
            self._queue.put((copy.deepcopy(event), time_mod.monotonic()))
            return None  # downstream continues on a worker thread
        return event

    @property
    def shed_count(self) -> int:
        """Events shed by this queue (full / max_wait / deadline)."""
        return self._shed

    @property
    def error_count(self) -> int:
        """Async branch errors observed below this queue."""
        return self._errors

    def wait_empty(self, timeout: float = 30.0) -> bool:
        """Drain; True when empty, False on timeout (callers must not treat
        a timeout as completion)."""
        if self._queue is None:
            return True
        import time as time_mod

        deadline = time_mod.monotonic() + timeout
        while time_mod.monotonic() < deadline:
            with self._lock:
                if self._pending == 0:
                    return True
            time_mod.sleep(0.01)
        return False


class JoinStep(BaseStep):
    """Merge fan-out branches (reference analog: the storey stream ``Merge``
    step, mlrun/serving/merger.py:37): buffers the event per id until all
    parent branches delivered, then emits one merged event (dict bodies are
    union-merged; non-dict bodies are collected into a list)."""

    kind = "join"

    def __init__(self, name=None, after=None, expected: int | None = None):
        super().__init__(name, after)
        self.expected = expected
        self._pending: dict = {}
        self._lock = None

    def init_object(self, context, namespace, mode="sync"):
        import threading

        self._lock = threading.Lock()
        self._pending = {}

    def run(self, event, *args, **kwargs):
        expected = self.expected or max(len(self.after or []), 1)
        key = getattr(event, "id", None) or id(event)
        with self._lock:
            bucket = self._pending.setdefault(key, [])
            bucket.append(event.body)
            if len(bucket) < expected:
                return None  # wait for the remaining branches
            bodies = self._pending.pop(key)
        if all(isinstance(b, dict) for b in bodies):
            merged: dict = {}
            for body in bodies:
                merged.update(body)
        else:
            merged = bodies
        event.body = merged
        return event


class FlowStep(BaseStep):
    """A container of steps forming a DAG (states.py:892)."""

    kind = "flow"
    _dict_fields = BaseStep._dict_fields + ["steps", "engine"]

    def __init__(self, name=None, steps: dict | None = None, after=None,
                 engine: str | None = None):
        super().__init__(name, after)
        self._steps: dict[str, BaseStep] = {}
        self.engine = engine or "sync"
        self._start_steps: list[BaseStep] = []
        self.context = None
        if steps:
            for step_name, step in steps.items():
                self._add_existing(step_name, step)

    # -- construction ------------------------------------------------------
    @property
    def steps(self) -> dict:
        return self._steps

    @steps.setter
    def steps(self, steps: dict):
        self._steps = {}
        for name, step in (steps or {}).items():
            self._add_existing(name, step)

    def _add_existing(self, name: str, step):
        if isinstance(step, dict):
            step = step_from_dict(step)
        step.name = name
        step.set_parent(self)
        self._steps[name] = step

    def add_step(self, class_name=None, name=None, handler=None,
                 model_path: str | None = None, after=None, function=None,
                 full_event=None, input_path=None, result_path=None,
                 graph_shape=None, resilience: dict | None = None,
                 **class_args) -> BaseStep:
        if class_name == "$queue" or (isinstance(class_name, str)
                                      and class_name == "queue"):
            step = QueueStep(name=name, path=class_args.pop("path", ""),
                             **class_args)
        elif isinstance(class_name, str) and class_name in ("$join", "join"):
            step = JoinStep(name=name,
                            expected=class_args.pop("expected", None))
        elif isinstance(class_name, str) and class_name == "$router":
            step = RouterStep(name=name, class_args=class_args)
        elif isinstance(class_name, RouterStep):
            step = class_name
            step.name = name or step.name
        else:
            if model_path is not None:
                class_args["model_path"] = model_path
            step = TaskStep(class_name, class_args, handler, name=name,
                            function=function, full_event=full_event,
                            input_path=input_path, result_path=result_path)
        step.name = step.name or f"step{len(self._steps)}"
        if after:
            step.after = [a if isinstance(a, str) else a.name for a in after]
        if resilience:
            validate_resilience_spec(resilience, step.name)
            step.resilience = resilience
        step.set_parent(self)
        self._steps[step.name] = step
        return step

    def to(self, class_name=None, name=None, handler=None, model_path=None,
           function=None, full_event=None, input_path=None, result_path=None,
           **class_args) -> BaseStep:
        """First step in the flow (or chain from the flow itself)."""
        return self.add_step(
            class_name=class_name, name=name, handler=handler,
            model_path=model_path, function=function, after=[],
            full_event=full_event, input_path=input_path,
            result_path=result_path, **class_args)

    def add_route(self, *args, **kwargs):
        raise GraphError("add_route is valid on router topology graphs only")

    # -- init / run --------------------------------------------------------
    def init_object(self, context, namespace, mode="sync"):
        self.context = context
        if self.engine == "async":
            mode = "async"
        for step in self._steps.values():
            step.init_object(context, namespace, mode)
        self._start_steps = [
            s for s in self._steps.values() if not s.after
        ] or list(self._steps.values())[:1]
        self.check_and_process_graph()

    def check_and_process_graph(self, allow_empty: bool = False):
        """Validate the DAG: unknown after-references and cycles."""
        for step in self._steps.values():
            for parent in step.after or []:
                if parent not in self._steps:
                    raise GraphError(
                        f"step '{step.name}' is after unknown step '{parent}'")
        # cycle check via DFS
        visiting: set[str] = set()
        done: set[str] = set()

        def visit(name: str):
            if name in done:
                return
            if name in visiting:
                raise GraphError(f"graph has a cycle through '{name}'")
            visiting.add(name)
            for child in self._children(name):
                visit(child.name)
            visiting.discard(name)
            done.add(name)

        for step in self._start_steps:
            visit(step.name)

    def _children(self, name: str) -> list[BaseStep]:
        return [s for s in self._steps.values() if name in (s.after or [])]

    def _observed_run(self, step: BaseStep, event):
        """One step execution wrapped in telemetry: the per-step latency
        histogram always, plus a child span (parented on the server's
        root span) when the event carries a trace id."""
        tracer = getattr(self.context, "tracer", None)
        trace_id = getattr(event, "trace_id", None)
        span = None
        if tracer is not None and trace_id:
            span = tracer.start_span(
                f"step.{step.name}", trace_id=trace_id,
                parent_id=getattr(event, "span_id", None),
                attrs={"kind": step.kind}, activate=True)
        started = time.perf_counter()
        try:
            result = step.run(event)
        except Exception:
            STEP_LATENCY.observe(time.perf_counter() - started,
                                 step=step.name or "")
            if span is not None:
                tracer.end_span(span, status="error")
            raise
        STEP_LATENCY.observe(time.perf_counter() - started,
                             step=step.name or "")
        if span is not None:
            tracer.end_span(span)
        return result

    def run(self, event, *args, **kwargs):
        """Execute the DAG synchronously: follow after-links from the start
        steps; the responder step's (or last) result becomes the response."""
        response = None
        queue: list[tuple[BaseStep, Any]] = [
            (step, event) for step in self._start_steps]
        while queue:
            step, current = queue.pop(0)
            try:
                result = self._observed_run(step, current)
            except DeadlineExceeded:
                # no budget left — a fallback handler would still miss the
                # deadline, so always propagate as a fast 504
                raise
            except Exception as exc:  # noqa: BLE001 - route to error handler
                if step.on_error and step.on_error in self._steps:
                    error_event = copy.copy(current)
                    error_event.error = str(exc)
                    result = self._observed_run(
                        self._steps[step.on_error], error_event)
                else:
                    raise
            if result is None and isinstance(step, (QueueStep, JoinStep)):
                # queue: downstream continues on workers; join: waiting for
                # the remaining branches
                continue
            if getattr(step, "responder", False):
                response = result
            children = self._children(step.name)
            if not children and response is None:
                response = result
            for index, child in enumerate(children):
                # fan-out: siblings beyond the first get their own event copy
                # so one branch's output never leaks into another
                queue.append(
                    (child, result if index == 0 else copy.deepcopy(result)))
        return response

    def _run_downstream(self, from_step: BaseStep, event):
        """Run the subgraph below ``from_step`` (async queue workers)."""
        queue: list[tuple[BaseStep, Any]] = [
            (child, event) for child in self._children(from_step.name)]
        while queue:
            step, current = queue.pop(0)
            try:
                result = self._observed_run(step, current)
            except DeadlineExceeded:
                raise
            except Exception as exc:  # noqa: BLE001
                if step.on_error and step.on_error in self._steps:
                    error_event = copy.copy(current)
                    error_event.error = str(exc)
                    result = self._observed_run(
                        self._steps[step.on_error], error_event)
                else:
                    raise
            if result is None and isinstance(step, (QueueStep, JoinStep)):
                continue
            for index, child in enumerate(self._children(step.name)):
                queue.append(
                    (child, result if index == 0 else copy.deepcopy(result)))

    def _flush(self, timeout: float = 30.0) -> bool:
        drained = True
        for step in self._steps.values():
            if isinstance(step, QueueStep):
                if not step.wait_empty(timeout):
                    from ..utils import logger

                    logger.warning("async queue did not drain within timeout",
                                   step=step.name, timeout=timeout)
                    drained = False
        return drained

    def plot(self, filename=None, format=None, **kw):
        """Render the graph as mermaid text (graphviz-free)."""
        lines = ["graph LR"]
        for step in self._steps.values():
            for parent in step.after or []:
                lines.append(f"  {parent} --> {step.name}")
            if isinstance(step, RouterStep):
                for route in step.routes:
                    lines.append(f"  {step.name} -.-> {route}")
        text = "\n".join(lines)
        if filename:
            with open(filename, "w") as fp:
                fp.write(text)
        return text

    def to_dict(self, exclude=None):
        out = super().to_dict(exclude=["steps"])
        out["steps"] = {name: step.to_dict()
                        for name, step in self._steps.items()}
        return out


class RootFlowStep(FlowStep):
    """Top-level graph (states.py:1405)."""

    kind = "flow"


def step_from_dict(struct: dict) -> BaseStep:
    kind = struct.get("kind", "task")
    cls = {"task": TaskStep, "router": RouterStep, "queue": QueueStep,
           "flow": FlowStep, "error_step": ErrorStep,
           "join": JoinStep}.get(kind, TaskStep)
    step = cls.from_dict(struct)
    if kind == "router" and isinstance(step.routes, dict):
        step.routes = {
            key: (step_from_dict(r) if isinstance(r, dict) else r)
            for key, r in step.routes.items()
        }
    if kind == "flow":
        inner = struct.get("steps", {})
        step._steps = {}
        for name, sub in inner.items():
            step._add_existing(name, sub)
    return step


def graph_root_setter(server, graph):
    """Set a graph object or build one from a topology string/dict."""
    if isinstance(graph, dict):
        graph = step_from_dict(graph)
    if isinstance(graph, str):
        if graph == "router":
            graph = RouterStep()
        elif graph == "flow":
            graph = RootFlowStep()
        else:
            raise GraphError(f"unsupported topology '{graph}'")
    if isinstance(graph, RouterStep):
        root = RootFlowStep()
        graph.name = graph.name or "router"
        root._add_existing(graph.name, graph)
        root._router = graph
        return root
    if not isinstance(graph, FlowStep):
        raise GraphError("graph must be a router or flow step")
    return graph
