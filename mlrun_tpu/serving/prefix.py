"""Prefix-aware KV reuse index for the paged engine (serving/paged.py).

Production LLM traffic is dominated by shared prompt prefixes (system
prompts, few-shot templates, multi-turn history). The paged KV pool makes
those prefixes shareable at page granularity: this module is the host-side
radix index mapping chains of page-size token blocks to physical page ids
with refcounts. On admission the engine matches the longest cached chain,
points the new slot's page table at the shared pages read-only
(refcount++), and prefills only the uncached suffix — the TTFT win the
paper's <200ms serving claim needs on repeated-prefix workloads.

Invariants the engine relies on:

- Only FULL blocks are indexed, and a match never covers the whole prompt
  (at least one token is left to prefill, because the engine needs the
  last real position's logits to sample the first generated token).
- Shared pages are read-only by construction: decode writes land at
  positions >= prompt_len, which always sit in the slot's private pages.
- Every indexed node carries a page; the page-bearing set is closed under
  ancestors (chains register root-down, eviction is leaf-first), so a
  match can always walk a contiguous chain.
- Eviction only reclaims refcount-0 pages, leaf-first in LRU order
  (evicting a parent before its child would orphan the child's chain).

Pure host-side bookkeeping owned by the engine's scheduler thread — no
jax imports, no locking (single-writer by construction, like the page
free-list it feeds).
"""

from __future__ import annotations

import hashlib
import heapq


def block_chain_key(tokens, block_tokens: int,
                    max_blocks: int | None = None,
                    adapter: str = "") -> int:
    """Stable 64-bit hash of a prompt's leading full ``block_tokens``-sized
    token blocks — the fleet routing key (serving/fleet.py).

    This is the same block-chain identity the radix index keys on: two
    prompts sharing their leading full blocks (a system prompt, a few-shot
    template) produce the SAME key, so a consistent-hash router sends them
    to the same replica and the prefix KV stays cache-resident there.
    ``max_blocks`` caps how deep the chain reaches into the prompt (the
    router wants prefix locality, not whole-prompt uniqueness — without
    the cap, two prompts sharing a hot prefix but differing later would
    route apart and re-prefill the shared blocks on both replicas).
    Prompts shorter than one full block hash their raw tokens, namespaced
    so a short prompt can never collide with a block chain. Uses sha256,
    not ``hash()``: the key must agree across processes and runs.

    ``adapter`` namespaces the key per tenant (docs/serving.md
    "Multi-tenant LoRA"): KV computed under adapter A is useless to
    adapter B, so the SAME prompt under different adapters must route —
    and cache — as different identities. The empty adapter (base model)
    hashes byte-identically to the pre-adapter key."""
    if block_tokens <= 0:
        raise ValueError(f"block_tokens must be > 0, got {block_tokens}")
    digest = hashlib.sha256()
    if adapter:
        digest.update(b"adapter:")
        digest.update(adapter.encode())
        digest.update(b"\n")
    full = len(tokens) // block_tokens
    if max_blocks is not None:
        full = min(full, int(max_blocks))
    if full <= 0:
        digest.update(b"short:")
        digest.update(",".join(str(int(t)) for t in tokens).encode())
    else:
        for i in range(full):
            block = tokens[i * block_tokens:(i + 1) * block_tokens]
            digest.update(b"|")
            digest.update(",".join(str(int(t)) for t in block).encode())
    return int.from_bytes(digest.digest()[:8], "big")


class _Node:
    """One full block in a cached chain: the physical page holding its KV,
    how many active slots reference that page, and an LRU stamp."""

    __slots__ = ("parent", "block", "children", "page_id", "refcount",
                 "last_used")

    def __init__(self, parent: "_Node | None" = None, block: tuple = ()):
        self.parent = parent
        self.block = block
        self.children: dict[tuple, _Node] = {}
        self.page_id = -1
        self.refcount = 0
        self.last_used = 0


class PrefixCache:
    """Radix index over page-size token blocks -> (page id, refcount)."""

    def __init__(self, page_size: int):
        if page_size <= 0:
            raise ValueError(f"page_size must be > 0, got {page_size}")
        self.page_size = int(page_size)
        # one radix root per adapter id (docs/serving.md "Multi-tenant
        # LoRA"): block-chain identity is (adapter, blocks), so KV
        # computed under adapter A can never be matched — and served —
        # to adapter B, while same-tenant traffic still shares. "" is
        # the base model's root (the pre-adapter behavior).
        self._roots: dict[str, _Node] = {"": _Node()}
        self._tick = 0          # monotonic LRU clock (deterministic)
        self._cached = 0        # page-bearing node count
        self._held = 0          # nodes with refcount > 0
        # observability counters (surfaced through engine stats)
        self.queries = 0
        self.hits = 0
        self.cached_tokens = 0  # prompt tokens served from cache
        self.evictions = 0

    @property
    def _root(self) -> _Node:
        """The base model's root (back-compat accessor for tests)."""
        return self._roots[""]

    def _root_for(self, adapter: str) -> _Node:
        root = self._roots.get(adapter)
        if root is None:
            root = self._roots[adapter] = _Node()
        return root

    def _block(self, prompt, i: int) -> tuple:
        ps = self.page_size
        return tuple(prompt[i * ps:(i + 1) * ps])

    def _hold(self, node: _Node) -> None:
        if node.refcount == 0:
            self._held += 1
        node.refcount += 1
        node.last_used = self._tick

    # -- lookup --------------------------------------------------------------
    def match(self, prompt,
              adapter: str = "") -> tuple[list[int], list[_Node]]:
        """Longest cached chain of full blocks UNDER ``adapter``'s root,
        capped at ``(len(prompt) - 1) // page_size`` so at least one
        suffix token remains to prefill. Increments refcounts on the
        matched nodes (caller must :meth:`release` them when the slot
        frees). Returns (page_ids, nodes), both possibly empty. The
        hit/query counters are the ENGINE's to update — it may
        match-and-release repeatedly while the head-of-line request
        waits for pages."""
        self._tick += 1
        limit = max(0, (len(prompt) - 1) // self.page_size)
        node = self._root_for(adapter)
        pages: list[int] = []
        nodes: list[_Node] = []
        for i in range(limit):
            child = node.children.get(self._block(prompt, i))
            if child is None or child.page_id < 0:
                break
            self._hold(child)
            pages.append(child.page_id)
            nodes.append(child)
            node = child
        return pages, nodes

    def release(self, nodes) -> None:
        """Drop one slot-hold per node (admission abort or slot free)."""
        for node in nodes:
            if node.refcount > 0:
                node.refcount -= 1
                if node.refcount == 0:
                    self._held -= 1

    # -- registration --------------------------------------------------------
    def register(self, prompt, page_ids, matched_nodes,
                 adapter: str = "") -> tuple[list[_Node], list[int]]:
        """Index the prompt's full blocks past the matched chain, claiming
        the freshly written pages ``page_ids[i]`` for blocks that are not
        already cached. Returns (held_nodes, claimed_page_ids): claimed
        pages are now cache-owned — the slot must NOT return them to the
        free list on release (they stay cached until evicted). Blocks that
        raced an identical registration keep the caller's page private
        (skipped, not claimed) but are still HELD, so every slot holds a
        contiguous root-down chain — the invariant behind the O(1)
        :meth:`evictable_pages` count."""
        self._tick += 1
        k = len(matched_nodes)
        node = matched_nodes[-1] if matched_nodes \
            else self._root_for(adapter)
        full = len(prompt) // self.page_size
        held: list[_Node] = []
        claimed: list[int] = []
        for i in range(k, full):
            block = self._block(prompt, i)
            child = node.children.get(block)
            if child is None:
                child = _Node(parent=node, block=block)
                child.page_id = int(page_ids[i])
                node.children[block] = child
                self._cached += 1
                claimed.append(child.page_id)
            # else: identical chain raced us — the request's physical page
            # for this block stays private to the slot (freed on release)
            self._hold(child)
            held.append(child)
            node = child
        return held, claimed

    # -- eviction ------------------------------------------------------------
    def cached_pages(self) -> int:
        return self._cached

    def evictable_pages(self) -> int:
        """Pages reclaimable right now. Because every slot holds a
        contiguous root-down chain (match holds the prefix, register holds
        everything it descends through), a held node's ancestors are all
        held too — so a refcount-0 node can never sit above a held one and
        the count is simply cached minus held. O(1): this runs on every
        submit() via the pressure ladder."""
        return self._cached - self._held

    def evict(self, n: int, on_evict=None) -> list[int]:
        """Reclaim up to ``n`` refcount-0 pages, leaf-first in LRU order.
        ``on_evict(node)`` observes each victim before detach (the engine
        fires the ``llm.prefix_evict`` chaos point there). Returns the
        freed page ids.

        ONE trie walk collects the candidate leaves into a heap; parents
        are promoted as their last child is evicted — O(trie + n log n)
        per reclaim, not a re-walk per page."""
        freed: list[int] = []
        if n <= 0:
            return freed
        heap: list[tuple[int, int, _Node]] = []
        roots = set(self._roots.values())

        def walk(node: _Node):
            for child in node.children.values():
                walk(child)
            if node not in roots and not node.children \
                    and node.refcount == 0:
                heap.append((node.last_used, id(node), node))

        for root in self._roots.values():
            walk(root)
        heapq.heapify(heap)
        while heap and len(freed) < n:
            _, _, victim = heapq.heappop(heap)
            if on_evict is not None:
                on_evict(victim)
            parent = victim.parent
            parent.children.pop(victim.block, None)
            self._cached -= 1
            self.evictions += 1
            freed.append(victim.page_id)
            if parent not in roots and not parent.children \
                    and parent.refcount == 0:
                heapq.heappush(heap,
                               (parent.last_used, id(parent), parent))
        # drop per-adapter roots whose last chain just evicted (the base
        # "" root stays): a rotating tenant population must not grow
        # _roots — and the walk above — forever
        for adapter in [a for a, root in self._roots.items()
                        if a and not root.children]:
            del self._roots[adapter]
        return freed
