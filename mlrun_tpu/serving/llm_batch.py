"""Continuous batching for the TPU LLM engine.

Slot-based scheduler over a fixed-size decode batch (the vLLM-style design,
TPU-shaped): the KV cache is a static [layers, slots, max_len, heads, dim]
allocation so every decode dispatch is ONE compiled program regardless of
which requests occupy the slots. Requests are admitted into free slots by a
bucketed batch=1 prefill whose kv rows are inserted into the big cache with
`dynamic_update_slice`; decode then advances every active slot one token per
step with per-row positions (per-row RoPE tables + scatter cache writes).
Finished rows free their slot for the next queued request — no
head-of-line blocking on long generations.

The reference has no inference engine at all (its V2ModelServer calls user
predict(), mlrun/serving/v2_serving.py); this is the TPU-native capability
behind the <200ms p50 TTFT target under concurrency (BASELINE.md).
"""

from __future__ import annotations

import functools
import math
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field, replace as dataclass_replace
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..chaos import FaultPoints, fire
from ..config import mlconf
from ..models.llama import LlamaConfig, Params
from ..obs import (
    ADAPTER_LIVE,
    ADAPTER_LOADS,
    LLM_DECODE_TICK,
    LLM_EVENTS,
    LLM_FREE_PAGE_FRAC,
    LLM_ITL,
    LLM_QUEUE_DEPTH,
    LLM_SPEC_ROUNDS,
    LLM_SPEC_TOKENS,
    LLM_TTFT,
    REGISTRY,
    RequestLedger,
    export_phases,
    flight_record,
    get_flight_recorder,
    get_tracer,
    register_memory_collector,
)
from ..obs.stats import nearest_rank
from ..ops.norms import rms_norm
from ..ops.rotary import apply_rope, rope_table
from ..utils import logger
from ..utils.profiler import tick as profiler_tick
from .canary import get_canary_router, split_key_for
from .llm import _cached_attention, _forward_with_cache, init_kv_cache
from .samples import emit_sample, sampling_enabled
from .resilience import (  # noqa: F401 - EngineStoppedError re-exported
    DeadlineExceeded,
    DegradationLadder,
    EngineStoppedError,
    PromptTooLongError,
    QueueFullError,
)


def _decode_rowwise(config: LlamaConfig, params: Params, tokens: jax.Array,
                    cache: dict, rng: jax.Array = None,
                    temperature: jax.Array = None,
                    top_k: jax.Array = None, top_p: jax.Array = None,
                    lora=None, adapter_ids: jax.Array = None):
    """One decode token per row with PER-ROW positions (slots at different
    generation depths). tokens: [B, 1]; cache rows advance independently.

    Per-row sampling settings (temperature/top_k/top_p arrays) ride the
    same compiled program: greedy rows (temperature 0) take an exact
    argmax via jnp.where — see serving/sampling.py.

    ``lora``/``adapter_ids`` add per-row multi-tenant LoRA
    (docs/serving.md "Multi-tenant LoRA"): each slot gathers its OWN
    (A, B) factors from the stacked adapter bank by its [B] slot index
    (0 = base model / inactive rows), so a mixed-tenant batch decodes in
    one compiled program."""
    from .llm import _lora_delta

    b = tokens.shape[0]
    start = cache["pos"]                      # [B]
    positions = start[:, None]                # [B, 1]
    rows = jnp.arange(b)
    x = params["embedding"][tokens].astype(config.dtype)
    cos, sin = rope_table(positions, config.head_dim, config.rope_theta)

    new_k, new_v, new_ks, new_vs = [], [], [], []
    for layer in range(config.n_layers):
        lp = jax.tree_util.tree_map(lambda a: a[layer], params["layers"])
        h = rms_norm(x, lp["attn_norm_scale"], config.norm_eps)

        def proj(h_in, w, t=None, _layer=layer):
            out = jnp.einsum("bse,eh->bsh", h_in, w,
                             preferred_element_type=jnp.float32)
            if lora is not None and t is not None and t in lora:
                out = out + _lora_delta(h_in, lora[t], _layer, adapter_ids)
            return out.astype(x.dtype)

        q = proj(h, lp["wq"], "wq").reshape(b, 1, config.n_heads,
                                            config.head_dim)
        k = proj(h, lp["wk"], "wk").reshape(b, 1, config.n_kv_heads,
                                            config.head_dim)
        v = proj(h, lp["wv"], "wv").reshape(b, 1, config.n_kv_heads,
                                            config.head_dim)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        quantized = "k_scale" in cache
        if quantized:
            from .llm import _dequantize_kv, _quantize_kv

            kq, ks = _quantize_kv(k[:, 0])
            vq, vs = _quantize_kv(v[:, 0])
            k_cache = cache["k"][layer].at[rows, start].set(kq)
            v_cache = cache["v"][layer].at[rows, start].set(vq)
            k_scale = cache["k_scale"][layer].at[rows, start].set(ks)
            v_scale = cache["v_scale"][layer].at[rows, start].set(vs)
            k_attn = _dequantize_kv(k_cache, k_scale, config.dtype)
            v_attn = _dequantize_kv(v_cache, v_scale, config.dtype)
            new_ks.append(k_scale)
            new_vs.append(v_scale)
        else:
            # per-row scatter at each row's own position
            k_cache = cache["k"][layer].at[rows, start].set(
                k[:, 0].astype(cache["k"].dtype))
            v_cache = cache["v"][layer].at[rows, start].set(
                v[:, 0].astype(cache["v"].dtype))
            k_attn, v_attn = k_cache, v_cache
        attn = _cached_attention(config, q, k_attn, v_attn, positions,
                                 cache["k"].shape[2])
        attn = attn.reshape(b, 1, config.qkv_dim)
        x_mid = x + proj(attn, lp["wo"], "wo")
        h2 = rms_norm(x_mid, lp["mlp_norm_scale"], config.norm_eps)
        gate = proj(h2, lp["w_gate"], "w_gate")
        up = proj(h2, lp["w_up"], "w_up")
        x = x_mid + proj(jax.nn.silu(gate) * up, lp["w_down"], "w_down")
        new_k.append(k_cache)
        new_v.append(v_cache)

    x = rms_norm(x, params["final_norm_scale"], config.norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embedding"].T
    logits = jnp.einsum("bse,ev->bsv", x, head,
                        preferred_element_type=jnp.float32)[:, 0]
    if rng is None:
        next_token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    else:
        from .sampling import sample_logits

        next_token = sample_logits(logits, rng, temperature, top_k, top_p)
    new_cache = {"k": jnp.stack(new_k), "v": jnp.stack(new_v),
                 "pos": cache["pos"] + 1}
    if new_ks:
        new_cache["k_scale"] = jnp.stack(new_ks)
        new_cache["v_scale"] = jnp.stack(new_vs)
    return next_token, new_cache


def _verify_rowwise(config: LlamaConfig, params: Params, chunk: jax.Array,
                    cache: dict, lora=None, adapter_ids: jax.Array = None):
    """Batched multi-token speculative verify with PER-ROW positions
    (docs/serving.md "Speculative decoding"). ``chunk``: [B, S] = each
    row's committed last token followed by its k draft proposals, at
    positions ``pos[r]..pos[r]+S-1``. ONE forward computes the target's
    argmax at ALL S positions — the chunk attends the dense cache in
    place under per-position causal masking, no ``all_logits`` dense
    replay of the prefix.

    Rollback contract (same as the batch=1 path's ``cache['pos']``
    rewind): the chunk's KV is scattered at its positions BEFORE
    attention reads, but ``pos`` is NOT advanced here — the host commits
    it to the accepted length afterwards, so entries past the accepted
    position are stale-but-unreadable and get overwritten before any
    later query can attend them. Rows speculating fewer than S-1 tokens
    simply have their trailing writes land past the committed position
    (same stale-entry argument); writes past ``max_len`` drop
    (``mode="drop"``) rather than clamp, so a row at the cache tail
    never has a garbage lane collide with its real last entry."""
    from .llm import _lora_delta

    b, s = chunk.shape
    start = cache["pos"]                               # [B]
    positions = start[:, None] + jnp.arange(s)[None, :]  # [B, S]
    rows = jnp.arange(b)[:, None]                      # [B, 1]
    x = params["embedding"][chunk].astype(config.dtype)
    cos, sin = rope_table(positions, config.head_dim, config.rope_theta)

    new_k, new_v, new_ks, new_vs = [], [], [], []
    for layer in range(config.n_layers):
        lp = jax.tree_util.tree_map(lambda a: a[layer], params["layers"])
        h = rms_norm(x, lp["attn_norm_scale"], config.norm_eps)

        def proj(h_in, w, t=None, _layer=layer):
            out = jnp.einsum("bse,eh->bsh", h_in, w,
                             preferred_element_type=jnp.float32)
            if lora is not None and t is not None and t in lora:
                out = out + _lora_delta(h_in, lora[t], _layer, adapter_ids)
            return out.astype(x.dtype)

        q = proj(h, lp["wq"], "wq").reshape(b, s, config.n_heads,
                                            config.head_dim)
        k = proj(h, lp["wk"], "wk").reshape(b, s, config.n_kv_heads,
                                            config.head_dim)
        v = proj(h, lp["wv"], "wv").reshape(b, s, config.n_kv_heads,
                                            config.head_dim)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        quantized = "k_scale" in cache
        if quantized:
            from .llm import _dequantize_kv, _quantize_kv

            kq, ks = _quantize_kv(k)
            vq, vs = _quantize_kv(v)
            k_cache = cache["k"][layer].at[rows, positions].set(
                kq, mode="drop")
            v_cache = cache["v"][layer].at[rows, positions].set(
                vq, mode="drop")
            k_scale = cache["k_scale"][layer].at[rows, positions].set(
                ks, mode="drop")
            v_scale = cache["v_scale"][layer].at[rows, positions].set(
                vs, mode="drop")
            k_attn = _dequantize_kv(k_cache, k_scale, config.dtype)
            v_attn = _dequantize_kv(v_cache, v_scale, config.dtype)
            new_ks.append(k_scale)
            new_vs.append(v_scale)
        else:
            k_cache = cache["k"][layer].at[rows, positions].set(
                k.astype(cache["k"].dtype), mode="drop")
            v_cache = cache["v"][layer].at[rows, positions].set(
                v.astype(cache["v"].dtype), mode="drop")
            k_attn, v_attn = k_cache, v_cache
        attn = _cached_attention(config, q, k_attn, v_attn, positions,
                                 cache["k"].shape[2])
        attn = attn.reshape(b, s, config.qkv_dim)
        x_mid = x + proj(attn, lp["wo"], "wo")
        h2 = rms_norm(x_mid, lp["mlp_norm_scale"], config.norm_eps)
        gate = proj(h2, lp["w_gate"], "w_gate")
        up = proj(h2, lp["w_up"], "w_up")
        x = x_mid + proj(jax.nn.silu(gate) * up, lp["w_down"], "w_down")
        new_k.append(k_cache)
        new_v.append(v_cache)

    x = rms_norm(x, params["final_norm_scale"], config.norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embedding"].T
    logits = jnp.einsum("bse,ev->bsv", x, head,
                        preferred_element_type=jnp.float32)
    verified = jnp.argmax(logits, axis=-1).astype(jnp.int32)   # [B, S]
    new_cache = {"k": jnp.stack(new_k), "v": jnp.stack(new_v),
                 "pos": cache["pos"]}
    if new_ks:
        new_cache["k_scale"] = jnp.stack(new_ks)
        new_cache["v_scale"] = jnp.stack(new_vs)
    return verified, new_cache


# distinct `engine` label per instance on the shared gauges/counters
_ENGINE_SEQUENCE = iter(range(1, 1 << 30))


def _percentile(sorted_samples: list, q: float) -> float:
    """Nearest-rank percentile over an already-sorted sample list (the
    shared ``obs.stats.nearest_rank`` helper — one definition for the
    engine rings and the trainer's StepTimer; kept as a module name for
    existing importers, e.g. serving/fleet.py)."""
    return nearest_rank(sorted_samples, q)


@dataclass
class KVHandoff:
    """Prefill→decode KV handoff payload (docs/serving.md "Engine fleet").

    The serialization boundary is the batch=1 admission slot-cache — the
    same pytree ``gather_prefix_pages``/``insert_prompt_pages`` already
    move between the page pool and a slot — held as HOST numpy arrays
    trimmed to the prompt rows, so the payload can cross a process
    boundary as plain arrays. A prefill replica produces one via
    ``submit_prefill()``; a decode replica consumes it via
    ``submit_prefilled()`` and decodes token-identically to the
    single-engine path (greedy).

    Wire format: ``kv`` always carries ``k``/``v`` ``[L, prompt_len,
    Hkv, D]``; on an int8 pool (``kv_dtype == "int8"``) they stay int8
    and the per-vector f32 dequant scales ride alongside as
    ``k_scale``/``v_scale`` ``[L, prompt_len, Hkv]`` — a quantized
    handoff is never densified to the native dtype on either side
    (half the bytes on the wire, and the decode pool imports the exact
    int8 values the prefill pool computed)."""

    prompt: list
    first_token: int
    kv: dict                     # {"k","v"[, "k_scale","v_scale"]}: numpy
    prompt_len: int
    kv_dtype: str = "native"     # "native" | "int8" — the pool dtype the
    #                              payload was exported from
    cached_prefix: int = 0       # prompt tokens served from the prefill
    #                              replica's prefix cache
    sampling: tuple = (0.0, 0, 1.0)
    prefill_s: float = 0.0       # submit→export wall time on the prefill
    #                              replica (chunk scheduling included)
    replica: str = ""            # prefill replica id (fleet bookkeeping)
    adapter: str = ""            # tenant id the KV was computed under —
    #                              the decode replica MUST decode with the
    #                              same adapter (docs/serving.md
    #                              "Multi-tenant LoRA")
    timing: Optional[dict] = None  # prefill-side phase-ledger summary
    #                              (obs/reqledger.py) the fleet merges
    #                              into the request's end-to-end timing
    prewarm: bool = False        # pre-warm replay (serving/podfleet.py):
    #                              the importing engine REGISTERS the
    #                              imported pages in its prefix index so
    #                              the reassigned key's first real
    #                              request is a cache hit (a plain
    #                              decode-pool import never registers —
    #                              that pool serves no prefills)

    def nbytes(self) -> int:
        return int(sum(arr.nbytes for arr in self.kv.values()))


@dataclass
class _Admission:
    """A request claimed off the queue and being prefilled into a slot.

    With chunked prefill the same admission resumes across scheduler
    ticks: ``offset`` is the absolute prefill cursor (it starts at
    ``base`` > 0 on a paged prefix-cache hit, where the cached prefix KV
    was gathered into ``small`` instead of recomputed)."""

    slot: int
    request_id: int
    prompt: list
    max_new: int
    eos_id: Optional[int]
    future: Future
    submitted: float
    sampling: tuple
    expires: Optional[float]
    small: dict = None
    base: int = 0
    offset: int = 0
    chunks: int = 0
    first_token: int = -1
    # trace context captured at submit ((trace_id, parent_span_id)) and
    # the wall clock when the request was claimed off the queue — the
    # scheduler emits the llm.prefill span from these
    trace: Optional[tuple] = None
    claimed: float = 0.0
    # paged-engine bookkeeping (unused by the dense engine)
    page_ids: object = None
    pages: list = field(default_factory=list)
    prefix_nodes: list = field(default_factory=list)
    # prefix-hit kernel path (docs/serving.md "Attention kernels"): the
    # cached prefix was NOT gathered into ``small`` — prefill dispatches
    # attend the shared pages in place through ``prefix_ids`` (full
    # pages_per_slot length, -1 past the prefix) and LSE-merge
    kernel_prefix: bool = False
    prefix_ids: object = None
    # fleet disaggregation (docs/serving.md "Engine fleet"): an export
    # admission resolves its future with a KVHandoff instead of
    # activating a decode slot; a prefilled admission arrived WITH its
    # KV (imported handoff) and skips the prefill dispatch entirely
    export: bool = False
    prefilled: bool = False
    # prewarm import: register the imported pages in the prefix index
    # (see KVHandoff.prewarm)
    register_import: bool = False
    # multi-tenant LoRA: the request's adapter name and its device bank
    # slot (resolved at admission by AdapterRegistry.ensure_loaded)
    adapter: str = ""
    adapter_slot: int = 0
    # monitoring tap (serving/samples.py): first-token top1-top2 logit
    # gap, captured at prefill only while a sample observer is armed
    logit_margin: float = float("nan")
    # per-request phase ledger (obs/reqledger.py): phase transitions
    # sum to the request wall by construction; None when disabled
    ledger: Optional[RequestLedger] = None


@dataclass
class _Slot:
    request_id: int = -1
    tokens: list = field(default_factory=list)
    remaining: int = 0
    eos_id: Optional[int] = None
    future: Optional[Future] = None
    started: float = 0.0
    ttft: float = 0.0
    prompt_len: int = 0
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    # trace context + decode-phase start (wall clock) for the llm.decode
    # span emitted at finish
    trace: Optional[tuple] = None
    decode_started: float = 0.0
    # multi-tenant LoRA: the occupying request's adapter + bank slot
    # (the decode tick gathers per-row factors by adapter_slot)
    adapter: str = ""
    adapter_slot: int = 0
    # monitoring tap: threaded from the admission for the finish sample
    logit_margin: float = float("nan")
    # per-request phase ledger, handed over from the admission; the
    # decode loop flips it decode_active/decode_stall around every tick
    ledger: Optional[RequestLedger] = None

    @property
    def active(self) -> bool:
        return self.request_id >= 0


class ContinuousBatchingEngine:
    """Admission + decode loop over a fixed slot batch.

    ``submit()`` is thread-safe and returns a Future resolving to
    (tokens, stats). All device dispatch happens on the single scheduler
    thread, so the engine serializes TPU access by construction.
    """

    def __init__(self, config: LlamaConfig, params: Params,
                 max_len: int = 2048, slots: int = 4,
                 prefill_buckets: tuple = (128, 512, 1024),
                 seed: int = 0, kv_dtype: str = "native",
                 max_queue_size: int = 0, max_wait: float = 0.0,
                 degradation: dict | None = None,
                 prefill_chunk: int | None = None,
                 latency_window: int | None = None,
                 attention_impl: str | None = None,
                 adapters=None, max_live_adapters: int | None = None,
                 adapter_rate: float | None = None,
                 adapter_burst: float | None = None,
                 request_ledger: bool | None = None,
                 speculative: dict | None = None):
        from ..ops.attention import resolve_prefill_impl
        from .adapters import AdapterRegistry, TenantRateLimiter

        self.config = config
        self.params = params
        self.max_len = max_len
        self.slots = slots
        self.kv_dtype = kv_dtype
        # -- overload protection (docs/serving_resilience.md) --------------
        # max_queue_size: bounded admission queue, reject-newest shedding
        # (0 = unbounded, the pre-resilience behavior)
        # max_wait: per-request queue-time budget in seconds (0 = off) —
        # an overloaded engine fails queued requests fast instead of
        # hanging their futures until result(timeout=300)
        if max_queue_size < 0:
            raise ValueError("max_queue_size must be >= 0")
        if max_wait < 0:
            raise ValueError("max_wait must be >= 0")
        self.max_queue_size = int(max_queue_size)
        self.max_wait = float(max_wait)
        self.degradation = DegradationLadder.from_spec(degradation)
        # -- chunked prefill (docs/serving.md "Prefill & prefix cache") ----
        # at most prefill_chunk prompt tokens run per scheduler tick, so
        # admitting a long prompt never freezes inter-token latency for
        # the slots already decoding; 0 = whole-prompt prefill inline
        llm_defaults = mlconf.serving.llm
        if prefill_chunk is None:
            prefill_chunk = int(llm_defaults.prefill_chunk)
        if prefill_chunk < 0:
            raise ValueError("prefill_chunk must be >= 0")
        self.prefill_chunk = min(int(prefill_chunk), max_len)
        if latency_window is None:
            latency_window = int(llm_defaults.latency_window)
        if latency_window <= 0:
            raise ValueError("latency_window must be > 0")
        # bounded rings behind the p50/p95 TTFT / inter-token-latency
        # percentiles in stats (per-slot ttft alone was discarded)
        self._ttft_ring: deque = deque(maxlen=latency_window)
        self._itl_ring: deque = deque(maxlen=latency_window)
        # decode-dispatch wall time (the attention-dominated device step,
        # admission prefill excluded) behind decode_tick_p50/p95_s
        self._tick_ring: deque = deque(maxlen=latency_window)
        # -- attention kernel dispatch (docs/serving.md "Attention kernels")
        # auto | flash | kernel | reference; prefill resolves to the
        # offset-aware flash kernel or the dense masked softmax. The
        # rowwise decode of THIS engine stays dense (per-row positions);
        # the paged subclass routes decode through the page-table kernel.
        if attention_impl is None:
            attention_impl = str(
                llm_defaults.get("attention_impl", "auto"))
        self.attention_impl = attention_impl
        self.prefill_impl = resolve_prefill_impl(attention_impl)
        # -- multi-tenant LoRA (docs/serving.md "Multi-tenant LoRA") -------
        # named adapters hot-loaded from the artifact store into a
        # device-resident bank; every prefill/decode dispatch gathers
        # per-row (A, B) deltas by bank slot index. None = single-tenant
        # engine, compile-identical to the pre-adapter programs.
        if adapters is None:
            self._adapters = None
            self._owns_adapters = True
        elif isinstance(adapters, AdapterRegistry):
            # shared registry (advanced): engines share one device bank.
            # Registry-level telemetry (mlt_adapter_*, registry stats,
            # per-tenant queue split) is published by NO engine then —
            # the registry's pins/loads are global, and each engine
            # republishing them under its own labels would multiply
            # every federated sum by the engine count.
            self._adapters = adapters
            self._owns_adapters = False
        else:
            self._adapters = AdapterRegistry(config, sources=adapters,
                                             max_live=max_live_adapters)
            self._owns_adapters = True
        adapters_conf = llm_defaults.get("adapters", {})
        if adapter_rate is None:
            adapter_rate = float(adapters_conf.get("rate", 0.0))
        if adapter_burst is None:
            adapter_burst = float(adapters_conf.get("burst", 8.0))
        # per-tenant admission fairness: a token bucket per adapter id in
        # FRONT of the shared queue (0 = off)
        self._tenant_limiter = (
            TenantRateLimiter(adapter_rate, adapter_burst)
            if adapter_rate > 0 else None)
        # adapter label values this engine has emitted series for —
        # removed with the rest of its series on stop()
        self._adapter_labels_seen: set = set()
        # per-request phase ledger (obs/reqledger.py,
        # docs/observability.md "Request attribution"): off = one None
        # check per instrumented site, nothing allocated
        if request_ledger is None:
            from ..obs import ledger_enabled

            request_ledger = ledger_enabled()
        self.request_ledger = bool(request_ledger)
        # injectable for deterministic fake-clock closure tests; every
        # ledger transition reads THIS clock exactly once
        self._ledger_clock = time.perf_counter
        # the admission being prefilled right now (chunked mode resumes it
        # across ticks; only ever touched by the scheduler thread)
        self._admission: Optional[_Admission] = None
        # flipped by the degradation ladder; speculative decoders consult
        # it via their gate (serving/speculative.py)
        self.speculative_enabled = True
        self.prefill_buckets = tuple(
            b for b in sorted(prefill_buckets) if b <= max_len) or (max_len,)

        self._prefill = jax.jit(functools.partial(
            _forward_with_cache, config, attn_impl=self.prefill_impl))
        self._decode = jax.jit(functools.partial(_decode_rowwise, config),
                               donate_argnums=(2,))
        # the sampled variant is the same jit object called with the extra
        # (rng, temperature, top_k, top_p) args — jax.jit specializes per
        # argument structure, so greedy and sampled ticks each get their
        # own cached executable
        self._decode_sampled = self._decode
        self._rng = jax.random.PRNGKey(seed)

        def insert(big_cache, small, slot, pos):
            big_cache = dict(big_cache)
            for name in ("k", "v", "k_scale", "v_scale"):
                if name in big_cache:
                    idx = (0, slot) + (0,) * (big_cache[name].ndim - 2)
                    big_cache[name] = jax.lax.dynamic_update_slice(
                        big_cache[name],
                        small[name].astype(big_cache[name].dtype), idx)
            big_cache["pos"] = big_cache["pos"].at[slot].set(pos)
            return big_cache

        self._insert = jax.jit(insert, donate_argnums=(0,))

        self._cache = self._make_cache()
        self._slot_state = [_Slot() for _ in range(slots)]
        self._queue: queue.Queue = queue.Queue()
        self._running = False
        self._stopped = False
        self._crash_exc: Optional[Exception] = None
        self._thread: Optional[threading.Thread] = None
        # scheduler-epoch guard (docs/observability.md is unrelated; see
        # stop()): each scheduler thread runs one epoch; stop() and the
        # thread race for teardown ownership through these sets under
        # self._lock, so exactly one side fails the in-flight admission
        self._epoch = 0
        self._dead_epochs: set = set()
        self._stale_epochs: set = set()
        # /metrics identity + scrape-time collector handle; ``replica`` is
        # the fleet-assigned label on every mlt_llm_* series ("" for a
        # standalone engine) — set it BEFORE start()/first submit()
        self._obs_name = (f"{type(self).__name__}-"
                          f"{next(_ENGINE_SEQUENCE)}")
        self.replica = ""
        self._metrics_collector = None
        self._next_id = 0
        # RLock: the expiry sweep holds it across drain/re-put while the
        # helpers it calls (stats, budget counter) re-acquire it
        self._lock = threading.RLock()
        # queued requests carrying a max_wait budget; the per-tick expiry
        # sweep is skipped entirely while this is zero
        self._budgeted = 0
        self._stats = {"requests": 0, "completed": 0, "ttft_sum": 0.0,
                       "tokens_out": 0, "shed": 0, "expired": 0,
                       "degraded": 0, "rejected_too_long": 0,
                       "prefill_chunks": 0, "prefill_tokens_tick_max": 0,
                       "handoffs_out": 0, "handoff_bytes_out": 0,
                       "handoffs_in": 0, "handoff_bytes_in": 0,
                       "adapter_rate_limited": 0}
        # -- in-engine speculative decoding (docs/serving.md
        # "Speculative decoding"): draft model resident alongside the
        # target, per-row adaptive k, one multi-token verify dispatch per
        # tick. Off unless a draft model is supplied.
        self._init_speculative(speculative)

    # -- speculative decoding (shared by the dense and paged engines) ----

    def _init_speculative(self, speculative: dict | None):
        conf_node = mlconf.serving.llm.get("speculative")
        conf = dict(conf_node.to_dict()) if conf_node is not None else {}
        draft_config = None
        draft_params = None
        enabled = bool(conf.get("enabled", False))
        if isinstance(speculative, dict):
            draft_config = speculative.get("draft_config")
            draft_params = speculative.get("draft_params")
            conf.update({k: v for k, v in speculative.items()
                         if k not in ("draft_config", "draft_params")})
            enabled = bool(conf.get("enabled", True))
        self.spec_k = max(1, int(conf.get("k", 4) or 4))
        self.spec_min_acceptance = float(conf.get("min_acceptance", 0.35))
        self.spec_window = max(1, int(conf.get("window", 32) or 32))
        self.spec_probe_every = max(1, int(conf.get("probe_every", 16)
                                           or 16))
        self.spec_enabled = bool(enabled and draft_config is not None
                                 and draft_params is not None)
        if not self.spec_enabled:
            return
        if draft_config.vocab_size != self.config.vocab_size:
            raise ValueError("draft and target must share a vocabulary")
        self._spec_draft_config = draft_config
        self._spec_draft_params = draft_params
        # draft KV is always the dense slot layout (tiny model — the page
        # pool exists for the TARGET's HBM footprint, not the draft's)
        self._spec_dcache = init_kv_cache(draft_config, self.slots,
                                          self.max_len)
        # entries BEHIND each slot's last committed token in the draft
        # cache (same invariant as cache['pos'] on the target)
        self._spec_dpos = np.zeros((self.slots,), np.int32)
        # prompt tokens per slot — the draft resync source after plain
        # (non-speculative) ticks advanced the target without the draft
        self._spec_prompts: dict = {}
        self._spec_stale: set = set()
        # per-adapter bounded acceptance window: deque of
        # (proposed, accepted) per verify round, plus probation counters
        self._spec_windows: dict = {}
        self._spec_probe: dict = {}
        # adapters whose draft-bank load failed once — don't retry per tick
        self._spec_draft_block: set = set()
        self._stats.update({"spec_rounds": 0, "spec_proposed": 0,
                            "spec_accepted": 0, "spec_rejected": 0,
                            "spec_tokens": 0, "spec_parked_ticks": 0,
                            "spec_resyncs": 0})
        self._spec_draft_prefill = jax.jit(functools.partial(
            _forward_with_cache, draft_config))
        k_max = self.spec_k

        def draft_steps(params, tokens, cache, lora=None, adapter_ids=None):
            """k_max greedy draft steps over the full slot batch; returns
            ([slots, k_max] proposals, cache)."""
            def body(carry, _):
                tok, c = carry
                nxt, c = _decode_rowwise(draft_config, params, tok, c,
                                         lora=lora, adapter_ids=adapter_ids)
                return (nxt[:, None], c), nxt

            (_, cache), proposals = jax.lax.scan(
                body, (tokens, cache), None, length=k_max)
            return proposals.T, cache

        self._spec_draft_steps = jax.jit(draft_steps, donate_argnums=(2,))
        # engine-specific multi-token verify program, built lazily on the
        # first speculative tick (the paged subclass resolves its kernel
        # impl after this base ctor runs)
        self._spec_verify = None

    def _make_verify_fn(self):
        """Jitted (verified [B,S], new_cache) verify program (hook: the
        paged engine swaps in the page-pool verify)."""
        return jax.jit(functools.partial(_verify_rowwise, self.config),
                       donate_argnums=(2,))

    def _spec_verify_fn(self):
        if self._spec_verify is None:
            self._spec_verify = self._make_verify_fn()
        return self._spec_verify

    def _spec_lora_kwargs(self, adapter_ids) -> dict:
        """Draft-bank LoRA kwargs for the draft dispatches (None when no
        per-tenant draft adapters are attached → base draft model)."""
        draft = (getattr(self._adapters, "draft", None)
                 if self._adapters is not None else None)
        if draft is None or adapter_ids is None:
            return {}
        return {"lora": draft.bank.tensors,
                "adapter_ids": jnp.asarray(adapter_ids)}

    def _spec_slot_draft_ids(self, active):
        """Per-slot DRAFT bank slot ids (0 = base draft model). A tenant
        without a registered draft adapter — or whose draft-load failed —
        drafts with the base model; its verify still runs under the
        tenant's TARGET adapter, so the stream stays the adapter's exact
        greedy output either way (draft quality only buys speed)."""
        draft = (getattr(self._adapters, "draft", None)
                 if self._adapters is not None else None)
        if draft is None:
            return None
        ids = np.zeros((self.slots,), np.int32)
        for i in active:
            adapter = self._slot_state[i].adapter
            if not adapter or adapter in self._spec_draft_block:
                continue
            try:
                ids[i] = draft.ensure_loaded(adapter)
            except Exception as exc:  # noqa: BLE001 - missing/oversubscribed
                # draft adapter degrades to the base draft, never the request
                self._spec_draft_block.add(adapter)
                logger.warning("draft adapter unavailable, using base draft",
                               adapter=adapter, error=str(exc))
        return ids

    def _spec_prefill_slot(self, index: int, tokens_seq, adapter=None):
        """(Re)build one slot's draft KV by prefilling ``tokens_seq``;
        afterwards ``_spec_dpos[index] == len(tokens_seq)`` (the draft's
        next proposal step attends exactly these entries)."""
        total = len(tokens_seq)
        if total <= 0 or total > self.max_len:
            self._spec_dpos[index] = max(0, min(total, self.max_len))
            return
        small = init_kv_cache(self._spec_draft_config, 1, self.max_len)
        pad_len = self._bucket_for(total)
        padded = np.zeros((1, pad_len), np.int32)
        padded[0, :total] = tokens_seq
        draft_ids = None
        draft = (getattr(self._adapters, "draft", None)
                 if self._adapters is not None else None)
        if (draft is not None and adapter
                and adapter not in self._spec_draft_block):
            try:
                draft_ids = np.asarray([draft.ensure_loaded(adapter)],
                                       np.int32)
            except Exception:  # noqa: BLE001 - fall back to base draft
                self._spec_draft_block.add(adapter)
        lora_kw = self._spec_lora_kwargs(draft_ids)
        _, small = self._spec_draft_prefill(
            self._spec_draft_params, jnp.asarray(padded), small, **lora_kw)
        # garbage KV at the padded tail is masked by position until real
        # writes land there (same argument as the target's bucket pad)
        self._spec_dcache = self._insert(self._spec_dcache, small, index,
                                         total)
        self._spec_dpos[index] = total

    def _spec_admit_slot(self, adm: "_Admission"):
        """Draft prefill for a fresh admission. The draft always ingests
        the FULL prompt tokens regardless of how the target prefilled —
        cold, prefix-cache hit, or imported ``KVHandoff`` — because the
        draft has no prefix cache or handoff of its own; that one rule
        keeps all three target paths speculation-ready."""
        self._spec_prompts[adm.slot] = list(adm.prompt)
        self._spec_stale.discard(adm.slot)
        self._spec_prefill_slot(adm.slot, adm.prompt, adm.adapter)

    def _spec_resync_row(self, index: int):
        """Rebuild a stale draft cache row (plain ticks advanced the
        target without the draft): re-prefill prompt + committed tokens
        minus the last. Draft-side only — target output never depends on
        draft KV contents, so a resync can't change the stream."""
        slot = self._slot_state[index]
        stream = list(self._spec_prompts.get(index, ())) + slot.tokens
        if len(stream) > 1:
            self._spec_prefill_slot(index, stream[:-1], slot.adapter)
        else:
            self._spec_dpos[index] = 0
        self._spec_stale.discard(index)
        with self._lock:
            self._stats["spec_resyncs"] += 1

    def _spec_release_slot(self, index: int):
        if not getattr(self, "spec_enabled", False):
            return
        self._spec_prompts.pop(index, None)
        self._spec_stale.discard(index)
        self._spec_dpos[index] = 0

    def _spec_row_k(self, adapter) -> int:
        """Adaptive per-row proposal length from the adapter's bounded
        acceptance window. Cold window → full k (optimistic); paying
        window → k scaled to expected acceptance; under-threshold →
        parked at 0 (plain decode) with a k=1 probe every
        ``spec_probe_every`` consulted rounds so a recovered draft can
        re-earn its budget. Round counters, never wall clock."""
        state = self._spec_windows.get(adapter)
        if state is None:
            state = self._spec_windows[adapter] = deque(
                maxlen=self.spec_window)
        proposed = sum(p for p, _ in state)
        if proposed < 8:
            return self.spec_k
        acc = sum(a for _, a in state) / proposed
        if acc < self.spec_min_acceptance:
            count = self._spec_probe.get(adapter, 0) + 1
            self._spec_probe[adapter] = count
            return 1 if count % self.spec_probe_every == 0 else 0
        self._spec_probe.pop(adapter, None)
        return max(1, min(self.spec_k,
                          int(round(acc * (self.spec_k + 1)))))

    def _spec_feed_window(self, adapter, proposed: int, accepted: int):
        self._spec_windows[adapter].append((proposed, accepted))

    def _spec_tick_viable(self, active) -> bool:
        if not getattr(self, "spec_enabled", False):
            return False
        # fleet-wide park: the degradation ladder's existing flag still
        # gates everything; per-row policy only runs under it
        if not self.speculative_enabled:
            return False
        # mixed greedy/sampled batches tick plain: verify-chunk argmax
        # equivalence is a greedy contract (docs/serving.md)
        return all(self._slot_state[i].temperature == 0.0 for i in active)

    def _spec_apply_positions(self, committed: dict):
        """Commit accepted positions on the target KV (hook: the paged
        engine writes its host-side ``_pos`` instead). Rewinding is the
        whole rollback — rejected entries are overwritten before any
        later query can attend them."""
        pos = np.array(self._cache["pos"])   # copy: device views read-only
        for index, value in committed.items():
            pos[index] = value
        self._cache["pos"] = jnp.asarray(pos)

    def _spec_verify_dispatch(self, chunk, active):
        """ONE multi-token verify forward over every slot (hook: the
        paged engine dispatches the page-pool verify kernel)."""
        lora_kw = (self._lora_kwargs(self._slot_adapter_ids())
                   if self._adapters is not None else {})
        verified, self._cache = self._spec_verify_fn()(
            self.params, jnp.asarray(chunk), self._cache, **lora_kw)
        return np.asarray(verified)

    def _spec_decode_tick(self, active) -> Optional[int]:
        """One speculative scheduler tick: k batched draft steps + ONE
        multi-token verify dispatch, then per-row accept/rollback.
        Returns None to fall through to the plain tick (chaos park, or
        every row's gate parked this round)."""
        from .speculative import accept_tokens

        # chaos: an armed llm.spec_verify fault parks THIS tick to plain
        # decode — never a client error; the stream stays exact-greedy
        # because plain ticks emit the same target argmax
        try:
            fire(FaultPoints.llm_spec_verify, engine=self._obs_name,
                 replica=self.replica, rows=len(active))
        except Exception as exc:  # noqa: BLE001 - any armed error parks
            with self._lock:
                self._stats["spec_parked_ticks"] += 1
            flight_record("engine.spec_park", engine=self._obs_name,
                          replica=self.replica, error=str(exc))
            return None

        k_max = self.spec_k
        k_effs = np.zeros((self.slots,), np.int32)
        any_spec = False
        for i in active:
            slot = self._slot_state[i]
            if slot.remaining < 1:
                continue
            # gate consult BEFORE resync: a parked row's stale draft
            # cache is never read (its chunk lane is k_eff 0, its
            # rollback discards the writes), so rebuilding it every
            # tick would tax exactly the fleets whose drafts don't pay
            k_row = min(self._spec_row_k(slot.adapter), slot.remaining,
                        k_max)
            k_effs[i] = max(0, k_row)
            if k_row > 0:
                any_spec = True
                if i in self._spec_stale:
                    self._spec_resync_row(i)
        if not any_spec:
            return None

        last = np.zeros((self.slots, 1), np.int32)
        for i in active:
            last[i, 0] = self._slot_state[i].tokens[-1]
        self._ledger_mark(active, "decode_active")
        draft_lora_kw = self._spec_lora_kwargs(
            self._spec_slot_draft_ids(active))
        self._spec_dcache["pos"] = jnp.asarray(self._spec_dpos)
        proposals, self._spec_dcache = self._spec_draft_steps(
            self._spec_draft_params, jnp.asarray(last), self._spec_dcache,
            **draft_lora_kw)
        proposals_h = np.asarray(proposals)           # [slots, k_max]
        chunk = np.zeros((self.slots, k_max + 1), np.int32)
        chunk[:, 0] = last[:, 0]
        chunk[:, 1:] = proposals_h
        verified_h = self._spec_verify_dispatch(chunk, active)
        self._ledger_mark(active, "decode_stall")

        finished = []
        committed = {}
        rounds = proposed_total = accepted_total = tokens_total = 0
        for i in active:
            slot = self._slot_state[i]
            k_eff = int(k_effs[i])
            emitted, n_accept = accept_tokens(
                proposals_h[i, :k_eff], verified_h[i], k_eff)
            if k_eff > 0:
                rounds += 1
                proposed_total += k_eff
                accepted_total += n_accept
                self._spec_feed_window(slot.adapter, k_eff, n_accept)
            if slot.eos_id is not None and slot.eos_id in emitted:
                emitted = emitted[:emitted.index(slot.eos_id) + 1]
            emitted = emitted[:max(0, slot.remaining)]
            slot.tokens.extend(int(t) for t in emitted)
            slot.remaining -= len(emitted)
            if k_eff > 0:
                tokens_total += len(emitted)
            pos_i = slot.prompt_len + len(slot.tokens) - 1
            committed[i] = pos_i
            self._spec_dpos[i] = pos_i
            capacity = slot.prompt_len + len(slot.tokens) >= self.max_len
            if ((slot.eos_id is not None and slot.tokens[-1] == slot.eos_id)
                    or slot.remaining <= 0 or capacity):
                finished.append(i)
        self._spec_apply_positions(committed)
        with self._lock:
            self._stats["spec_rounds"] += rounds
            self._stats["spec_proposed"] += proposed_total
            self._stats["spec_accepted"] += accepted_total
            self._stats["spec_rejected"] += proposed_total - accepted_total
            self._stats["spec_tokens"] += tokens_total
        for i in finished:
            self._finish(i)
        return len(active)

    def _make_cache(self):
        """Slot KV storage (hook: the paged engine swaps in a page pool)."""
        return init_kv_cache(self.config, self.slots, self.max_len,
                             kv_dtype=self.kv_dtype)

    def _lora_kwargs(self, slots=None) -> dict:
        """jit kwargs threading the adapter bank + per-row bank-slot
        indices into a dispatch; {} (compile-identical to the
        pre-adapter programs) when no registry is attached. ``slots`` is
        an int (batch=1 admission prefill) or a [slots] array (decode
        tick); default = every row on the base slot 0."""
        if self._adapters is None:
            return {}
        if slots is None:
            ids = np.zeros((self.slots,), np.int32)
        elif isinstance(slots, (int, np.integer)):
            ids = np.full((1,), slots, np.int32)
        else:
            ids = np.asarray(slots, np.int32)
        return {"lora": self._adapters.bank.tensors,
                "adapter_ids": jnp.asarray(ids)}

    def _slot_adapter_ids(self):
        """Per-engine-slot bank indices for the decode dispatch (inactive
        rows decode on the base slot — their outputs are discarded)."""
        return np.fromiter(
            (s.adapter_slot if s.active else 0 for s in self._slot_state),
            np.int32, self.slots)

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        if self._running:
            return
        self._running = True
        self._epoch += 1
        self._register_metrics()
        # device HBM / host RSS exposition while this engine lives
        # (mlt_device_mem_bytes — weakref, shared across owners)
        register_memory_collector(self)
        self._thread = threading.Thread(target=self._loop,
                                        args=(self._epoch,), daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 10.0):
        """Stop the scheduler and DRAIN the queue: every request still
        queued (or mid-generation in a slot) fails promptly with
        :class:`EngineStoppedError` instead of hanging its future until
        its own result() timeout.

        Epoch guard: ``join`` returning does NOT prove the scheduler is
        gone — it can still be wedged in a device dispatch past the
        timeout, and tearing down the in-flight admission here would race
        the live thread (page-table vs free-list divergence, both sides
        resolving one future → InvalidStateError). Teardown ownership is
        decided under the lock: if the scheduler's epoch already
        registered dead, stop() tears down; otherwise the epoch is marked
        stale ("disowned") and the scheduler runs the teardown itself on
        its way out — exactly one side ever does it.
        """
        self._running = False
        self._stopped = True
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=timeout)
        exc = EngineStoppedError(
            "engine stopped while the request was pending")
        epoch = self._epoch
        with self._lock:
            scheduler_live = thread is not None \
                and epoch not in self._dead_epochs
            if scheduler_live:
                self._stale_epochs.add(epoch)
            else:
                self._dead_epochs.discard(epoch)
        if scheduler_live:
            logger.warning(
                "engine stop: scheduler still in a dispatch after join "
                "timeout — queued requests failed now, in-flight "
                "admission/slot teardown deferred to the scheduler",
                timeout=timeout, epoch=epoch)
            self._drain_queue(exc)
        else:
            self._fail_pending(exc)
        self._unregister_metrics()

    def close(self):
        """Alias for :meth:`stop` (context-manager friendly name)."""
        self.stop()

    # -- /metrics collector --------------------------------------------------
    # cumulative stats() keys mirrored as counter series at scrape time
    # NOTE: the adapter_* stats keys are deliberately NOT mirrored here —
    # mlt_adapter_loads_total{outcome} is their one canonical family
    # (publishing them under mlt_llm_events_total too would double-count
    # adapter activity in federated sums)
    _COUNTER_STATS = ("requests", "completed", "tokens_out", "shed",
                      "expired", "degraded", "rejected_too_long",
                      "prefill_chunks", "prefix_queries", "prefix_hits",
                      "prefix_evictions", "prefix_cached_tokens",
                      "handoffs_out", "handoff_bytes_out", "handoffs_in",
                      "handoff_bytes_in")

    def _register_metrics(self):
        """Expose this engine on the process registry: queue-depth /
        free-page-fraction gauges, the cumulative stats counters, and
        the per-tenant adapter series, read at scrape time (weakly
        bound; retired on stop())."""
        if self._metrics_collector is not None:
            return
        import weakref

        ref = weakref.ref(self)
        name = self._obs_name
        replica = self.replica
        # shared mutable set: the engine adds adapter label values as it
        # serves tenants; removal drops exactly the series it created
        adapter_labels = self._adapter_labels_seen
        has_adapters = self._adapters is not None and self._owns_adapters
        # the fairness limiter exists independently of any registry —
        # its shed counter must be visible even on a base-model engine
        has_limiter = self._tenant_limiter is not None
        # speculation telemetry only exists on spec-capable engines; the
        # families are created lazily at first collect and retired here
        has_spec = getattr(self, "spec_enabled", False)

        counter_stats = self._COUNTER_STATS

        def remove_series():
            for adapter in adapter_labels | {""}:
                LLM_QUEUE_DEPTH.remove(engine=name, replica=replica,
                                       adapter=adapter)
            LLM_FREE_PAGE_FRAC.remove(engine=name, replica=replica)
            if has_spec:
                LLM_SPEC_ROUNDS.remove(engine=name, replica=replica)
                for outcome in ("accepted", "rejected"):
                    LLM_SPEC_TOKENS.remove(engine=name, replica=replica,
                                           outcome=outcome)
            for key in counter_stats:
                LLM_EVENTS.remove(engine=name, replica=replica, event=key)
            if has_adapters:
                ADAPTER_LIVE.remove(engine=name, replica=replica)
                for outcome in ("ok", "evict", "error", "capacity",
                                "unknown"):
                    ADAPTER_LOADS.remove(engine=name, replica=replica,
                                         outcome=outcome)
            if has_adapters or has_limiter:
                ADAPTER_LOADS.remove(engine=name, replica=replica,
                                     outcome="rate_limited")
            if replica:
                # fleet replicas own their latency-histogram series too —
                # a scaled-down replica must not pin them; standalone
                # engines (replica "") share one series, never removed
                for adapter in adapter_labels | {""}:
                    for family in (LLM_TTFT, LLM_ITL):
                        family.remove(replica=replica, adapter=adapter)
                LLM_DECODE_TICK.remove(replica=replica)

        def collect():
            engine = ref()
            if engine is None:
                remove_series()
                return False
            stats = engine.stats
            # per-tenant queue depth: every LIVE adapter (resident or
            # active) gets its in-flight queued estimate — explicitly 0
            # when idle, so a drained tenant's gauge can't freeze at its
            # last busy value; "" carries the untenanted remainder, so
            # the sum over adapter values is the engine's total depth
            # (the autoscaler's federated sum stays correct)
            depth = stats.get("queue_depth", 0)
            named = engine._adapter_queue_depths()
            live = engine._live_adapter_labels() | set(named)
            for adapter in live:
                LLM_QUEUE_DEPTH.set(named.get(adapter, 0), engine=name,
                                    replica=replica, adapter=adapter)
            LLM_QUEUE_DEPTH.set(max(0, depth - sum(named.values())),
                                engine=name, replica=replica, adapter="")
            # retire series of tenants that are gone (evicted, idle, no
            # pins): lifetime ``adapter`` label values stay bounded by
            # the resident working set, not by every tenant ever served
            # — a rotating tenant population can't exhaust the families'
            # label-set bounds (fleet replicas retire their TTFT/ITL
            # series too; standalone engines share the replica="" series
            # and leave them)
            stale = adapter_labels - live - {""}
            for adapter in stale:
                LLM_QUEUE_DEPTH.remove(engine=name, replica=replica,
                                       adapter=adapter)
                if replica:
                    for family in (LLM_TTFT, LLM_ITL):
                        family.remove(replica=replica, adapter=adapter)
            adapter_labels.difference_update(stale)
            adapter_labels.update(live)
            frac = engine._free_page_frac()
            if frac is not None:
                LLM_FREE_PAGE_FRAC.set(frac, engine=name, replica=replica)
            for key in engine._COUNTER_STATS:
                if key in stats:
                    LLM_EVENTS.set_total(stats[key], engine=name,
                                         replica=replica, event=key)
            if has_spec:
                LLM_SPEC_ROUNDS.set_total(stats.get("spec_rounds", 0),
                                          engine=name, replica=replica)
                LLM_SPEC_TOKENS.set_total(stats.get("spec_accepted", 0),
                                          engine=name, replica=replica,
                                          outcome="accepted")
                LLM_SPEC_TOKENS.set_total(stats.get("spec_rejected", 0),
                                          engine=name, replica=replica,
                                          outcome="rejected")
            registry = engine._adapters if engine._owns_adapters else None
            if registry is not None:
                ADAPTER_LIVE.set(registry.live(), engine=name,
                                 replica=replica)
                reg_stats = registry.stats
                for outcome, key in (
                        ("ok", "adapter_loads"),
                        ("evict", "adapter_evictions"),
                        ("error", "adapter_load_errors"),
                        ("capacity", "adapter_rejected_capacity"),
                        ("unknown", "adapter_rejected_unknown")):
                    ADAPTER_LOADS.set_total(reg_stats[key], engine=name,
                                            replica=replica,
                                            outcome=outcome)
            if registry is not None or has_limiter:
                ADAPTER_LOADS.set_total(
                    stats.get("adapter_rate_limited", 0), engine=name,
                    replica=replica, outcome="rate_limited")
            return None

        self._metrics_collector = collect
        self._remove_metric_series = remove_series
        REGISTRY.add_collector(collect)

    def _adapter_queue_depths(self) -> dict:
        """{adapter: queued-but-not-active} derived from registry pins
        (one pin per in-flight request) minus rows already decoding —
        consistent on every completion path because pins die with the
        request future."""
        if self._adapters is None or not self._owns_adapters:
            # shared registry: pins are global across engines, so a
            # per-engine split would claim other engines' queued work —
            # the adapter="" series then carries this engine's full depth
            return {}
        pins = self._adapters.pinned_counts()
        if not pins:
            return {}
        active: dict = {}
        for slot in self._slot_state:
            if slot.active and slot.adapter:
                active[slot.adapter] = active.get(slot.adapter, 0) + 1
        adm = self._admission
        if adm is not None and adm.adapter:
            active[adm.adapter] = active.get(adm.adapter, 0) + 1
        return {adapter: max(0, count - active.get(adapter, 0))
                for adapter, count in pins.items()}

    def _live_adapter_labels(self) -> set:
        """Adapter names that should keep metric series right now:
        device residents (pinned or idle-cached) plus anything still
        occupying a slot/admission (belt-and-braces — an active slot's
        adapter is always pinned, hence resident)."""
        if self._adapters is None:
            return set()
        live = set(self._adapters.resident_names()) \
            if self._owns_adapters else set()
        live.update(s.adapter for s in self._slot_state
                    if s.active and s.adapter)
        adm = self._admission
        if adm is not None and adm.adapter:
            live.add(adm.adapter)
        return live

    def _unregister_metrics(self):
        """Drop the collector AND every labeled series this engine owns —
        a process churning engines (redeploys) must not pin dead series
        until the family's cardinality bound starts dropping live ones."""
        collector, self._metrics_collector = self._metrics_collector, None
        if collector is not None:
            REGISTRY.remove_collector(collector)
            self._remove_metric_series()

    def warmup(self):
        """Compile prefill buckets, decode step, and insertion."""
        started = time.perf_counter()
        # with a registry attached, warm the adapter-aware program
        # structure (bank on the base slot) — the serving-time dispatch
        # shape regardless of which tenant lands first
        prefill_kw = self._lora_kwargs(0)
        decode_kw = self._lora_kwargs()
        for bucket in self.prefill_buckets:
            small = init_kv_cache(self.config, 1, self.max_len,
                                  kv_dtype=self.kv_dtype)
            tokens = jnp.zeros((1, bucket), jnp.int32)
            _, small = self._prefill(self.params, tokens, small,
                                     **prefill_kw)
            # the last-token replay used for non-bucket prompt lengths
            _, small = self._prefill(self.params,
                                     jnp.zeros((1, 1), jnp.int32), small,
                                     **prefill_kw)
            self._cache = self._insert(self._cache, small, 0, bucket)
        if self.prefill_chunk and self.prefill_chunk not in \
                self.prefill_buckets:
            # chunked prefill dispatches a fixed (1, chunk) shape
            small = init_kv_cache(self.config, 1, self.max_len,
                                  kv_dtype=self.kv_dtype)
            self._prefill(self.params,
                          jnp.zeros((1, self.prefill_chunk), jnp.int32),
                          small, **prefill_kw)
        step = jnp.zeros((self.slots, 1), jnp.int32)
        tok, self._cache = self._decode(self.params, step, self._cache,
                                        **decode_kw)
        float(jnp.sum(tok))  # host fetch = real sync on the relay
        # compile the sampled variant too (first sampled request must not
        # pay the compile)
        tok, self._cache = self._decode_sampled(
            self.params, step, self._cache, jax.random.PRNGKey(0),
            jnp.zeros((self.slots,), jnp.float32),
            jnp.zeros((self.slots,), jnp.int32),
            jnp.ones((self.slots,), jnp.float32), **decode_kw)
        float(jnp.sum(tok))
        self._cache["pos"] = jnp.zeros((self.slots,), jnp.int32)
        self._spec_warmup()
        logger.info("continuous batching engine warm",
                    slots=self.slots,
                    buckets=list(self.prefill_buckets),
                    warmup_s=round(time.perf_counter() - started, 2))

    def _spec_warmup(self):
        """Compile the speculative programs — draft prefill buckets, the
        k-step draft scan, and the engine's verify dispatch — so the
        first speculative tick doesn't pay the compiles. Garbage KV the
        warm dispatches write sits behind pos 0 / on the scratch page
        and is overwritten before any read (the bucket-pad argument)."""
        if not getattr(self, "spec_enabled", False):
            return
        ids = self._spec_slot_draft_ids(range(self.slots))
        row_kw = self._spec_lora_kwargs(
            None if ids is None else ids[:1])
        for bucket in self.prefill_buckets:
            small = init_kv_cache(self._spec_draft_config, 1, self.max_len)
            self._spec_draft_prefill(
                self._spec_draft_params, jnp.zeros((1, bucket), jnp.int32),
                small, **row_kw)
        step = jnp.zeros((self.slots, 1), jnp.int32)
        _, self._spec_dcache = self._spec_draft_steps(
            self._spec_draft_params, step, self._spec_dcache,
            **self._spec_lora_kwargs(ids))
        self._spec_dcache["pos"] = jnp.zeros((self.slots,), jnp.int32)
        self._spec_warmup_verify()

    def _spec_warmup_verify(self):
        """Verify-program compile (hook: the paged engine warms its
        page-pool verify against the scratch page instead)."""
        chunk = jnp.zeros((self.slots, self.spec_k + 1), jnp.int32)
        lora_kw = self._lora_kwargs(self._slot_adapter_ids()) \
            if self._adapters is not None else {}
        _, self._cache = self._spec_verify_fn()(
            self.params, chunk, self._cache, **lora_kw)
        self._cache["pos"] = jnp.zeros((self.slots,), jnp.int32)

    # -- API ----------------------------------------------------------------
    def _free_page_frac(self) -> Optional[float]:
        """Paged engines report KV-page headroom; dense engines None."""
        return None

    def _queue_depth(self) -> int:
        return self._queue.qsize()

    def pressure_level(self) -> int:
        """Degradation-ladder level: 0 normal, 1 degraded (speculative
        off + max_new_tokens clamp), 2 shedding (queue full)."""
        depth = self._queue_depth()
        if self.max_queue_size and depth >= self.max_queue_size:
            return 2
        if self.degradation is not None:
            return self.degradation.level(depth, self.max_queue_size,
                                          self._free_page_frac())
        return 0

    def submit(self, prompt_tokens, max_new_tokens: int = 64,
               eos_id: int | None = None, temperature: float = 0.0,
               top_k: int = 0, top_p: float = 1.0,
               max_wait: float | None = None, adapter: str = "",
               request_key=None, _extra=None, _trace=None) -> Future:
        """Thread-safe request submission. ``max_wait`` overrides the
        engine-level queue-time budget for this request. The returned
        future fails FAST — QueueFullError when shedding,
        EngineStoppedError after stop/crash — never silently hangs.

        ``adapter`` names a registry LoRA adapter applied to every
        decode row of this request (docs/serving.md "Multi-tenant
        LoRA"): unknown names fail typed 404, a pinned-full working set
        429, and the per-tenant token bucket sheds a flooding tenant
        429 BEFORE the shared queue.

        ``_extra``/``_trace`` are the fleet's internal channel: ``_extra``
        marks an export ("export") or carries an imported
        :class:`KVHandoff`; ``_trace`` overrides the thread-local span
        capture so a router dispatching from a callback thread still
        parents the engine's llm.* spans on the originating request."""
        from .adapters import AdapterError, UnknownAdapterError

        future: Future = Future()
        if self._stopped and not self._running:
            cause = f": {self._crash_exc}" if self._crash_exc else ""
            future.set_exception(EngineStoppedError(
                f"engine is stopped, not accepting requests{cause}"))
            return future
        prompt_len = len(prompt_tokens)
        if prompt_len + max_new_tokens > self.max_len:
            # 400-class rejection up front — past the largest bucket the
            # prefill path would otherwise pad/truncate undefined
            with self._lock:
                self._stats["rejected_too_long"] += 1
            future.set_exception(PromptTooLongError(
                f"prompt_len {prompt_len} + max_new_tokens "
                f"{max_new_tokens} exceeds max_len {self.max_len}"))
            return future
        adapter = adapter or ""
        # phase ledger from here on: everything submit-side (canary
        # resolution, 404 lookup, the pin) is "admission" time; the
        # limiter check is split out as "rate_limit_wait"
        ledger = RequestLedger(clock=self._ledger_clock) \
            if self.request_ledger else None
        split_tenant = split_side = ""
        if adapter and not isinstance(_extra, KVHandoff):
            # canary/version resolution (serving/canary.py): a tenant id
            # with loop state becomes its effective versioned id HERE,
            # before the prefix cache, the rate limiter and the bank see
            # it — canary traffic is a distinct identity end to end. An
            # imported handoff arrives already resolved (the prefill
            # side decided its side) and must not re-roll the split.
            # ``request_key`` pins the split side across requests (a
            # session id); absent, the prompt tokens decide. Metering
            # happens at admission (_meter_split), not here — shed
            # requests must not skew the split-fraction telemetry.
            router = get_canary_router()
            if router is not None:
                resolved, side = router.resolve(
                    adapter, split_key_for(prompt_tokens, request_key))
                if side:
                    split_tenant, split_side = adapter, side
                adapter = resolved
        if adapter:
            # the 404 check runs BEFORE the limiter: unknown names must
            # never mint rate-limit buckets (an untrusted client would
            # grow them unboundedly) and must fail 404, not 429
            if self._adapters is None:
                future.set_exception(UnknownAdapterError(
                    f"engine has no adapter registry "
                    f"(adapter='{adapter}')"))
                return future
            try:
                self._adapters.check_known(adapter)
            except AdapterError as exc:
                future.set_exception(exc)
                return future
        # per-tenant fairness BEFORE the shared queue: a flooding tenant
        # burns its own bucket, not everyone's queue capacity. The
        # internal prefill→decode hop (an imported KVHandoff) was
        # already charged once at its client-facing prefill admission —
        # charging again would 429 a request whose prefill compute and
        # handoff bytes are already spent.
        if self._tenant_limiter is not None \
                and not isinstance(_extra, KVHandoff):
            if ledger is not None:
                ledger.enter("rate_limit_wait")
            acquired = self._tenant_limiter.try_acquire(adapter)
            if ledger is not None:
                ledger.enter("admission")
            if not acquired:
                from .adapters import AdapterRateLimitError

                with self._lock:
                    self._stats["adapter_rate_limited"] += 1
                future.set_exception(AdapterRateLimitError(
                    f"tenant '{adapter or '<base>'}' is over its "
                    f"admission rate — shed to protect the shared queue"))
                return future
        # the chaos point fires BEFORE the pin: an armed error here must
        # not strand a refcount (the future below is the pin's lifetime
        # authority, and it does not exist as a completion path yet)
        fire(FaultPoints.llm_submit, prompt_len=prompt_len,
             max_new_tokens=max_new_tokens, adapter=adapter)
        if adapter:
            try:
                self._adapters.pin(adapter)
            except AdapterError as exc:
                future.set_exception(exc)
                return future
            # one pin per in-flight request, released on ANY completion
            # path (result, shed, expiry, stop) — the future is the
            # single lifetime authority
            future.add_done_callback(
                lambda _f, a=adapter: self._adapters.unpin(a))
            try:
                self._enqueue(future, prompt_tokens,
                              max_new_tokens, eos_id, temperature,
                              top_k, top_p, max_wait, adapter,
                              _extra, _trace, ledger)
            except Exception as exc:  # noqa: BLE001 - an exception past
                # the pin must complete the future (that runs the unpin
                # callback) instead of leaking a refcount forever
                if not future.done():
                    future.set_exception(exc)
                return future
            self._meter_split(split_tenant, split_side, future)
            return future
        self._enqueue(future, prompt_tokens, max_new_tokens,
                      eos_id, temperature, top_k, top_p, max_wait,
                      adapter, _extra, _trace, ledger)
        self._meter_split(split_tenant, split_side, future)
        return future

    @staticmethod
    def _meter_split(tenant: str, side: str, future: Future):
        """Count one ADMITTED request on the canary split telemetry —
        called after the queue put, so sheds/rejections (whose futures
        already failed) and fleet re-dispatch attempts that never
        enqueued don't skew the canary/(canary+stable) fraction."""
        from ..obs import CANARY_REQUESTS

        if side and (not future.done() or future.exception() is None):
            CANARY_REQUESTS.inc(adapter=tenant, side=side)

    def _enqueue(self, future: Future, prompt_tokens, max_new_tokens,
                 eos_id, temperature, top_k, top_p, max_wait, adapter,
                 _extra, _trace, ledger=None) -> Future:
        """Pressure/degradation checks + the actual queue put (the tail
        of :meth:`submit`, split out so the adapter-pinned path can
        armor it)."""
        level = self.pressure_level()
        if level >= 2:
            with self._lock:
                self._stats["shed"] += 1
            flight_record("engine.shed", engine=self._obs_name,
                          queue_depth=self._queue.qsize(),
                          adapter=adapter)
            future.set_exception(QueueFullError(
                f"engine queue is full (max_queue_size="
                f"{self.max_queue_size}, depth {self._queue.qsize()}) — "
                f"shedding"))
            return future
        if level >= 1:
            # degraded: clamp the token budget and park speculative
            # decoding before we have to start shedding
            if self.degradation is not None:
                max_new_tokens = self.degradation.clamp_max_new(
                    max_new_tokens, level)
            if self.speculative_enabled:
                logger.warning("engine degraded: speculative decoding off",
                               queue_depth=self._queue.qsize())
            self.speculative_enabled = False
            with self._lock:
                self._stats["degraded"] += 1
        else:
            self.speculative_enabled = True
        budget = self.max_wait if max_wait is None else float(max_wait)
        expires = (time.perf_counter() + budget) if budget > 0 else None
        # trace context crosses the thread boundary inside the queue item:
        # the scheduler emits llm.prefill/llm.decode spans parented on the
        # submitting step's span (docs/observability.md)
        if _trace is None:
            current_span = get_tracer().current()
            _trace = ((current_span.trace_id, current_span.span_id)
                      if current_span is not None else None)
        if ledger is not None:
            if _trace is not None:
                ledger.trace_id = _trace[0]
            # submit-side work done; the clock now charges the queue
            ledger.enter("queue_wait")
        # enqueue under the lock: the expiry sweep drains and re-puts the
        # queue atomically, so a racing put must not land mid-sweep and
        # jump ahead of older requests
        with self._lock:
            request_id = self._next_id
            self._next_id += 1
            self._stats["requests"] += 1
            if expires is not None:
                self._budgeted += 1
            self._queue.put((request_id, list(prompt_tokens),
                             max_new_tokens, eos_id, future,
                             time.perf_counter(),
                             (float(temperature), int(top_k), float(top_p)),
                             expires, _trace, _extra, adapter, ledger))
        if not self._running:
            self.start()
        return future

    # -- prefill/decode disaggregation (docs/serving.md "Engine fleet") ------
    def submit_prefill(self, prompt_tokens, eos_id: int | None = None,
                       temperature: float = 0.0, top_k: int = 0,
                       top_p: float = 1.0, max_wait: float | None = None,
                       adapter: str = "", request_key=None,
                       _trace=None) -> Future:
        """Run ONLY the (chunked) prefill for a prompt; the returned future
        resolves to a :class:`KVHandoff` a decode replica can import via
        :meth:`submit_prefilled`. The prompt's KV still lands in this
        engine's prefix cache (paged) under ``adapter``'s root, so hot
        prefixes stay cache-resident — per tenant — on the prefill pool.
        ``max_new_tokens=1`` bounds the paged page reservation to the
        prompt itself."""
        return self.submit(prompt_tokens, max_new_tokens=1, eos_id=eos_id,
                           temperature=temperature, top_k=top_k,
                           top_p=top_p, max_wait=max_wait, adapter=adapter,
                           request_key=request_key,
                           _extra="export", _trace=_trace)

    def submit_prefilled(self, handoff: KVHandoff,
                         max_new_tokens: int = 64,
                         eos_id: int | None = None,
                         max_wait: float | None = None,
                         register_prefix: bool = False,
                         _trace=None) -> Future:
        """Admit an already-prefilled request: the handoff's KV is imported
        into the admission slot-cache and decode starts immediately — no
        prefill dispatch ever runs on this engine, so a decode pool's tick
        cadence is immune to fleet-wide long prompts. The handoff carries
        its adapter id: decode runs under the SAME adapter the KV was
        computed with. ``register_prefix`` is the pre-warm replay path
        (serving/podfleet.py): the imported prompt pages ALSO register in
        this engine's prefix index, so a reassigned hot key's first real
        request after a ring join is a cache hit."""
        expects_scales = self.kv_dtype == "int8"
        wire_dtype = getattr(handoff, "kv_dtype", None) or (
            "int8" if "k_scale" in handoff.kv else "native")
        if wire_dtype != self.kv_dtype or \
                ("k_scale" in handoff.kv) != expects_scales:
            raise ValueError(
                f"KV handoff dtype mismatch: engine kv_dtype="
                f"'{self.kv_dtype}' cannot import a '{wire_dtype}' "
                f"payload — prefill and decode pools must quantize "
                f"alike (docs/serving.md 'Engine fleet')")
        temperature, top_k, top_p = handoff.sampling
        if register_prefix and not handoff.prewarm:
            handoff = dataclass_replace(handoff, prewarm=True)
        return self.submit(handoff.prompt, max_new_tokens=max_new_tokens,
                           eos_id=eos_id, temperature=temperature,
                           top_k=top_k, top_p=top_p, max_wait=max_wait,
                           adapter=handoff.adapter, _extra=handoff,
                           _trace=_trace)

    def _handoff_kv(self, adm: _Admission, rows: int) -> dict:
        """Serialize an export admission's prompt KV to host numpy
        (the :class:`KVHandoff` payload — int8 pools ship int8 values +
        f32 scales, never densified to the native dtype). Hook: the
        paged engine's kernel-prefix path assembles the cached-prefix
        rows straight from its pool pages, since they were never
        gathered into the slot cache."""
        return {name: np.asarray(adm.small[name][:, 0, :rows])
                for name in ("k", "v", "k_scale", "v_scale")
                if name in adm.small}

    def _import_small(self, handoff: KVHandoff) -> dict:
        """Deserialize a handoff into the batch=1 admission cache (the
        inverse of :meth:`_export_admission`'s trim): prompt rows from the
        payload, zeros beyond — decode overwrites position >= prompt_len
        before ever attending over it."""
        shape = (self.config.n_layers, 1, self.max_len,
                 self.config.n_kv_heads, self.config.head_dim)
        dtypes = {"k": self.config.dtype, "v": self.config.dtype}
        if self.kv_dtype == "int8":
            dtypes = {"k": jnp.int8, "v": jnp.int8,
                      "k_scale": jnp.float32, "v_scale": jnp.float32}
        small = {}
        for name, dtype in dtypes.items():
            full_shape = shape if name in ("k", "v") else shape[:-1]
            host = np.zeros(full_shape, dtype)
            payload = handoff.kv.get(name)
            if payload is not None:
                rows = min(payload.shape[1], self.max_len)
                host[:, 0, :rows] = payload[:, :rows]
            small[name] = jnp.asarray(host)
        small["pos"] = jnp.full((1,), handoff.prompt_len, jnp.int32)
        return small

    def _export_admission(self, adm: _Admission):
        """Resolve an export admission's future with the KV handoff and
        free the slot storage immediately — a prefill replica never holds
        a decode slot. The paged engine's `_complete_storage` already
        registered the prompt blocks, so the prefix stays cache-resident
        here for the next request sharing it."""
        if adm.ledger is not None:
            # the slot-cache trim/serialize below is the prefill-side
            # handoff cost; the ledger closes here and rides the payload
            adm.ledger.enter("handoff")
        rows = len(adm.prompt)
        kv = self._handoff_kv(adm, rows)
        prefill_s = time.perf_counter() - adm.submitted
        timing = None
        if adm.ledger is not None:
            timing = adm.ledger.close("handoff")
            export_phases(timing, adapter=adm.adapter)
        if adm.trace is not None:
            # the export admission's llm.prefill span is emitted HERE
            # (not in _finish_admission) so it can carry the closed
            # prefill-hop ledger — the assembled waterfall's ledger view
            # then spans both hops of a disaggregated request
            attrs = {"slot": adm.slot, "prompt_len": len(adm.prompt),
                     "chunks": adm.chunks, "cached_prefix": adm.base,
                     "imported": False, "exported": True,
                     "adapter": adm.adapter, "replica": self.replica}
            if timing is not None:
                attrs["timing"] = timing
            get_tracer().emit("llm.prefill", adm.trace[0], adm.trace[1],
                              start=adm.claimed, attrs=attrs)
        handoff = KVHandoff(
            prompt=list(adm.prompt), first_token=adm.first_token, kv=kv,
            prompt_len=len(adm.prompt), kv_dtype=self.kv_dtype,
            cached_prefix=adm.base, sampling=adm.sampling,
            prefill_s=prefill_s, replica=self.replica,
            adapter=adm.adapter, timing=timing)
        self._release_slot_storage(adm.slot)
        with self._lock:
            self._stats["handoffs_out"] += 1
            self._stats["handoff_bytes_out"] += handoff.nbytes()
            # a prefill replica's TTFT ring IS its prefill latency — the
            # first token ships inside the handoff
            self._ttft_ring.append(prefill_s)
            if adm.adapter:
                self._adapter_labels_seen.add(adm.adapter)
        LLM_TTFT.observe(prefill_s,
                         exemplar=(adm.trace[0] if adm.trace else None),
                         replica=self.replica, adapter=adm.adapter)
        if not adm.future.done():
            adm.future.set_result(handoff)

    def generate(self, prompt_tokens, max_new_tokens: int = 64,
                 eos_id: int | None = None, timeout: float = 300.0,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, adapter: str = "",
                 request_key=None):
        """Synchronous convenience wrapper around submit()."""
        return self.submit(prompt_tokens, max_new_tokens, eos_id,
                           temperature=temperature, top_k=top_k,
                           top_p=top_p, adapter=adapter,
                           request_key=request_key).result(timeout=timeout)

    # -- adapter source lifecycle (docs/continuous_tuning.md) ----------------
    def add_adapter_source(self, name: str, source):
        """Publish a named adapter at runtime (the canary hot-load
        path); requests naming it load through the normal pin/
        ensure_loaded admission flow — no engine restart, no
        recompile."""
        if self._adapters is None:
            raise ValueError(
                "engine has no adapter registry (build it with "
                "adapters=... to hot-load canaries)")
        self._adapters.add_source(name, source)

    def retire_adapter(self, name: str, keep_source: bool = False):
        """Drop an adapter from service (promotion's old-stable evict /
        a rollback's canary teardown); in-flight pins finish first."""
        if self._adapters is not None:
            self._adapters.retire(name, keep_source=keep_source)

    @property
    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
            ttfts = sorted(self._ttft_ring)
            itls = sorted(self._itl_ring)
            ticks = sorted(self._tick_ring)
        if out["completed"]:
            out["ttft_avg_s"] = out["ttft_sum"] / out["completed"]
        if ttfts:
            out["ttft_p50_s"] = _percentile(ttfts, 0.50)
            out["ttft_p95_s"] = _percentile(ttfts, 0.95)
        if itls:
            out["itl_p50_s"] = _percentile(itls, 0.50)
            out["itl_p95_s"] = _percentile(itls, 0.95)
        if ticks:
            out["decode_tick_p50_s"] = _percentile(ticks, 0.50)
            out["decode_tick_p95_s"] = _percentile(ticks, 0.95)
        out["attention_impl"] = self.attention_impl
        out["prefill_impl"] = self.prefill_impl
        out["queue_depth"] = self._queue_depth()
        out["pressure_level"] = self.pressure_level()
        out["speculative_enabled"] = self.speculative_enabled
        if "spec_rounds" in out:
            out["acceptance_rate"] = (
                out["spec_accepted"] / out["spec_proposed"]
                if out["spec_proposed"] else 0.0)
            out["spec_tokens_per_round"] = (
                out["spec_tokens"] / out["spec_rounds"]
                if out["spec_rounds"] else 0.0)
        if self._adapters is not None and self._owns_adapters:
            out.update(self._adapters.stats)
            out["adapter_live"] = self._adapters.live()
            out["adapter_resident"] = self._adapters.resident_names()
        return out

    # -- scheduler ----------------------------------------------------------
    def _bucket_for(self, length: int) -> int:
        for bucket in self.prefill_buckets:
            if length <= bucket:
                return bucket
        return self.max_len

    def _first_token(self, logits, sampling: tuple) -> int:
        """Sample/argmax the first generated token from last-position
        logits (shared by the inline and chunked prefill paths)."""
        temperature, top_k, top_p = sampling
        if temperature > 0:
            from .sampling import sample_logits

            self._rng, sub = jax.random.split(self._rng)
            return int(np.asarray(sample_logits(
                logits, sub, jnp.full((1,), temperature, jnp.float32),
                jnp.full((1,), top_k, jnp.int32),
                jnp.full((1,), top_p, jnp.float32)))[0])
        return int(np.asarray(jnp.argmax(logits, axis=-1))[0])

    def _run_prefill(self, adm: _Admission,
                     limit: int | None = None) -> bool:
        """Advance the admission's prefill by ONE dispatch: up to ``limit``
        prompt tokens (the whole remaining suffix, bucket-padded, when
        limit is None). The cursor starts at ``adm.base`` — on a paged
        prefix-cache hit the cached prefix KV is already in ``adm.small``
        and only the suffix runs. Returns True once the prompt is fully
        prefilled and the first token is sampled."""
        if adm.ledger is not None and \
                adm.ledger.current_phase != "prefill":
            # first chunk dispatch: the request is in prefill from here
            # to the first token — decode ticks interleaved between
            # chunks included, that IS this request's prefill latency
            adm.ledger.enter("prefill")
        fire(FaultPoints.llm_prefill, request_id=adm.request_id,
             slot=adm.slot, offset=adm.offset, chunks=adm.chunks)
        prompt = adm.prompt
        total = len(prompt)
        start = adm.offset
        remaining = total - start
        cap = self.max_len - start
        if limit is None:
            # prefer a warmed bucket shape that still fits the cache tail
            # (start > 0 after a prefix hit can rule the usual bucket
            # out); the cap fallback compiles once per distinct tail
            pad_len = next(
                (b for b in self.prefill_buckets if remaining <= b <= cap),
                min(self._bucket_for(remaining), cap))
        else:
            pad_len = min(limit, cap)
        take = min(remaining, pad_len)
        padded = np.zeros((1, pad_len), np.int32)
        padded[0, :take] = prompt[start:start + take]
        adm.small["pos"] = jnp.full((1,), start, jnp.int32)
        lora_kw = self._lora_kwargs(adm.adapter_slot)
        logits, adm.small = self._prefill_dispatch(
            adm, jnp.asarray(padded), lora_kw)
        adm.offset += take
        adm.chunks += 1
        with self._lock:
            self._stats["prefill_chunks"] += 1
            # tick instrumentation: the most prefill compute any single
            # scheduler iteration absorbed (tests assert <= prefill_chunk)
            if take > self._stats["prefill_tokens_tick_max"]:
                self._stats["prefill_tokens_tick_max"] = take
        if adm.offset < total:
            return False
        if take != pad_len:
            # padding advanced pos past the prompt; replay the last real
            # token for its logits (same trick as LLMEngine.generate)
            adm.small["pos"] = jnp.full((1,), total - 1, jnp.int32)
            logits, adm.small = self._prefill_dispatch(
                adm, jnp.asarray([[prompt[-1]]], dtype=jnp.int32),
                lora_kw)
        if sampling_enabled():
            # monitoring tap: first-token top1-top2 logit gap (a cheap
            # model-confidence proxy for the drift analyzer's "logit
            # statistics"). Only while an observer is armed — the host
            # transfer of one logits row is not paid when dark.
            row = np.asarray(logits).reshape(-1)
            if row.size >= 2:
                top2 = np.partition(row, -2)[-2:]
                adm.logit_margin = float(top2[1] - top2[0])
        adm.first_token = self._first_token(logits, adm.sampling)
        return True

    def _prefill_dispatch(self, adm: _Admission, tokens, lora_kw):
        """One prefill device dispatch for an admission (chunk or the
        last-token replay). Hook: the paged engine routes prefix-hit
        admissions through the merged paged-prefill kernel so the cached
        prefix is attended in place instead of gathered."""
        return self._prefill(self.params, tokens, adm.small, **lora_kw)

    def _activate_slot(self, free: int, request_id: int, first_token: int,
                       max_new: int, eos_id, future, submitted: float,
                       prompt_len: int, sampling: tuple,
                       trace: tuple | None = None, adapter: str = "",
                       adapter_slot: int = 0,
                       logit_margin: float = float("nan"),
                       ledger: RequestLedger | None = None):
        """Fill slot bookkeeping after a successful prefill (shared by the
        dense and paged admission paths)."""
        temperature, top_k, top_p = sampling
        slot = self._slot_state[free]
        slot.request_id = request_id
        slot.tokens = [first_token]
        slot.remaining = max_new - 1
        slot.eos_id = eos_id
        slot.future = future
        slot.started = submitted
        slot.ttft = time.perf_counter() - submitted
        slot.prompt_len = prompt_len
        slot.temperature = temperature
        slot.top_k = top_k
        slot.top_p = top_p
        slot.trace = trace
        slot.adapter = adapter
        slot.adapter_slot = adapter_slot
        slot.logit_margin = logit_margin
        slot.ledger = ledger
        slot.decode_started = time.time()
        if ledger is not None:
            # the row now waits for its first decode dispatch; every
            # tick flips decode_active around the device step
            ledger.enter("decode_stall")
        with self._lock:
            self._ttft_ring.append(slot.ttft)
            if adapter:
                self._adapter_labels_seen.add(adapter)
        LLM_TTFT.observe(slot.ttft,
                         exemplar=(trace[0] if trace else None),
                         replica=self.replica, adapter=adapter)
        if (eos_id is not None and first_token == eos_id) or \
                slot.remaining <= 0:
            self._finish(free)

    # -- admission -----------------------------------------------------------
    def _validate_item(self, item) -> bool:
        """Expiry + capacity checks on a dequeued request. Returns False
        (consuming the item) when its future was already failed."""
        (_, prompt, max_new, _, future, submitted, _, expires) = item[:8]
        if self._request_expired(future, submitted, expires):
            return False
        if len(prompt) + max_new > self.max_len:
            # backstop for requests enqueued before a config change —
            # submit() already rejects these up front
            future.set_exception(PromptTooLongError(
                f"prompt_len {len(prompt)} + max_new_tokens {max_new} "
                f"exceeds max_len {self.max_len}"))
            return False
        return True

    def _prepare_admission(self) -> Optional[_Admission]:
        """Claim a free slot + the next valid queued request; build the
        admission (batch=1 prefill cache, cursor at 0). The paged engine
        overrides this with page reservation + prefix matching."""
        free = next((i for i, s in enumerate(self._slot_state)
                     if not s.active), None)
        if free is None:
            return None
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                return None
            self._consume_budget(item[7])
            if not self._validate_item(item):
                continue
            (request_id, prompt, max_new, eos_id, future, submitted,
             sampling, expires) = item[:8]
            extra = item[9] if len(item) > 9 else None
            adapter = item[10] if len(item) > 10 else ""
            ledger = item[11] if len(item) > 11 else None
            if ledger is not None:
                # claimed off the queue: queue_wait closes here
                ledger.enter("adapter_load_wait" if adapter
                             else "admission")
            adapter_slot = self._resolve_adapter(adapter, future)
            if ledger is not None and adapter:
                ledger.enter("admission")
            if adapter_slot is None:
                continue  # adapter load failed — request failed typed
            try:
                adm = _Admission(
                    slot=free, request_id=request_id, prompt=prompt,
                    max_new=max_new, eos_id=eos_id, future=future,
                    submitted=submitted, sampling=sampling,
                    expires=expires, trace=item[8], claimed=time.time(),
                    adapter=adapter, adapter_slot=adapter_slot,
                    ledger=ledger)
                self._apply_directive(adm, extra)
                if adm.small is None:
                    adm.small = init_kv_cache(self.config, 1, self.max_len,
                                              kv_dtype=self.kv_dtype)
                return adm
            except Exception as exc:
                # dequeued but not yet tracked in self._admission — fail
                # the future before the scheduler dies or it would hang
                # outside every container _fail_pending drains
                if not future.done():
                    future.set_exception(exc)
                raise

    def _resolve_adapter(self, adapter: str, future: Future):
        """Materialize the request's adapter in the device bank (on the
        scheduler thread — the single device owner). Returns the bank
        slot, or None after failing the request's future: a corrupt or
        unreachable adapter artifact fails ONE request typed, never the
        engine."""
        if not adapter:
            return 0
        try:
            return self._adapters.ensure_loaded(adapter)
        except Exception as exc:  # noqa: BLE001 - per-request failure
            logger.warning("adapter load failed", adapter=adapter,
                           error=str(exc))
            if not future.done():
                future.set_exception(exc)
            return None

    def _apply_directive(self, adm: _Admission, extra):
        """Fold the fleet directive (item[9]) into a fresh admission:
        "export" flags a prefill-only request; a KVHandoff means the
        prefill already happened on another replica — import its KV and
        skip straight to slot activation."""
        if extra == "export":
            adm.export = True
        elif isinstance(extra, KVHandoff):
            if adm.ledger is not None:
                # deserialize + storage completion are the decode-side
                # handoff cost (the prefill side closed its own ledger
                # into "handoff" at export)
                adm.ledger.enter("handoff")
            adm.small = self._import_small(extra)
            adm.offset = len(adm.prompt)
            adm.first_token = extra.first_token
            adm.prefilled = True
            adm.register_import = bool(getattr(extra, "prewarm", False))
            with self._lock:
                self._stats["handoffs_in"] += 1
                self._stats["handoff_bytes_in"] += extra.nbytes()

    def _complete_storage(self, adm: _Admission):
        """Move the prefilled batch=1 cache into slot storage (the paged
        engine scatters into its page pool instead)."""
        self._cache = self._insert(self._cache, adm.small, adm.slot,
                                   len(adm.prompt))

    def _finish_admission(self, adm: _Admission):
        self._complete_storage(adm)
        if adm.ledger is not None:
            adm.ledger.note("prefill_chunks", adm.chunks)
            if adm.base:
                adm.ledger.note("cached_prefix", adm.base)
        if adm.trace is not None and not adm.export:
            # the prefill scheduler phase as a span under the submitting
            # step — chunk count, cached-prefix length and the serving
            # replica ride as attrs (imported=True marks a KV-handoff
            # import: no prefill ran); the replica attr is what lets a
            # /debug/trace waterfall tell the fleet hops apart. Export
            # admissions emit theirs in _export_admission instead, so
            # the span can carry the closed prefill-hop ledger.
            get_tracer().emit(
                "llm.prefill", adm.trace[0], adm.trace[1],
                start=adm.claimed, attrs={
                    "slot": adm.slot, "prompt_len": len(adm.prompt),
                    "chunks": adm.chunks, "cached_prefix": adm.base,
                    "imported": adm.prefilled, "exported": False,
                    "adapter": adm.adapter, "replica": self.replica})
        # scheduler decision on the flight ring: one admission completed
        # (prompt length, reused prefix, chunking — the inputs to every
        # later latency question a post-mortem asks)
        flight_record("engine.admit", engine=self._obs_name,
                      request_id=adm.request_id,
                      prompt_len=len(adm.prompt), cached_prefix=adm.base,
                      chunks=adm.chunks, slot=adm.slot,
                      adapter=adm.adapter, export=bool(adm.export))
        if adm.export:
            self._export_admission(adm)
            return
        if getattr(self, "spec_enabled", False):
            self._spec_admit_slot(adm)
        self._activate_slot(adm.slot, adm.request_id, adm.first_token,
                            adm.max_new, adm.eos_id, adm.future,
                            adm.submitted, len(adm.prompt), adm.sampling,
                            trace=adm.trace, adapter=adm.adapter,
                            adapter_slot=adm.adapter_slot,
                            logit_margin=adm.logit_margin,
                            ledger=adm.ledger)

    def _abort_admission(self, adm: _Admission):
        """Release admission-held storage (expiry mid-prefill, stop). The
        dense engine's batch=1 cache just drops; the paged engine returns
        pages and prefix refs."""

    def _admit_one(self) -> bool:
        """Prefill one queued request into a free slot (returns True if a
        request was admitted). The admission is tracked in
        ``self._admission`` while prefill runs so a scheduler crash
        mid-prefill still fails its future (and frees its storage) via
        ``_fail_pending``."""
        adm = self._prepare_admission()
        if adm is None:
            return False
        self._admission = adm
        if not adm.prefilled:
            self._run_prefill(adm, limit=None)
        self._finish_admission(adm)
        self._admission = None
        return True

    def _admission_tick(self):
        """Admission work for one scheduler iteration. With chunked
        prefill at most ONE <= prefill_chunk dispatch runs per tick, so
        slots already decoding keep making progress while a long prompt
        prefills; otherwise admit whole prompts until slots or queue run
        out (the pre-chunking behavior)."""
        if not self.prefill_chunk:
            admitted = True
            while admitted:
                admitted = self._admit_one()
            return
        adm = self._admission
        if adm is None:
            adm = self._prepare_admission()
            if adm is None:
                return
            self._admission = adm
        # no expiry check here: max_wait is a QUEUE-time budget, spent the
        # moment the request was dequeued in _prepare_admission — a
        # mid-prefill admission is being served, not waiting (the
        # unchunked path behaves the same)
        if adm.prefilled or self._run_prefill(adm, limit=self.prefill_chunk):
            self._finish_admission(adm)
            self._admission = None

    def _ledger_mark(self, active: list, phase: str):
        """Flip every active slot's ledger into ``phase`` (the
        decode_active/decode_stall split around each device dispatch —
        transition-based, so the split still sums to wall exactly)."""
        for i in active:
            ledger = self._slot_state[i].ledger
            if ledger is not None:
                ledger.enter(phase)

    def _finish(self, index: int):
        slot = self._slot_state[index]
        stats = {
            "ttft_s": slot.ttft,
            "generated": len(slot.tokens),
            "prompt_len": slot.prompt_len,
            "total_s": time.perf_counter() - slot.started,
        }
        timing = None
        if slot.ledger is not None:
            timing = slot.ledger.close()
            stats["timing"] = timing
            export_phases(timing, adapter=slot.adapter)
        with self._lock:
            self._stats["completed"] += 1
            self._stats["ttft_sum"] += slot.ttft
            self._stats["tokens_out"] += len(slot.tokens)
        if slot.trace is not None:
            # the ledger rides the decode span so an assembled
            # /debug/trace waterfall can reconcile its critical path
            # against the request's own attribution (obs/traceview.py)
            attrs = {"slot": index, "generated": len(slot.tokens),
                     "replica": self.replica}
            if timing is not None:
                attrs["timing"] = timing
            get_tracer().emit(
                "llm.decode", slot.trace[0], slot.trace[1],
                start=slot.decode_started, attrs=attrs)
        if sampling_enabled():
            # monitoring tap (docs/continuous_tuning.md): one bounded
            # per-completion sample for the drift analyzer — output
            # token ids, lengths, latency, first-token logit margin
            emit_sample(adapter=slot.adapter, tokens=list(slot.tokens),
                        prompt_len=slot.prompt_len,
                        generated=len(slot.tokens), ttft_s=slot.ttft,
                        total_s=stats["total_s"],
                        logit_margin=slot.logit_margin,
                        engine=self._obs_name, replica=self.replica)
        future, tokens = slot.future, slot.tokens
        self._slot_state[index] = _Slot()
        self._release_slot_storage(index)
        if future is not None and not future.done():
            future.set_result((tokens, stats))

    def _release_slot_storage(self, index: int):
        # zero the freed row's position so decode writes land in its own
        # (now unused) region
        self._cache["pos"] = self._cache["pos"].at[index].set(0)
        self._spec_release_slot(index)

    def _decode_tick(self) -> int:
        active = [i for i, s in enumerate(self._slot_state) if s.active]
        if not active:
            return 0
        if self._spec_tick_viable(active):
            done = self._spec_decode_tick(active)
            if done is not None:
                return done
        if getattr(self, "spec_enabled", False):
            # a plain tick advances the target without the draft: those
            # rows' draft caches go stale and resync on the next spec tick
            self._spec_stale.update(active)
        return self._plain_decode_tick(active)

    def _plain_decode_tick(self, active) -> int:
        last = np.zeros((self.slots, 1), np.int32)
        for i in active:
            last[i, 0] = self._slot_state[i].tokens[-1]
        lora_kw = self._lora_kwargs(self._slot_adapter_ids()) \
            if self._adapters is not None else {}
        self._ledger_mark(active, "decode_active")
        if any(self._slot_state[i].temperature > 0 for i in active):
            temp = np.zeros((self.slots,), np.float32)
            top_k = np.zeros((self.slots,), np.int32)
            top_p = np.ones((self.slots,), np.float32)
            for i in active:
                slot = self._slot_state[i]
                temp[i] = slot.temperature
                top_k[i] = slot.top_k
                top_p[i] = slot.top_p
            self._rng, sub = jax.random.split(self._rng)
            next_token, self._cache = self._decode_sampled(
                self.params, jnp.asarray(last), self._cache, sub,
                jnp.asarray(temp), jnp.asarray(top_k), jnp.asarray(top_p),
                **lora_kw)
        else:
            next_token, self._cache = self._decode(
                self.params, jnp.asarray(last), self._cache, **lora_kw)
        tokens_host = np.asarray(next_token)
        self._ledger_mark(active, "decode_stall")
        for i in active:
            slot = self._slot_state[i]
            token = int(tokens_host[i])
            slot.tokens.append(token)
            slot.remaining -= 1
            capacity = slot.prompt_len + len(slot.tokens) >= self.max_len
            if (slot.eos_id is not None and token == slot.eos_id) or \
                    slot.remaining <= 0 or capacity:
                self._finish(i)
        return len(active)

    def _consume_budget(self, expires: float | None):
        """A budgeted item left the admission queue for good."""
        if expires is not None:
            with self._lock:
                self._budgeted = max(0, self._budgeted - 1)

    def _request_expired(self, future: Future, submitted: float,
                         expires: float | None) -> bool:
        """Fail a request whose queue-time budget is spent (fast 504-class
        failure instead of a future hanging for result(timeout=300))."""
        if expires is None or time.perf_counter() < expires:
            return False
        waited = time.perf_counter() - submitted
        with self._lock:
            self._stats["expired"] += 1
        future.set_exception(DeadlineExceeded(
            f"request spent {waited:.2f}s queued, over its max_wait "
            f"budget — engine overloaded"))
        return True

    def _expire_queued(self):
        """Sweep the admission queue for requests past their queue-time
        budget. Runs every scheduler iteration, so even when every slot is
        busy with long generations the queued requests still fail within
        one decode tick of their budget. Free when no queued request
        carries a budget (the default), and atomic vs submit() so the
        drain/re-put can never reorder a racing newcomer ahead of older
        requests."""
        if self._budgeted <= 0 or self._queue.empty():
            return
        with self._lock:
            keep = []
            while True:
                try:
                    item = self._queue.get_nowait()
                except queue.Empty:
                    break
                if self._request_expired(item[4], item[5], item[7]):
                    self._consume_budget(item[7])
                else:
                    keep.append(item)
            for item in keep:  # FIFO order preserved
                self._queue.put(item)

    def _control_tick(self):
        """Scheduler-thread hook for out-of-band control work that must
        not race device dispatch (the paged engine drains its
        fetch_prefix/import_prefix control deque here — its page pool is
        donated through every decode dispatch, so off-thread access is
        unsafe by construction; docs/serving.md "Hierarchical KV").
        Base engine: nothing."""

    def _loop(self, epoch: int = 0):
        try:
            while self._running:
                # the ITL sample spans the WHOLE iteration (admission
                # prefill included): an unchunked long-prompt prefill
                # between two decode ticks IS the inter-token gap clients
                # see, and the percentiles must show it
                started = time.perf_counter()
                # on-demand profiling (POST /debug/profile): claims or
                # advances an armed capture — one global check when dark
                profiler_tick(self._obs_name)
                # fail-slow injection seam: an armed delay() narrowed to
                # one replica stretches every scheduler iteration there —
                # TTFT and ITL rise, nothing ever errors
                fire(FaultPoints.fleet_degrade, replica=self.replica,
                     engine=self._obs_name)
                self._expire_queued()
                self._control_tick()
                self._admission_tick()
                if not any(s.active for s in self._slot_state):
                    if self._admission is None:
                        time.sleep(0.002)  # idle: poll admissions at 2ms
                    continue
                t_tick = time.perf_counter()
                # per-tenant ITL: one observation per adapter active in
                # the tick (captured BEFORE the tick — finished rows are
                # reset inside it)
                tick_adapters = {s.adapter for s in self._slot_state
                                 if s.active}
                if self._decode_tick():
                    now = time.perf_counter()
                    elapsed = now - started
                    tick_s = now - t_tick
                    with self._lock:
                        self._itl_ring.append(elapsed)
                        # decode dispatch alone (admission prefill
                        # excluded): the per-tick attention cost the
                        # kernel work targets
                        self._tick_ring.append(tick_s)
                        self._adapter_labels_seen.update(
                            a for a in tick_adapters if a)
                    for tick_adapter in tick_adapters:
                        LLM_ITL.observe(elapsed, replica=self.replica,
                                        adapter=tick_adapter)
                    LLM_DECODE_TICK.observe(tick_s, replica=self.replica)
        except Exception as exc:  # noqa: BLE001 - a dead scheduler must
            # fail pending work loudly, not leave futures hanging forever
            logger.error("continuous batching scheduler died",
                         error=str(exc))
            flight_record("engine.crash", engine=self._obs_name,
                          replica=self.replica, error=str(exc),
                          error_type=type(exc).__name__)
            self._running = False
            self._stopped = True
            self._crash_exc = exc
            self._fail_pending(exc)
        finally:
            # epoch-guard handshake with stop(): register this epoch dead
            # and, if stop() already disowned teardown to us (its join
            # timed out while this thread was wedged in a dispatch), run
            # the teardown here — we are the only thread that may touch
            # the in-flight admission/slot state (_fail_pending is
            # idempotent, so the crash path above is safe to follow)
            with self._lock:
                self._dead_epochs.add(epoch)
                disowned = epoch in self._stale_epochs
                self._stale_epochs.discard(epoch)
            if disowned:
                self._fail_pending(EngineStoppedError(
                    "engine stopped while the request was pending"))

    def _drain_queue(self, exc: Exception):
        """Fail every request still in the (thread-safe) admission queue.
        Safe from any thread — each item is popped exactly once."""
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            future = item[4]
            if not future.done():
                future.set_exception(exc)

    def _fail_pending(self, exc: Exception):
        failed = int(self._admission is not None) \
            + sum(1 for s in self._slot_state
                  if s.active and s.future is not None
                  and not s.future.done()) + self._queue.qsize()
        flight_record("engine.fail_pending", engine=self._obs_name,
                      replica=self.replica, failed=failed,
                      error_type=type(exc).__name__)
        if not isinstance(exc, EngineStoppedError):
            # a crash teardown (scheduler death, not a clean stop) is a
            # post-mortem moment: the decision sequence into it — chaos
            # fires, admissions, breaker trips — is the debugging record
            get_flight_recorder().dump(
                "engine-crash", extra={"engine": self._obs_name,
                                       "error": str(exc)})
        adm, self._admission = self._admission, None
        if adm is not None:
            # a request parked mid-chunked-prefill fails with everything
            # else on stop/crash (and returns its storage)
            if not adm.future.done():
                adm.future.set_exception(exc)
            self._abort_admission(adm)
        with self._lock:
            self._budgeted = 0
        for i, slot in enumerate(self._slot_state):
            if not slot.active:
                continue
            if slot.future is not None and not slot.future.done():
                slot.future.set_exception(exc)
            self._slot_state[i] = _Slot()
            # return slot storage (paged: pages back to the free list,
            # prefix holds released) so teardown leaves the free list and
            # page table consistent; guarded — a crash mid-decode can
            # leave the dense cache donated, and storage cleanup must
            # never stop the remaining futures from failing
            try:
                self._release_slot_storage(i)
            except Exception:  # noqa: BLE001
                pass
        self._drain_queue(exc)
