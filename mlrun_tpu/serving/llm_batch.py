"""Continuous batching for the TPU LLM engine.

Slot-based scheduler over a fixed-size decode batch (the vLLM-style design,
TPU-shaped): the KV cache is a static [layers, slots, max_len, heads, dim]
allocation so every decode dispatch is ONE compiled program regardless of
which requests occupy the slots. Requests are admitted into free slots by a
bucketed batch=1 prefill whose kv rows are inserted into the big cache with
`dynamic_update_slice`; decode then advances every active slot one token per
step with per-row positions (per-row RoPE tables + scatter cache writes).
Finished rows free their slot for the next queued request — no
head-of-line blocking on long generations.

The reference has no inference engine at all (its V2ModelServer calls user
predict(), mlrun/serving/v2_serving.py); this is the TPU-native capability
behind the <200ms p50 TTFT target under concurrency (BASELINE.md).
"""

from __future__ import annotations

import functools
import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.llama import LlamaConfig, Params
from ..ops.norms import rms_norm
from ..ops.rotary import apply_rope, rope_table
from ..utils import logger
from .llm import _cached_attention, _forward_with_cache, init_kv_cache


def _decode_rowwise(config: LlamaConfig, params: Params, tokens: jax.Array,
                    cache: dict):
    """One decode token per row with PER-ROW positions (slots at different
    generation depths). tokens: [B, 1]; cache rows advance independently."""
    b = tokens.shape[0]
    start = cache["pos"]                      # [B]
    positions = start[:, None]                # [B, 1]
    rows = jnp.arange(b)
    x = params["embedding"][tokens].astype(config.dtype)
    cos, sin = rope_table(positions, config.head_dim, config.rope_theta)

    new_k, new_v = [], []
    for layer in range(config.n_layers):
        lp = jax.tree_util.tree_map(lambda a: a[layer], params["layers"])
        h = rms_norm(x, lp["attn_norm_scale"], config.norm_eps)

        def proj(h_in, w):
            return jnp.einsum("bse,eh->bsh", h_in, w,
                              preferred_element_type=jnp.float32
                              ).astype(x.dtype)

        q = proj(h, lp["wq"]).reshape(b, 1, config.n_heads, config.head_dim)
        k = proj(h, lp["wk"]).reshape(b, 1, config.n_kv_heads,
                                      config.head_dim)
        v = proj(h, lp["wv"]).reshape(b, 1, config.n_kv_heads,
                                      config.head_dim)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        # per-row scatter at each row's own position
        k_cache = cache["k"][layer].at[rows, start].set(
            k[:, 0].astype(cache["k"].dtype))
        v_cache = cache["v"][layer].at[rows, start].set(
            v[:, 0].astype(cache["v"].dtype))
        attn = _cached_attention(config, q, k_cache, v_cache, positions,
                                 cache["k"].shape[2])
        attn = attn.reshape(b, 1, config.qkv_dim)
        x_mid = x + proj(attn, lp["wo"])
        h2 = rms_norm(x_mid, lp["mlp_norm_scale"], config.norm_eps)
        gate = proj(h2, lp["w_gate"])
        up = proj(h2, lp["w_up"])
        x = x_mid + proj(jax.nn.silu(gate) * up, lp["w_down"])
        new_k.append(k_cache)
        new_v.append(v_cache)

    x = rms_norm(x, params["final_norm_scale"], config.norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embedding"].T
    logits = jnp.einsum("bse,ev->bsv", x, head,
                        preferred_element_type=jnp.float32)[:, 0]
    next_token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    new_cache = {"k": jnp.stack(new_k), "v": jnp.stack(new_v),
                 "pos": cache["pos"] + 1}
    return next_token, new_cache


@dataclass
class _Slot:
    request_id: int = -1
    tokens: list = field(default_factory=list)
    remaining: int = 0
    eos_id: Optional[int] = None
    future: Optional[Future] = None
    started: float = 0.0
    ttft: float = 0.0
    prompt_len: int = 0

    @property
    def active(self) -> bool:
        return self.request_id >= 0


class ContinuousBatchingEngine:
    """Admission + decode loop over a fixed slot batch.

    ``submit()`` is thread-safe and returns a Future resolving to
    (tokens, stats). All device dispatch happens on the single scheduler
    thread, so the engine serializes TPU access by construction.
    """

    def __init__(self, config: LlamaConfig, params: Params,
                 max_len: int = 2048, slots: int = 4,
                 prefill_buckets: tuple = (128, 512, 1024)):
        self.config = config
        self.params = params
        self.max_len = max_len
        self.slots = slots
        self.prefill_buckets = tuple(
            b for b in sorted(prefill_buckets) if b <= max_len) or (max_len,)

        self._prefill = jax.jit(functools.partial(_forward_with_cache,
                                                  config))
        self._decode = jax.jit(functools.partial(_decode_rowwise, config),
                               donate_argnums=(2,))

        def insert(big_cache, k_row, v_row, slot, pos):
            big_cache = dict(big_cache)
            big_cache["k"] = jax.lax.dynamic_update_slice(
                big_cache["k"], k_row.astype(big_cache["k"].dtype),
                (0, slot, 0, 0, 0))
            big_cache["v"] = jax.lax.dynamic_update_slice(
                big_cache["v"], v_row.astype(big_cache["v"].dtype),
                (0, slot, 0, 0, 0))
            big_cache["pos"] = big_cache["pos"].at[slot].set(pos)
            return big_cache

        self._insert = jax.jit(insert, donate_argnums=(0,))

        self._cache = init_kv_cache(config, slots, max_len)
        self._slot_state = [_Slot() for _ in range(slots)]
        self._queue: queue.Queue = queue.Queue()
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._next_id = 0
        self._lock = threading.Lock()
        self._stats = {"requests": 0, "completed": 0, "ttft_sum": 0.0,
                       "tokens_out": 0}

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        if self._running:
            return
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def warmup(self):
        """Compile prefill buckets, decode step, and insertion."""
        started = time.perf_counter()
        for bucket in self.prefill_buckets:
            small = init_kv_cache(self.config, 1, self.max_len)
            tokens = jnp.zeros((1, bucket), jnp.int32)
            _, small = self._prefill(self.params, tokens, small)
            # the last-token replay used for non-bucket prompt lengths
            _, small = self._prefill(self.params,
                                     jnp.zeros((1, 1), jnp.int32), small)
            self._cache = self._insert(self._cache, small["k"], small["v"],
                                       0, bucket)
        step = jnp.zeros((self.slots, 1), jnp.int32)
        tok, self._cache = self._decode(self.params, step, self._cache)
        float(jnp.sum(tok))  # host fetch = real sync on the relay
        self._cache["pos"] = jnp.zeros((self.slots,), jnp.int32)
        logger.info("continuous batching engine warm",
                    slots=self.slots,
                    buckets=list(self.prefill_buckets),
                    warmup_s=round(time.perf_counter() - started, 2))

    # -- API ----------------------------------------------------------------
    def submit(self, prompt_tokens, max_new_tokens: int = 64,
               eos_id: int | None = None) -> Future:
        future: Future = Future()
        with self._lock:
            request_id = self._next_id
            self._next_id += 1
            self._stats["requests"] += 1
        self._queue.put((request_id, list(prompt_tokens), max_new_tokens,
                         eos_id, future, time.perf_counter()))
        if not self._running:
            self.start()
        return future

    def generate(self, prompt_tokens, max_new_tokens: int = 64,
                 eos_id: int | None = None, timeout: float = 300.0):
        """Synchronous convenience wrapper around submit()."""
        return self.submit(prompt_tokens, max_new_tokens,
                           eos_id).result(timeout=timeout)

    @property
    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
        if out["completed"]:
            out["ttft_avg_s"] = out["ttft_sum"] / out["completed"]
        return out

    # -- scheduler ----------------------------------------------------------
    def _bucket_for(self, length: int) -> int:
        for bucket in self.prefill_buckets:
            if length <= bucket:
                return bucket
        return self.max_len

    def _admit_one(self) -> bool:
        """Prefill one queued request into a free slot (returns True if a
        request was admitted)."""
        free = next((i for i, s in enumerate(self._slot_state)
                     if not s.active), None)
        if free is None:
            return False
        try:
            (request_id, prompt, max_new, eos_id, future,
             submitted) = self._queue.get_nowait()
        except queue.Empty:
            return False
        prompt = np.asarray(prompt, np.int32).reshape(1, -1)
        prompt_len = prompt.shape[1]
        if prompt_len + max_new > self.max_len:
            future.set_exception(ValueError(
                f"prompt_len {prompt_len} + max_new_tokens {max_new} "
                f"exceeds max_len {self.max_len}"))
            return True
        bucket = self._bucket_for(prompt_len)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :prompt_len] = prompt

        small = init_kv_cache(self.config, 1, self.max_len)
        logits, small = self._prefill(self.params, jnp.asarray(padded),
                                      small)
        if prompt_len != bucket:
            # bucket padding advanced pos past the prompt; replay the last
            # real token for its logits (same trick as LLMEngine.generate)
            small["pos"] = jnp.full((1,), prompt_len - 1, jnp.int32)
            logits, small = self._prefill(
                self.params, jnp.asarray(prompt[:, -1:]), small)
        first_token = int(np.asarray(jnp.argmax(logits, axis=-1))[0])
        self._cache = self._insert(self._cache, small["k"], small["v"],
                                   free, prompt_len)

        slot = self._slot_state[free]
        slot.request_id = request_id
        slot.tokens = [first_token]
        slot.remaining = max_new - 1
        slot.eos_id = eos_id
        slot.future = future
        slot.started = submitted
        slot.ttft = time.perf_counter() - submitted
        slot.prompt_len = prompt_len
        if (eos_id is not None and first_token == eos_id) or \
                slot.remaining <= 0:
            self._finish(free)
        return True

    def _finish(self, index: int):
        slot = self._slot_state[index]
        stats = {
            "ttft_s": slot.ttft,
            "generated": len(slot.tokens),
            "prompt_len": slot.prompt_len,
            "total_s": time.perf_counter() - slot.started,
        }
        with self._lock:
            self._stats["completed"] += 1
            self._stats["ttft_sum"] += slot.ttft
            self._stats["tokens_out"] += len(slot.tokens)
        future, tokens = slot.future, slot.tokens
        self._slot_state[index] = _Slot()
        # zero the freed row's position so decode writes land in its own
        # (now unused) region
        self._cache["pos"] = self._cache["pos"].at[index].set(0)
        if future is not None and not future.cancelled():
            future.set_result((tokens, stats))

    def _decode_tick(self):
        active = [i for i, s in enumerate(self._slot_state) if s.active]
        if not active:
            return
        last = np.zeros((self.slots, 1), np.int32)
        for i in active:
            last[i, 0] = self._slot_state[i].tokens[-1]
        next_token, self._cache = self._decode(
            self.params, jnp.asarray(last), self._cache)
        tokens_host = np.asarray(next_token)
        for i in active:
            slot = self._slot_state[i]
            token = int(tokens_host[i])
            slot.tokens.append(token)
            slot.remaining -= 1
            capacity = slot.prompt_len + len(slot.tokens) >= self.max_len
            if (slot.eos_id is not None and token == slot.eos_id) or \
                    slot.remaining <= 0 or capacity:
                self._finish(i)

    def _loop(self):
        try:
            while self._running:
                admitted = True
                while admitted:
                    admitted = self._admit_one()
                if not any(s.active for s in self._slot_state):
                    time.sleep(0.002)  # idle: poll admissions at 2ms
                    continue
                self._decode_tick()
        except Exception as exc:  # noqa: BLE001 - a dead scheduler must
            # fail pending work loudly, not leave futures hanging forever
            logger.error("continuous batching scheduler died",
                         error=str(exc))
            self._running = False
            self._fail_pending(exc)

    def _fail_pending(self, exc: Exception):
        for i, slot in enumerate(self._slot_state):
            if slot.active and slot.future is not None:
                slot.future.set_exception(exc)
            self._slot_state[i] = _Slot()
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            item[4].set_exception(exc)
