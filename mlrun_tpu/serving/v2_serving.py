"""V2 model server (reference analog: mlrun/serving/v2_serving.py:32
V2ModelServer — do_event :228 op dispatch, load/predict/explain/validate/
preprocess/postprocess hooks :204-391, _ModelLogPusher :429).

TPU twist: ``TpuModelServer`` below compiles the model's forward with
``jax.jit`` at load time and runs warmup so first-request latency excludes
XLA compilation (the <200ms TTFT budget in BASELINE.md).
"""

from __future__ import annotations

import threading
import time
import traceback
import uuid
from typing import Any, Dict, Optional, Union

from ..utils import logger, now_iso
from .resilience import ModelNotReadyError


class V2ModelServer:
    """Base model-serving class — subclass and implement load() + predict()."""

    def __init__(self, context=None, name: str | None = None,
                 model_path: str | None = None, model=None,
                 protocol: str | None = None, input_path: str | None = None,
                 result_path: str | None = None, **class_args):
        self.name = name
        self.version = ""
        if name and ":" in name:
            self.name, self.version = name.split(":", 1)
        self.context = context
        self.ready = False
        self.error = ""
        self.protocol = protocol or "v2"
        self.model_path = model_path
        self.model_spec = None
        self.model = model
        self.class_args = class_args
        self.input_path = input_path
        self.result_path = result_path
        self._model_logger = None
        self.metrics: dict = {}
        self.labels: dict = {}
        self._lock = threading.Lock()
        self._load_time = 0.0

    def post_init(self, mode: str = "sync"):
        """Called by the graph after construction: load + announce."""
        if self.model is None:
            started = time.monotonic()
            try:
                self.load()
            except Exception as exc:  # noqa: BLE001 - keep serving other models
                self.error = str(exc)
                if self.context:
                    self.context.logger.error(
                        "model load failed", model=self.name, error=str(exc))
                return
            self._load_time = time.monotonic() - started
        self.ready = True
        if self.context and getattr(self.context, "monitoring_stream", None) \
                is not None:
            self._model_logger = _ModelLogPusher(self, self.context)
        if self.context:
            self.context.logger.info(
                "model loaded", model=self.name,
                load_time_s=round(self._load_time, 3))

    # -- model lifecycle hooks (override) ----------------------------------
    def load(self):
        """Load the model; use get_model() to fetch from the registry."""

    def get_model(self, suffix: str = ""):
        """Fetch the model artifact → (local_path, model_spec, extra_data)."""
        from ..artifacts.model import get_model

        local_path, model_spec, extra_data = get_model(self.model_path, suffix)
        self.model_spec = model_spec
        return local_path, extra_data

    def predict(self, request: dict) -> Any:
        raise NotImplementedError("implement predict() in your model class")

    def explain(self, request: dict) -> Any:
        raise NotImplementedError(f"model {self.name} has no explain method")

    def validate(self, request: dict, operation: str) -> dict:
        if self.protocol == "v2" and operation in ("infer", "predict"):
            if not isinstance(request, dict) or "inputs" not in request:
                raise ValueError("request must contain an 'inputs' field")
        return request

    def preprocess(self, request: dict, operation: str) -> dict:
        return request

    def postprocess(self, request: dict) -> dict:
        return request

    def logged_results(self, request: dict, response: dict, op: str):
        """Hook to shape what gets pushed to monitoring."""
        return request.get("inputs"), response.get("outputs")

    def set_metric(self, name: str, value):
        self.metrics[name] = value

    # -- event dispatch ----------------------------------------------------
    def do_event(self, event, *args, **kwargs):
        """Dispatch infer/predict/explain/metrics/ready ops (v2_serving.py:228)."""
        event_body = event.body if hasattr(event, "body") else event
        path = getattr(event, "path", "/") or "/"
        op = self._extract_op(event_body, path)

        if op == "ready":
            if not self.ready:
                raise ModelNotReadyError(
                    f"model {self.name} is not ready: {self.error}")
            event.body = {"name": self.name, "ready": True}
            return event
        if op == "metrics":
            event.body = {"name": self.name, "metrics": dict(self.metrics)}
            return event
        if op == "explain" or op in ("infer", "predict", ""):
            request = event_body if isinstance(event_body, dict) else {
                "inputs": event_body}
            if not self.ready:
                with self._lock:
                    if not self.ready:
                        self.post_init()
                if not self.ready:
                    raise ModelNotReadyError(
                        f"model {self.name} failed to load: "
                        f"{self.error}")
            start = time.monotonic()
            try:
                request = self.preprocess(request, op)
                request = self.validate(request, op or "infer")
                if op == "explain":
                    outputs = self.explain(request)
                else:
                    outputs = self.predict(request)
                response = {
                    "id": request.get("id") or getattr(event, "id", None)
                    or uuid.uuid4().hex,
                    "model_name": self.name,
                    "outputs": _to_serializable(outputs),
                }
                if self.version:
                    response["model_version"] = self.version
                response = self.postprocess(response)
            except Exception as exc:  # noqa: BLE001
                if self._model_logger:
                    self._model_logger.push_error(request, str(exc))
                raise
            microsec = int((time.monotonic() - start) * 1e6)
            self.metrics["latency_microsec"] = microsec
            self.metrics["requests"] = self.metrics.get("requests", 0) + 1
            if self._model_logger:
                self._model_logger.push(request, response, op or "infer",
                                        microsec)
            event.body = response
            return event
        raise ValueError(f"unsupported operation '{op}'")

    @staticmethod
    def _extract_op(body, path: str) -> str:
        parts = [p for p in path.split("/") if p]
        # v2 path convention: /v2/models/<name>/<op>
        if parts and parts[-1] in ("infer", "predict", "explain", "metrics",
                                   "ready"):
            return parts[-1]
        if isinstance(body, dict) and "operation" in body:
            return body["operation"]
        return "infer"


class TpuModelServer(V2ModelServer):
    """A V2ModelServer whose forward is an XLA-compiled JAX callable.

    Subclasses implement ``build_forward() -> (fn, params)`` or pass
    ``forward_fn``/``params`` as class args; inputs are batched to device and
    the compiled fn runs on the TPU. ``warmup_shapes`` are compiled at load
    time so serving never pays XLA compile latency on-path.
    """

    def __init__(self, *args, forward_fn=None, params=None,
                 warmup_shapes: list | None = None, **kwargs):
        super().__init__(*args, **kwargs)
        self._forward = forward_fn
        self._params = params
        self._warmup_shapes = warmup_shapes or []

    def build_forward(self):
        """Override: return (forward_fn(params, inputs), params)."""
        if self._forward is None:
            raise NotImplementedError(
                "pass forward_fn/params or override build_forward()")
        return self._forward, self._params

    def load(self):
        import jax
        import jax.numpy as jnp

        forward, params = self.build_forward()
        self._jitted = jax.jit(forward)
        self._params = params
        for shape in self._warmup_shapes:
            dummy = jnp.zeros(shape, dtype=jnp.float32)
            _ = jax.block_until_ready(self._jitted(self._params, dummy))
        self.model = self._jitted

    def predict(self, request: dict):
        import jax
        import jax.numpy as jnp
        import numpy as np

        inputs = jnp.asarray(np.asarray(request["inputs"]))
        outputs = jax.block_until_ready(self._jitted(self._params, inputs))
        return np.asarray(outputs)


class _ModelLogPusher:
    """Streams inference events to the monitoring pipeline
    (reference v2_serving.py:429)."""

    def __init__(self, model: V2ModelServer, context):
        self.model = model
        self.context = context
        self.stream = getattr(context, "monitoring_stream", None)
        self.hostname = ""
        self.function_uri = getattr(
            getattr(context, "server", None), "function_uri", "") or ""

    def base_data(self) -> dict:
        return {
            "class": self.model.__class__.__name__,
            "model": self.model.name,
            "version": self.model.version,
            "function_uri": self.function_uri,
            "when": now_iso(),
            "labels": self.model.labels,
        }

    def push(self, request, response, op: str, microsec: int):
        if self.stream is None:
            return
        inputs, outputs = self.model.logged_results(request, response, op)
        data = self.base_data()
        data.update({
            "request": {"inputs": _to_serializable(inputs),
                        "id": response.get("id")},
            "resp": {"outputs": _to_serializable(outputs)},
            "op": op,
            "microsec": microsec,
            "metrics": dict(self.model.metrics),
        })
        try:
            self.stream.push(data)
        except Exception as exc:  # noqa: BLE001 - monitoring must not break serving
            logger.warning("failed to push monitoring event", error=str(exc))

    def push_error(self, request, error: str):
        if self.stream is None:
            return
        data = self.base_data()
        data.update({"error": error, "request": _to_serializable(request)})
        try:
            self.stream.push(data)
        except Exception:  # noqa: BLE001
            pass


def _to_serializable(obj):
    import numpy as np

    if obj is None or isinstance(obj, (str, int, float, bool)):
        return obj
    if isinstance(obj, dict):
        return {k: _to_serializable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_to_serializable(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if hasattr(obj, "tolist"):
        try:
            return obj.tolist()
        except Exception:  # noqa: BLE001
            return str(obj)
    return str(obj)
