"""Routers & ensembles (reference analog: mlrun/serving/routers.py:167
ModelRouter, :245 ParallelRun, :480 VotingEnsemble)."""

from __future__ import annotations

import concurrent.futures
import copy
import time
from typing import Optional, Union

import numpy as np

from ..common.retry import RetryPolicy, compute_backoff
from ..utils import logger
from .resilience import check_deadline, deadline_remaining


class BaseModelRouter:
    """Route events to child model steps by url path or body field."""

    def __init__(self, context=None, name: str | None = None,
                 routes: dict | None = None, protocol: str = "v2",
                 url_prefix: str | None = None, health_prefix: str | None = None,
                 **kwargs):
        self.context = context
        self.name = name or "router"
        self.routes = routes or {}
        self.protocol = protocol
        self.url_prefix = url_prefix or f"/{self.protocol}/models"
        self.health_prefix = health_prefix or f"/{self.protocol}/health"
        self.inputs_key = "inputs"
        self._kwargs = kwargs

    def post_init(self, mode: str = "sync"):
        pass

    def parse_event(self, event):
        """Normalize body: allow raw lists as {'inputs': [...]}."""
        body = event.body
        if isinstance(body, (list, np.ndarray)):
            event.body = {self.inputs_key: body}
        return event

    def _resolve_route(self, event) -> tuple[str, str]:
        """Return (model_name, op) parsed from the path or body."""
        path = getattr(event, "path", "/") or "/"
        if path.startswith(self.url_prefix):
            rest = path[len(self.url_prefix):].strip("/")
            parts = rest.split("/") if rest else []
            model = parts[0] if parts else ""
            op = parts[1] if len(parts) > 1 else "infer"
            return model, op
        body = event.body
        if isinstance(body, dict):
            return body.get("model", ""), body.get("operation", "infer")
        return "", "infer"

    def do_event(self, event, *args, **kwargs):
        event = self.parse_event(event)
        path = getattr(event, "path", "/") or "/"
        if path.startswith(self.health_prefix) or path in ("/", ""):
            if getattr(event, "method", "GET") == "GET" and not isinstance(
                    event.body, dict):
                event.body = {
                    "models": list(self.routes.keys()),
                    "router": self.name,
                }
                return event
        model, op = self._resolve_route(event)
        if event.body is None and getattr(event, "method", "POST") == "GET" \
                and (not model or model not in self.routes):
            event.body = {"models": list(self.routes.keys()),
                          "router": self.name}
            return event
        if not model:
            if len(self.routes) == 1:
                model = next(iter(self.routes))
            else:
                event.body = {"models": list(self.routes.keys())}
                return event
        if model not in self.routes:
            raise ValueError(
                f"model '{model}' not found in routes {list(self.routes)}")
        # an expired request must not reach the model at all
        check_deadline(event, f"{self.name}/{model}")
        return self.routes[model].run(event)


class ModelRouter(BaseModelRouter):
    """Default router (reference routers.py:167)."""


class ParallelRun(BaseModelRouter):
    """Fan an event to all routes in parallel and merge results
    (reference routers.py:245; thread pool executor)."""

    def __init__(self, *args, extend_event=None, executor_type: str = "thread",
                 **kwargs):
        super().__init__(*args, **kwargs)
        self.executor_type = executor_type
        self.extend_event = extend_event

    def merger(self, body: dict, results: dict) -> dict:
        for result in results.values():
            if isinstance(result, dict):
                body.update(result)
        return body

    def do_event(self, event, *args, **kwargs):
        event = self.parse_event(event)
        # fan-out multiplies the cost of serving an expired request by
        # len(routes) — check the budget once before dispatching anywhere
        check_deadline(event, self.name)
        results = {}
        with concurrent.futures.ThreadPoolExecutor(
                max_workers=max(1, len(self.routes))) as pool:
            futures = {
                name: pool.submit(step.run, copy.copy(event))
                for name, step in self.routes.items()
            }
            for name, future in futures.items():
                out = future.result()
                results[name] = out.body if hasattr(out, "body") else out
        body = event.body if isinstance(event.body, dict) else {}
        event.body = self.merger(body, results)
        return event


class PrefixAffinityRouter(BaseModelRouter):
    """Consistent-hash prefix-affinity routing over LLM replica routes
    (docs/serving.md "Engine fleet").

    Routes are interchangeable model replicas (each typically an
    ``LLMModelServer`` — in-process engine or a ``RemoteStep``-backed
    process); the router keys each request on the prompt's leading
    prefix blocks (``prefix.block_chain_key``) so requests sharing a hot
    prefix hit the replica whose KV cache already holds it. A 503-class
    failure (draining or stopped replica, open breaker, shed) re-routes
    to the next ring node with bounded deterministic backoff instead of
    surfacing the failure to the client; an explicit
    ``/v2/models/<name>`` path still addresses one replica directly.
    """

    def __init__(self, *args, route_block_tokens: int = 64,
                 route_blocks: int = 4, vnodes: int = 64,
                 max_dispatch_attempts: int = 3, backoff: float = 0.05,
                 **kwargs):
        super().__init__(*args, **kwargs)
        self.route_block_tokens = int(route_block_tokens)
        self.route_blocks = int(route_blocks)
        self.max_dispatch_attempts = int(max_dispatch_attempts)
        self._retry_policy = RetryPolicy(
            max_retries=self.max_dispatch_attempts, backoff=float(backoff),
            backoff_factor=2.0, backoff_max=1.0, jitter=0.1)
        from .fleet import ConsistentHashRing

        self._ring = ConsistentHashRing(vnodes=int(vnodes))
        self.redispatches = 0

    def post_init(self, mode: str = "sync"):
        for name in self.routes:
            self._ring.add(name)

    def _routing_key(self, event) -> int:
        """Key on the first input's leading blocks: token lists hash
        token blocks (the radix-index identity); strings hash byte
        blocks, which is the same shared-prefix grouping one tokenizer
        hop earlier. The v2 body's ``adapter`` id namespaces the key —
        the same prompt under two tenants is two routing identities
        (docs/serving.md "Multi-tenant LoRA"). A tenant with canary-loop
        state resolves to its effective versioned id first
        (serving/canary.py, key computation only — the downstream server
        meters and applies the split), so canary traffic routes as its
        own identity. NOTE: with string inputs and no explicit
        ``request_key`` the router's side guess keys on prompt BYTES
        while the engine keys on tokens — pass ``request_key`` when
        exact router/engine side agreement matters (locality-only skew
        otherwise)."""
        from .canary import resolve_adapter
        from .prefix import block_chain_key

        body = event.body if isinstance(event.body, dict) else {}
        inputs = body.get(self.inputs_key) or []
        first = inputs[0] if inputs else ""
        if isinstance(first, str):
            first = list(first.encode())
        adapter = str(body.get("adapter", "") or "")
        if adapter:
            adapter = resolve_adapter(
                adapter, list(first),
                body.get("request_key") or None, count=False)
        return block_chain_key(list(first), self.route_block_tokens,
                               max_blocks=self.route_blocks,
                               adapter=adapter)

    def do_event(self, event, *args, **kwargs):
        from .fleet import redispatchable

        event = self.parse_event(event)
        path = getattr(event, "path", "/") or "/"
        if path.startswith(self.health_prefix):
            event.body = {"models": list(self.routes.keys()),
                          "router": self.name}
            return event
        model, _ = self._resolve_route(event)
        if model:
            # an explicit replica address bypasses affinity
            # (ops/debugging); an UNKNOWN one is an addressing error the
            # caller must see (base-router contract), not traffic to
            # silently affinity-route — a stale address after scale-down
            # would otherwise look like a healthy replica
            if model not in self.routes:
                raise ValueError(
                    f"model '{model}' not found in routes "
                    f"{list(self.routes)}")
            check_deadline(event, f"{self.name}/{model}")
            return self.routes[model].run(event)
        if getattr(event, "method", "POST") == "GET" or not isinstance(
                event.body, dict):
            event.body = {"models": list(self.routes.keys()),
                          "router": self.name}
            return event
        key = self._routing_key(event)
        order = self._ring.preference(key)
        last_exc = None
        for attempt, name in enumerate(order[:self.max_dispatch_attempts]):
            check_deadline(event, f"{self.name}/{name}")
            if attempt:
                delay = compute_backoff(
                    attempt - 1, self._retry_policy,
                    seed=f"{self.name}:{key}")
                remaining = deadline_remaining(event)
                if remaining is not None and delay >= remaining:
                    break  # no budget for another replica
                if delay > 0:
                    time.sleep(delay)
            try:
                return self.routes[name].run(copy.copy(event))
            except Exception as exc:  # noqa: BLE001 - classified below
                if not redispatchable(exc):
                    raise
                last_exc = exc
                self.redispatches += 1
                incr = getattr(self.context, "incr", None)
                if callable(incr):
                    incr(f"router.{self.name}.redispatched")
                logger.warning("affinity router re-dispatching",
                               router=self.name, replica=name,
                               attempt=attempt + 1, error=str(exc))
        from .resilience import ReplicaUnavailableError

        raise ReplicaUnavailableError(
            f"router '{self.name}' exhausted its replicas "
            f"({min(len(order), self.max_dispatch_attempts)} tried)"
        ) from last_exc


class VotingTypes:
    classification = "classification"
    regression = "regression"


class VotingEnsemble(BaseModelRouter):
    """Send the event to all models and vote/average
    (reference routers.py:480)."""

    def __init__(self, *args, vote_type: str | None = None,
                 weights: dict | None = None, prediction_col_name: str = None,
                 **kwargs):
        super().__init__(*args, **kwargs)
        self.vote_type = vote_type
        self.weights = weights or {}
        self.prediction_col_name = prediction_col_name or "prediction"

    def _vote(self, predictions: dict[str, list]) -> list:
        names = list(predictions.keys())
        arrays = [np.asarray(predictions[n], dtype=float) for n in names]
        stacked = np.stack(arrays)  # [models, batch, ...]
        weights = np.asarray(
            [self.weights.get(n, 1.0) for n in names], dtype=float)
        weights = weights / weights.sum()
        vote_type = self.vote_type or (
            VotingTypes.classification
            if np.allclose(stacked, np.round(stacked))
            else VotingTypes.regression)
        if vote_type == VotingTypes.regression:
            return np.tensordot(weights, stacked, axes=1).tolist()
        # weighted majority per sample
        out = []
        flat = stacked.reshape(stacked.shape[0], -1)
        for col in range(flat.shape[1]):
            votes: dict = {}
            for m, w in enumerate(weights):
                votes[flat[m, col]] = votes.get(flat[m, col], 0.0) + w
            out.append(max(votes.items(), key=lambda kv: kv[1])[0])
        return np.asarray(out).reshape(stacked.shape[1:]).tolist()

    def do_event(self, event, *args, **kwargs):
        event = self.parse_event(event)
        path = getattr(event, "path", "/") or "/"
        model, op = self._resolve_route(event)
        if model and model in self.routes:
            # direct route to a specific member model
            return self.routes[model].run(event)
        if op in ("metrics", "ready") or (
                getattr(event, "method", "POST") == "GET"):
            event.body = {"models": list(self.routes.keys()),
                          "router": self.name}
            return event
        check_deadline(event, self.name)
        predictions = {}
        for name, step in self.routes.items():
            sub = copy.copy(event)
            sub.body = copy.deepcopy(event.body)
            out = step.run(sub)
            body = out.body if hasattr(out, "body") else out
            predictions[name] = body.get("outputs") if isinstance(body, dict) \
                else body
        voted = self._vote(predictions)
        event.body = {
            "id": getattr(event, "id", None),
            "model_name": self.name,
            "outputs": voted,
            "model_version": "v1",
        }
        return event


class EnrichmentModelRouter(ModelRouter):
    """Router that enriches the event with feature-store features before
    routing (reference routers.py:1118)."""

    def __init__(self, *args, feature_vector_uri: str = "",
                 impute_policy: dict | None = None, **kwargs):
        super().__init__(*args, **kwargs)
        self.feature_vector_uri = feature_vector_uri
        self.impute_policy = impute_policy or {}
        self._service = None

    def post_init(self, mode: str = "sync"):
        if self.feature_vector_uri:
            from ..feature_store import get_online_feature_service

            self._service = get_online_feature_service(
                self.feature_vector_uri, impute_policy=self.impute_policy)

    def parse_event(self, event):
        event = super().parse_event(event)
        if self._service is not None and isinstance(event.body, dict):
            entities = event.body.get(self.inputs_key, [])
            enriched = self._service.get(
                [e if isinstance(e, dict) else {"id": e} for e in entities],
                as_list=True)
            event.body[self.inputs_key] = enriched
        return event


class EnrichmentVotingEnsemble(VotingEnsemble, EnrichmentModelRouter):
    """Voting ensemble with feature enrichment (reference routers.py:1199)."""
