"""Batch=1 speculative decoding utilities — draft-proposes,
target-verifies, EXACT greedy output (no reference analog: the reference
delegates inference entirely; this is TPU-native serving capability
beyond parity).

This module is the standalone/utility layer: ``SpeculativeDecoder`` runs
one stream against dense caches, and ``accept_tokens`` is the shared
greedy acceptance rule. The PRIMARY speculation path is in-engine —
``ContinuousBatchingEngine`` / ``PagedContinuousBatchingEngine`` run
batched draft steps and ONE multi-token verify dispatch per scheduler
tick (the paged engine through the verify kernel, no dense gather and no
``all_logits`` forward), with per-row adaptive k and per-tenant LoRA
drafts — see docs/serving.md "Speculative decoding".

Why it fits TPU: single-token decode is memory-bound (one HBM sweep of
the weights per token). Verifying k proposed tokens costs ONE target
forward over k+1 positions — nearly the same HBM traffic as one decode
step — so each accepted proposal is almost-free throughput. The draft
model runs k cheap steps; the target amortizes its sweep over the
accepted prefix plus one bonus token.

Greedy equivalence: proposals are accepted only while they match the
target's own argmax at that position, and the first mismatch is replaced
by the target's argmax — so given consistent target logits the emitted
stream is IDENTICAL to plain greedy decoding of the target model,
independent of draft quality (draft quality only changes speed via the
acceptance rate). Caveat shared by every speculative implementation: the
(k+1)-token verify forward and a 1-token decode forward are different
compiled programs, so their logits can differ by float rounding (~1e-2
with bf16 activations); an argmax whose top-2 gap is below that noise can
tie-break differently. Trained models' confident tokens sit far above it.

KV-cache rollback uses the engine's append-only layout: rejected
positions simply rewind ``cache['pos']``; stale entries are overwritten
by the next write before any query can attend to them (writes always
land at ``pos`` before attention runs).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import jax
import jax.numpy as jnp

from ..models.llama import LlamaConfig
from ..utils import logger
from .llm import _forward_with_cache, init_kv_cache

Params = dict


def accept_tokens(proposals, verified, k_eff: int) -> tuple[list, int]:
    """The greedy acceptance rule, shared by the batch=1 decoder and the
    engines' per-row commit loop. ``proposals``: the row's ``k_eff``
    draft tokens; ``verified``: the target's argmax at chunk positions
    0..k_eff (position i = the target's next token after seeing
    proposal i-1; position 0 follows the committed last token).

    Accept while proposal == target argmax; the first mismatch is
    replaced by the target's own argmax. Full acceptance emits the k_eff
    proposals WITHOUT the bonus token at position k_eff — the draft
    cache has no KV for it, so emitting it would leave an unwritten hole
    later draft queries attend as zeros. ``k_eff == 0`` degenerates to
    plain decode: emit the target argmax after the last token.

    Returns (emitted tokens, n_accept).
    """
    n_accept = 0
    while n_accept < k_eff and int(proposals[n_accept]) == int(
            verified[n_accept]):
        n_accept += 1
    if n_accept == k_eff and k_eff > 0:
        emitted = [int(t) for t in proposals]
    else:
        emitted = ([int(t) for t in proposals[:n_accept]]
                   + [int(verified[n_accept])])
    return emitted, n_accept


@dataclasses.dataclass
class SpecStats:
    rounds: int = 0
    proposed: int = 0
    accepted: int = 0
    tokens: int = 0
    elapsed_s: float = 0.0
    fallback_rounds: int = 0  # rounds decoded target-only (gate closed)

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.proposed if self.proposed else 0.0

    @property
    def tokens_per_round(self) -> float:
        return self.tokens / self.rounds if self.rounds else 0.0

    def to_dict(self) -> dict:
        return {"rounds": self.rounds, "proposed": self.proposed,
                "accepted": self.accepted, "tokens": self.tokens,
                "acceptance_rate": round(self.acceptance_rate, 4),
                "tokens_per_round": round(self.tokens_per_round, 3),
                "fallback_rounds": self.fallback_rounds,
                "elapsed_s": round(self.elapsed_s, 4)}


class SpeculativeDecoder:
    """Greedy speculative decoding with a small draft model.

    Both models share the tokenizer/vocab. ``k`` is the static proposal
    length — every round compiles to one k-step draft loop plus one
    (k+1)-token target verify, both cached by jit after the first round.
    """

    def __init__(self, target_config: LlamaConfig, target_params: Params,
                 draft_config: LlamaConfig, draft_params: Params,
                 k: int = 4, max_len: int = 2048,
                 kv_dtype: str = "native", gate=None):
        if target_config.vocab_size != draft_config.vocab_size:
            raise ValueError("draft and target must share a vocabulary")
        # degradation-ladder hook: a callable consulted every round; when it
        # returns False the round decodes ONE token target-only (exact
        # greedy, same stream) instead of running draft+verify. Wire an
        # engine's flag: gate=lambda: engine.speculative_enabled
        self.enabled = True
        self.gate = gate
        self.target_config = target_config
        self.target_params = target_params
        self.draft_config = draft_config
        self.draft_params = draft_params
        self.k = int(k)
        self.max_len = max_len
        self.kv_dtype = kv_dtype

        def draft_propose(params, token, cache):
            """k greedy draft steps; returns ([1, k] proposals, cache)."""
            def body(carry, _):
                tok, c = carry
                logits, c = _forward_with_cache(
                    self.draft_config, params, tok[:, None], c)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (nxt, c), nxt

            (_, cache), proposals = jax.lax.scan(
                body, (token, cache), None, length=self.k)
            return proposals.T, cache  # [1, k]

        def target_verify(params, token, proposals, cache):
            """One (k+1)-token forward; returns per-position argmaxes
            [1, k+1] (position i = target's next-token after seeing
            proposal i-1) and the updated cache."""
            chunk = jnp.concatenate([token[:, None], proposals], axis=1)
            logits, cache = _forward_with_cache(
                self.target_config, params, chunk, cache, all_logits=True)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

        self._draft_propose = jax.jit(draft_propose)
        self._target_verify = jax.jit(target_verify)

    def _speculation_allowed(self) -> bool:
        if not self.enabled:
            return False
        if self.gate is not None:
            try:
                return bool(self.gate())
            except Exception as exc:  # noqa: BLE001 - a broken gate must
                # not take decoding down; fall back to full speculation
                logger.warning("speculative gate failed, assuming enabled",
                               error=str(exc))
        return True

    def _prefill(self, params, config, tokens):
        cache = init_kv_cache(config, 1, self.max_len,
                              kv_dtype=self.kv_dtype)
        logits, cache = _forward_with_cache(
            config, params, jnp.asarray([tokens], jnp.int32), cache)
        return logits, cache

    def generate(self, prompt_tokens, max_new_tokens: int = 64,
                 eos_id: Optional[int] = None) -> tuple[list, SpecStats]:
        """Greedy generation, exactly equal to the target model's own
        greedy decode; returns (tokens, stats)."""
        prompt = [int(t) for t in prompt_tokens]
        if len(prompt) + max_new_tokens + self.k + 1 > self.max_len:
            from .resilience import PromptTooLongError

            raise PromptTooLongError(
                "prompt + max_new_tokens exceeds max_len")
        stats = SpecStats()
        start = time.perf_counter()

        t_logits, t_cache = self._prefill(
            self.target_params, self.target_config, prompt)
        _, d_cache = self._prefill(
            self.draft_params, self.draft_config, prompt)
        last = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)  # [1]
        out = [int(last[0])]

        while len(out) < max_new_tokens and (
                eos_id is None or out[-1] != eos_id):
            if not self._speculation_allowed():
                # degraded mode (engine under pressure): decode ONE token
                # target-only. Exact same greedy stream, no draft compute;
                # both caches stay in sync so speculation can resume the
                # moment the gate reopens.
                t_logits, t_cache = _forward_with_cache(
                    self.target_config, self.target_params,
                    last[:, None], t_cache)
                _, d_cache = _forward_with_cache(
                    self.draft_config, self.draft_params,
                    last[:, None], d_cache)
                nxt = int(jax.device_get(
                    jnp.argmax(t_logits, axis=-1))[0])
                out.append(nxt)
                stats.rounds += 1
                stats.fallback_rounds += 1
                last = jnp.asarray([out[-1]], jnp.int32)
                continue
            proposals, d_cache = self._draft_propose(
                self.draft_params, last, d_cache)
            verified, t_cache = self._target_verify(
                self.target_params, last, proposals, t_cache)
            proposals_h = jax.device_get(proposals)[0]
            verified_h = jax.device_get(verified)[0]

            # shared greedy acceptance rule (accept_tokens docstring has
            # the full-acceptance bonus-token rationale)
            emitted, n_accept = accept_tokens(proposals_h, verified_h,
                                              self.k)
            if eos_id is not None and eos_id in emitted:
                emitted = emitted[:emitted.index(eos_id) + 1]
            room = max_new_tokens - len(out)
            emitted = emitted[:room]
            out.extend(int(t) for t in emitted)

            stats.rounds += 1
            stats.proposed += self.k
            stats.accepted += n_accept

            # rewind both caches to the committed stream length:
            # target wrote k+1 entries (last + proposals), draft wrote k
            committed = len(prompt) + len(out) - 1  # entries BEHIND `last`
            t_cache = dict(t_cache)
            d_cache = dict(d_cache)
            t_cache["pos"] = jnp.full_like(t_cache["pos"], committed)
            d_cache["pos"] = jnp.full_like(d_cache["pos"], committed)
            last = jnp.asarray([out[-1]], jnp.int32)

        stats.tokens = len(out)
        stats.elapsed_s = time.perf_counter() - start
        logger.debug("speculative decode", **stats.to_dict())
        return out, stats
