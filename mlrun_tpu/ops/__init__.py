from .attention import (  # noqa: F401
    attention,
    attention_reference,
    flash_attention_mlt,
)
from .norms import rms_norm, rms_norm_pallas  # noqa: F401
from .ring_attention import make_ring_attention, ring_attention  # noqa: F401
from .rotary import apply_rope, apply_rope_qk, rope_table  # noqa: F401
from .ulysses import make_ulysses_attention, ulysses_attention  # noqa: F401
