"""Ring attention — sequence/context parallelism over a mesh axis.

The reference has NO long-context capability (SURVEY.md §5.7); this introduces
it TPU-natively: q/k/v are sharded along the sequence on a mesh axis, each
device computes blockwise attention against its local kv shard, then rotates
the kv shard around the ring with ``jax.lax.ppermute`` (XLA lowers to ICI
neighbor transfers that overlap with compute). Online-softmax accumulation
makes the result exact; causal masking uses global positions derived from the
ring index.

Use under ``jax.shard_map`` with q/k/v sharded as P(batch_axes, seq_axis):
``ring_attention(q, k, v, axis_name="seq")``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .attention import NEG_INF, _repeat_kv


def _block_attn(q, k, v, q_offset, k_offset, causal: bool, scale: float):
    """One q-shard x kv-shard blockwise attention with global positions.

    q: [B, Sq, H, D]; k,v: [B, Sk, H, D]. Returns (numerator [B,Sq,H,D] f32,
    max [B,Sq,H] f32, denom [B,Sq,H] f32).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = k_offset + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)                         # [B,H,Sq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)                          # [B,H,Sq]
    num = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return num, m.transpose(0, 2, 1), l.transpose(0, 2, 1)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   axis_name: str = "seq", causal: bool = True) -> jax.Array:
    """Exact attention over a sequence-sharded axis (inside shard_map).

    q,k,v: local shards [B, S_local, H(q/kv), D]. The kv shard rotates
    ``axis_size - 1`` times around the ring (the final block is folded in
    without a trailing rotation); accumulation is online-softmax so memory
    stays O(S_local).
    """
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    n_rep = q.shape[2] // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    scale = q.shape[-1] ** -0.5
    s_local = q.shape[1]
    q_offset = my_idx * s_local

    b, sq, h, d = q.shape
    num0 = jnp.zeros((b, sq, h, d), jnp.float32)
    m0 = jnp.full((b, sq, h), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, sq, h), jnp.float32)

    # ring: at step t we hold the kv shard originally from device (my_idx - t)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def accumulate(acc, t, k_cur, v_cur):
        num, m, l = acc
        src_idx = (my_idx - t) % axis_size
        k_offset = src_idx * k_cur.shape[1]
        bnum, bm, bl = _block_attn(q, k_cur, v_cur, q_offset, k_offset,
                                   causal, scale)
        m_new = jnp.maximum(m, bm)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(bm - m_new)
        num = num * alpha[..., None] + bnum * beta[..., None]
        l = l * alpha + bl * beta
        return num, m_new, l

    def body(carry, t):
        num, m, l, k_cur, v_cur = carry
        num, m, l = accumulate((num, m, l), t, k_cur, v_cur)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (num, m, l, k_nxt, v_nxt), None

    # scan the first P-1 ring steps (each ends with a rotation), then fold in
    # the final kv shard outside the scan — P-1 rotations total, not P
    (num, m, l, k_last, v_last), _ = jax.lax.scan(
        body, (num0, m0, l0, k, v), jnp.arange(axis_size - 1))
    num, m, l = accumulate((num, m, l), axis_size - 1, k_last, v_last)
    l = jnp.maximum(l, 1e-30)
    return (num / l[..., None]).astype(q.dtype)


def make_ring_attention(mesh, seq_axis: str = "seq", causal: bool = True):
    """Wrap ring_attention in shard_map over the given mesh.

    Returns fn(q, k, v) taking fully-addressable arrays sharded
    P(('data','fsdp'), seq_axis, ...) along batch/seq.
    """
    from jax.sharding import PartitionSpec as P

    from ..parallel.compat import shard_map

    batch_axes = tuple(a for a in ("data", "fsdp") if a in mesh.axis_names
                       and mesh.shape[a] > 1) or None
    spec = P(batch_axes, seq_axis, None, None)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec, check_vma=False)
    def _ring(q, k, v):
        return ring_attention(q, k, v, axis_name=seq_axis, causal=causal)

    return _ring
