"""Rotary position embeddings (RoPE), llama-3 style with optional NTK scaling."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("head_dim", "theta"))
def rope_table(positions: jax.Array, head_dim: int,
               theta: float = 500000.0) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables for given positions: [seq, head_dim/2] each."""
    freqs = 1.0 / (theta ** (
        jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    angles = positions.astype(jnp.float32)[..., None] * freqs  # [S, D/2]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """Apply rotary embedding. x: [..., seq, heads, head_dim] (interleaved
    pair convention: (x1, x2) halves)."""
    dtype = x.dtype
    x = x.astype(jnp.float32)
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    # broadcast cos/sin [seq, half] over heads: [..., seq, 1, half]
    while cos.ndim < x1.ndim:
        cos = cos[..., None, :] if cos.ndim == x1.ndim - 1 else cos[None]
        sin = sin[..., None, :] if sin.ndim == x1.ndim - 1 else sin[None]
    out1 = x1 * cos - x2 * sin
    out2 = x2 * cos + x1 * sin
    return jnp.concatenate([out1, out2], axis=-1).astype(dtype)


def apply_rope_qk(q: jax.Array, k: jax.Array, positions: jax.Array,
                  theta: float = 500000.0) -> tuple[jax.Array, jax.Array]:
    """Apply RoPE to q & k: [batch, seq, heads, head_dim]."""
    cos, sin = rope_table(positions, q.shape[-1], theta)
    return apply_rope(q, cos, sin), apply_rope(k, cos, sin)
