"""Normalization ops.

RMSNorm is the transformer hot elementwise op; XLA fuses the jnp version into
neighboring ops, which on TPU is usually optimal (HBM-bound fusion). A pallas
variant is provided for cases where fusion is blocked (e.g. explicit
checkpoint boundaries).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, scale: jax.Array, eps: float = 1e-5) -> jax.Array:
    """RMSNorm in f32 accumulation, cast back to input dtype."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    variance = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    normed = x32 * jax.lax.rsqrt(variance + eps)
    return (normed * scale.astype(jnp.float32)).astype(dtype)


def _rms_norm_kernel(x_ref, scale_ref, out_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)
    variance = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    normed = x * jax.lax.rsqrt(variance + eps)
    out_ref[:] = (normed * scale_ref[:].astype(jnp.float32)).astype(
        out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("eps", "block_rows"))
def rms_norm_pallas(x: jax.Array, scale: jax.Array, eps: float = 1e-5,
                    block_rows: int = 256) -> jax.Array:
    """Pallas RMSNorm over the last dim; x is [..., rows, features]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    orig_shape = x.shape
    features = orig_shape[-1]
    rows = 1
    for dim in orig_shape[:-1]:
        rows *= dim
    x2 = x.reshape(rows, features)
    block_rows = min(block_rows, rows)
    grid = (pl.cdiv(rows, block_rows),)
    out = pl.pallas_call(
        functools.partial(_rms_norm_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct((rows, features), x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, features), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((features,), lambda i: (0,),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_rows, features), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
    )(x2, scale)
    return out.reshape(orig_shape)
