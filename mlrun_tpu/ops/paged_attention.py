"""Paged-decode attention: one token per slot straight off the KV page pool.

The paged engine (serving/paged.py) historically materialized a dense
``[slots, max_len]`` KV view per layer per decode tick (``jnp.take`` over
the page table) and ran plain masked attention on it — HBM traffic on the
order of the whole cache for every generated token. This module computes
the same attention by indexing the page pool THROUGH the page table inside
a Pallas kernel: each grid step DMAs exactly one (page, kv-head) tile from
HBM into VMEM, so the bytes read per tick are the slot's *live* pages once
— never a gathered copy of the full view.

Layout (one layer of the pool, see serving/paged.py):

- q:          [slots, n_heads, head_dim]   — the current decode token,
  post-RoPE (its KV must already be written into the pool; the kernel
  masks ``k_pos <= pos`` so the current position participates).
- k/v pages:  [n_pages + 1, page_size, n_kv_heads, head_dim] — the LAST
  physical page is the scratch page; page-table entries < 0 are routed to
  it (they are masked out by ``pos`` anyway, the routing just keeps the
  DMA addresses in-bounds).
- page_table: [slots, pages_per_slot] int32, -1 = unmapped.
- pos:        [slots] int32 absolute position of the current token
  (valid cache length is ``pos + 1``).

Grid ``(slots, kv_heads, pages_per_slot)``: for a fixed (slot, kv head)
the kernel streams that slot's pages in order, carrying the online-softmax
running max/denominator/accumulator for the head's GQA query group in VMEM
scratch — the same accumulation scheme as the verified flash_v2 kernel
(ops/attention.py), so numerics match the dense reference to float32
round-off. The page table and positions ride scalar prefetch
(``PrefetchScalarGridSpec``) because the k/v BlockSpec index maps need
them to translate (slot, page-slot) -> physical page id before the DMA.

Dispatch mirrors ``ops.attention.attention``: ``resolve_paged_impl``
picks the kernel on TPU, the gather+dense reference on CPU — unless
interpret mode is forced (``MLT_ATTN_INTERPRET=1``), which runs the real
kernel code path under the Pallas interpreter so tier-1 exercises it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .attention import (
    NEG_INF,
    _on_tpu,
    _PALLAS_OK,
    _repeat_kv,
    interpret_forced,
)

if _PALLAS_OK:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu


def resolve_paged_impl(impl: str = "auto") -> str:
    """Resolve a serving ``attention_impl`` knob to the paged-decode path:
    ``kernel`` (Pallas, page-table indexed) or ``reference``
    (gather+dense). ``flash`` counts as an explicit kernel opt-in;
    ``dense`` as an explicit reference opt-in."""
    if impl in ("kernel", "flash"):
        return "kernel"
    if impl in ("reference", "dense"):
        return "reference"
    if impl != "auto":
        raise ValueError(
            f"unknown paged attention impl '{impl}' "
            "(auto | flash | kernel | reference | dense)")
    if _PALLAS_OK and (_on_tpu() or interpret_forced()):
        return "kernel"
    return "reference"


# ---------------------------------------------------------------------------
# pallas kernel
# ---------------------------------------------------------------------------

def _paged_decode_kernel(pt_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                         m_scr, l_scr, acc_scr, *, page_size: int,
                         pages_per_slot: int, scale: float):
    """Grid (slot, kv_head, page-slot); refs:
    q [1, n_rep, d] (this kv head's GQA query group), k/v [1, page_size,
    1, d] (the physical page the index map resolved via the page table).
    Scratch carries the online softmax across the page-slot grid dim."""
    s = pl.program_id(0)
    p = pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    pos = pos_ref[s]
    n_rep = q_ref.shape[1]
    # pages wholly past the current position contribute nothing — skip the
    # flops (the DMA already happened; it fetched the scratch page or a
    # masked page, both harmless)
    live = p * page_size <= pos

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale          # [n_rep, d]
        k = k_ref[0, :, 0, :].astype(jnp.float32)          # [page_size, d]
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        logits = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        k_pos = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (n_rep, page_size), 1)
        logits = jnp.where(k_pos <= pos, logits, NEG_INF)
        m_prev = m_scr[:]
        m_cur = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        weight = jnp.exp(logits - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = l_scr[:] * alpha + jnp.sum(weight, axis=-1,
                                              keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jnp.dot(
            weight, v, preferred_element_type=jnp.float32)
        m_scr[:] = m_new

    @pl.when(p == pages_per_slot - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("page_size", "interpret"))
def _paged_decode_call(q, k_pages, v_pages, page_table, pos,
                       page_size: int, interpret=None):
    """q [slots, H, D] x pool pages [P+1, page_size, Hkv, D] -> [slots,
    H, D]. ``page_table`` may contain -1 (routed to the scratch page)."""
    if interpret is None:
        interpret = not _on_tpu()
    slots, h, d = q.shape
    hkv = k_pages.shape[2]
    n_rep = h // hkv
    pages_per_slot = page_table.shape[1]
    scale = d ** -0.5
    scratch_page = k_pages.shape[0] - 1
    safe_table = jnp.where(page_table >= 0, page_table,
                           scratch_page).astype(jnp.int32)
    pos = pos.astype(jnp.int32)

    kernel = functools.partial(
        _paged_decode_kernel, page_size=page_size,
        pages_per_slot=pages_per_slot, scale=scale)

    def q_map(s, h_, p, pt, ps):
        return (s, h_, 0)

    def kv_map(s, h_, p, pt, ps):
        return (pt[s, p], 0, h_, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(slots, hkv, pages_per_slot),
        in_specs=[
            pl.BlockSpec((1, n_rep, d), q_map),
            pl.BlockSpec((1, page_size, 1, d), kv_map),
            pl.BlockSpec((1, page_size, 1, d), kv_map),
        ],
        out_specs=pl.BlockSpec((1, n_rep, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((n_rep, 1), jnp.float32),   # running max
            pltpu.VMEM((n_rep, 1), jnp.float32),   # running denom
            pltpu.VMEM((n_rep, d), jnp.float32),   # accumulator
        ],
    )
    # q reshaped so the head dim blocks by kv-head group: heads h*n_rep..
    # (h+1)*n_rep are kv head h's GQA group (matches _repeat_kv order)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((slots, h, d), q.dtype),
        interpret=interpret,
    )(safe_table, pos, q, k_pages, v_pages)


# ---------------------------------------------------------------------------
# gather+dense reference (the pre-kernel engine math)
# ---------------------------------------------------------------------------

def paged_decode_reference(q, k_pages, v_pages, page_table, pos,
                           page_size: int):
    """Dense-view reference: gather every slot's pages into
    [slots, max_len] (the materialization the kernel exists to avoid) and
    run masked attention. Used for parity tests and as the CPU path."""
    slots, h, d = q.shape
    hkv = k_pages.shape[2]
    n_rep = h // hkv
    safe = jnp.maximum(page_table, 0)
    kd = jnp.take(k_pages, safe, axis=0)     # [slots, pps, ps, hkv, d]
    vd = jnp.take(v_pages, safe, axis=0)
    s_, p_, ps_, hh, dd = kd.shape
    kd = _repeat_kv(kd.reshape(s_, p_ * ps_, hh, dd), n_rep)
    vd = _repeat_kv(vd.reshape(s_, p_ * ps_, hh, dd), n_rep)
    scale = d ** -0.5
    logits = jnp.einsum("bhd,bkhd->bhk", q.astype(jnp.float32),
                        kd.astype(jnp.float32),
                        preferred_element_type=jnp.float32) * scale
    k_pos = jnp.arange(p_ * ps_)[None, None, :]
    logits = jnp.where(k_pos <= pos[:, None, None], logits, NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhk,bkhd->bhd", weights,
                      vd.astype(jnp.float32)).astype(q.dtype)


def paged_attention(q, k_pages, v_pages, page_table, pos, *,
                    page_size: int, impl: str = "auto",
                    interpret=None):
    """Dispatching paged-decode attention (see module docstring)."""
    impl = resolve_paged_impl(impl)
    if impl == "reference":
        return paged_decode_reference(q, k_pages, v_pages, page_table,
                                      pos, page_size)
    return _paged_decode_call(q, k_pages, v_pages, page_table, pos,
                              page_size, interpret=interpret)
