"""Paged-decode attention: one token per slot straight off the KV page pool.

The paged engine (serving/paged.py) historically materialized a dense
``[slots, max_len]`` KV view per layer per decode tick (``jnp.take`` over
the page table) and ran plain masked attention on it — HBM traffic on the
order of the whole cache for every generated token. This module computes
the same attention by indexing the page pool THROUGH the page table inside
a Pallas kernel: each grid step DMAs exactly one (page, kv-head) tile from
HBM into VMEM, so the bytes read per tick are the slot's *live* pages once
— never a gathered copy of the full view.

Layout (one layer of the pool, see serving/paged.py):

- q:          [slots, n_heads, head_dim]   — the current decode token,
  post-RoPE (its KV must already be written into the pool; the kernel
  masks ``k_pos <= pos`` so the current position participates).
- k/v pages:  [n_pages + 1, page_size, n_kv_heads, head_dim] — the LAST
  physical page is the scratch page; page-table entries < 0 are routed to
  it (they are masked out by ``pos`` anyway, the routing just keeps the
  DMA addresses in-bounds).
- page_table: [slots, pages_per_slot] int32, -1 = unmapped.
- pos:        [slots] int32 absolute position of the current token
  (valid cache length is ``pos + 1``).

Grid ``(slots, kv_heads, pages_per_slot)``: for a fixed (slot, kv head)
the kernel streams that slot's pages in order, carrying the online-softmax
running max/denominator/accumulator for the head's GQA query group in VMEM
scratch — the same accumulation scheme as the verified flash_v2 kernel
(ops/attention.py), so numerics match the dense reference to float32
round-off. The page table and positions ride scalar prefetch
(``PrefetchScalarGridSpec``) because the k/v BlockSpec index maps need
them to translate (slot, page-slot) -> physical page id before the DMA.

Beyond decode, this module carries the other two KV-heavy moments of the
serving path (docs/serving.md "Attention kernels"):

- **multi-row paged prefill** (``_paged_prefill_call`` /
  ``paged_prefill_attention``): on a prefix-cache hit, a chunk of query
  tokens attends the ``base`` cached prefix tokens IN PLACE through the
  same page-table-indexed BlockSpec design (page ids + base on scalar
  prefetch, grid (kv_head, q_block, page)), emitting a partial softmax
  state ``(o, lse)`` that ``merge_softmax_states`` LSE-merges with the
  local causal flash over the suffix — the admission-time dense
  ``gather_prefix_pages`` copy becomes the CPU/reference fallback only.
- **batched speculative verify** (``_paged_verify_call`` /
  ``paged_verify_attention``): the in-engine speculative-decoding verify
  dispatch (docs/serving.md "Speculative decoding") — every decode
  slot's (k+1)-token chunk attends its own prefix pages in place
  through the page table (per-row page ids AND per-row ``base`` on
  scalar prefetch), merged with the chunk's local causal part. The
  verify chunk is the prefill kernel's q-chunk form, batched per slot;
  ``paged_verify_reference`` is the gather+dense fallback.
- **int8 KV pages**: all kernels take optional per-vector f32 dequant
  scales riding the same page-table-indexed operands as the pages, so a
  ``kv_dtype="int8"`` pool (double the resident pages per HBM byte)
  runs the kernel path instead of downgrading to the reference.

Dispatch mirrors ``ops.attention.attention``: ``resolve_paged_impl``
picks the kernel on TPU, the gather+dense reference on CPU — unless
interpret mode is forced (``MLT_ATTN_INTERPRET=1``), which runs the real
kernel code path under the Pallas interpreter so tier-1 exercises it.
An EXPLICIT kernel request that cannot be honored raises the typed
:class:`KernelUnavailableError` instead of silently downgrading.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .attention import (
    NEG_INF,
    _fit_block,
    _flash_fwd_v2_cached_bounded,
    _on_tpu,
    _PALLAS_OK,
    _repeat_kv,
    interpret_forced,
)

if _PALLAS_OK:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu


class KernelUnavailableError(ValueError):
    """An EXPLICIT ``attention_impl="kernel"``/``"flash"`` request cannot
    be honored (Pallas missing from the jax build). Raised at engine
    construction — a silent downgrade would quietly serve on the
    reference path while the operator believes the kernel is live.
    ``auto`` may still fall back (warned once)."""


_warned_auto_fallback = False


def resolve_paged_impl(impl: str = "auto") -> str:
    """Resolve a serving ``attention_impl`` knob to the paged-decode path:
    ``kernel`` (Pallas, page-table indexed) or ``reference``
    (gather+dense). ``flash`` counts as an explicit kernel opt-in;
    ``dense`` as an explicit reference opt-in. Explicit kernel requests
    that cannot be honored raise :class:`KernelUnavailableError` —
    ``auto`` falls back to the reference (warned once when the fallback
    is a missing Pallas rather than the normal CPU default)."""
    global _warned_auto_fallback

    if impl in ("kernel", "flash"):
        if not _PALLAS_OK:
            raise KernelUnavailableError(
                f"attention_impl='{impl}' requested but Pallas is "
                "unavailable in this jax build — use 'auto' (falls back "
                "to the gather+dense reference) or 'reference'")
        return "kernel"
    if impl in ("reference", "dense"):
        return "reference"
    if impl != "auto":
        raise ValueError(
            f"unknown paged attention impl '{impl}' "
            "(auto | flash | kernel | reference | dense)")
    if not _PALLAS_OK:
        if not _warned_auto_fallback:
            _warned_auto_fallback = True
            from ..utils import logger

            logger.warning(
                "paged attention: Pallas unavailable — attention_impl "
                "'auto' resolves to the gather+dense reference path")
        return "reference"
    if _on_tpu() or interpret_forced():
        return "kernel"
    return "reference"


# ---------------------------------------------------------------------------
# pallas kernel
# ---------------------------------------------------------------------------

def _decode_page_update(q_ref, k, v, m_scr, l_scr, acc_scr, *,
                        p, pos, page_size: int, scale: float):
    """Shared online-softmax update over one (already dequantized) page
    tile — the native and int8 decode kernels differ only in how k/v
    reach f32."""
    n_rep = q_ref.shape[1]
    q = q_ref[0].astype(jnp.float32) * scale              # [n_rep, d]
    logits = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
    k_pos = p * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (n_rep, page_size), 1)
    logits = jnp.where(k_pos <= pos, logits, NEG_INF)
    m_prev = m_scr[:]
    m_cur = jnp.max(logits, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    weight = jnp.exp(logits - m_new)
    alpha = jnp.exp(m_prev - m_new)
    l_scr[:] = l_scr[:] * alpha + jnp.sum(weight, axis=-1,
                                          keepdims=True)
    acc_scr[:] = acc_scr[:] * alpha + jnp.dot(
        weight, v, preferred_element_type=jnp.float32)
    m_scr[:] = m_new


def _paged_decode_kernel(pt_ref, pos_ref, q_ref, k_ref, v_ref, *refs,
                         page_size: int, pages_per_slot: int,
                         scale: float, quantized: bool):
    """Grid (slot, kv_head, page-slot); refs:
    q [1, n_rep, d] (this kv head's GQA query group), k/v [1, page_size,
    1, d] (the physical page the index map resolved via the page table).
    Scratch carries the online softmax across the page-slot grid dim.

    ``quantized`` (static) inserts two extra refs after v: the int8
    pool's per-vector f32 dequant scales (ks/vs [1, page_size, 1]),
    riding the SAME page-table-indexed BlockSpecs as the pages —
    dequantization happens in-register, everything else is one code
    path."""
    if quantized:
        ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr = refs
    else:
        o_ref, m_scr, l_scr, acc_scr = refs
    s = pl.program_id(0)
    p = pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    pos = pos_ref[s]
    # pages wholly past the current position contribute nothing — skip the
    # flops (the DMA already happened; it fetched the scratch page or a
    # masked page, both harmless)
    live = p * page_size <= pos

    @pl.when(live)
    def _compute():
        k = k_ref[0, :, 0, :].astype(jnp.float32)          # [page_size, d]
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        if quantized:
            k = k * ks_ref[0, :, 0][:, None]
            v = v * vs_ref[0, :, 0][:, None]
        _decode_page_update(q_ref, k, v, m_scr, l_scr, acc_scr,
                            p=p, pos=pos, page_size=page_size,
                            scale=scale)

    @pl.when(p == pages_per_slot - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("page_size", "interpret"))
def _paged_decode_call(q, k_pages, v_pages, page_table, pos,
                       page_size: int, k_scale=None, v_scale=None,
                       interpret=None):
    """q [slots, H, D] x pool pages [P+1, page_size, Hkv, D] -> [slots,
    H, D]. ``page_table`` may contain -1 (routed to the scratch page).
    ``k_scale``/``v_scale`` ([P+1, page_size, Hkv] f32) select the int8
    kernel: pages are dequantized per vector inside the kernel."""
    if interpret is None:
        interpret = not _on_tpu()
    slots, h, d = q.shape
    hkv = k_pages.shape[2]
    n_rep = h // hkv
    pages_per_slot = page_table.shape[1]
    scale = d ** -0.5
    scratch_page = k_pages.shape[0] - 1
    safe_table = jnp.where(page_table >= 0, page_table,
                           scratch_page).astype(jnp.int32)
    pos = pos.astype(jnp.int32)
    quantized = k_scale is not None

    kernel = functools.partial(
        _paged_decode_kernel, page_size=page_size,
        pages_per_slot=pages_per_slot, scale=scale, quantized=quantized)

    def q_map(s, h_, p, pt, ps):
        return (s, h_, 0)

    def kv_map(s, h_, p, pt, ps):
        return (pt[s, p], 0, h_, 0)

    def sc_map(s, h_, p, pt, ps):
        return (pt[s, p], 0, h_)

    in_specs = [
        pl.BlockSpec((1, n_rep, d), q_map),
        pl.BlockSpec((1, page_size, 1, d), kv_map),
        pl.BlockSpec((1, page_size, 1, d), kv_map),
    ]
    operands = [safe_table, pos, q, k_pages, v_pages]
    if quantized:
        in_specs += [pl.BlockSpec((1, page_size, 1), sc_map),
                     pl.BlockSpec((1, page_size, 1), sc_map)]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(slots, hkv, pages_per_slot),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, n_rep, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((n_rep, 1), jnp.float32),   # running max
            pltpu.VMEM((n_rep, 1), jnp.float32),   # running denom
            pltpu.VMEM((n_rep, d), jnp.float32),   # accumulator
        ],
    )
    # q reshaped so the head dim blocks by kv-head group: heads h*n_rep..
    # (h+1)*n_rep are kv head h's GQA group (matches _repeat_kv order)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((slots, h, d), q.dtype),
        interpret=interpret,
    )(*operands)


# ---------------------------------------------------------------------------
# multi-row paged prefill: a prompt chunk over shared prefix pages in place
# ---------------------------------------------------------------------------

def _prefill_page_update(q_ref, k, v, m_scr, l_scr, acc_scr, *,
                         p, base, page_size: int, scale: float):
    """Shared prefill online-softmax update over one (already
    dequantized) prefix page tile — positions at or past ``base`` are
    masked; no causal mask (every prefix position precedes every query
    row)."""
    block_rows = q_ref.shape[1]
    q = q_ref[0].astype(jnp.float32) * scale          # [block_rows, d]
    logits = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
    k_pos = p * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (block_rows, page_size), 1)
    logits = jnp.where(k_pos < base, logits, NEG_INF)
    m_prev = m_scr[:]
    m_cur = jnp.max(logits, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    weight = jnp.exp(logits - m_new)
    alpha = jnp.exp(m_prev - m_new)
    l_scr[:] = l_scr[:] * alpha + jnp.sum(weight, axis=-1,
                                          keepdims=True)
    acc_scr[:] = acc_scr[:] * alpha + jnp.dot(
        weight, v, preferred_element_type=jnp.float32)
    m_scr[:] = m_new


def _paged_prefill_kernel(ids_ref, base_ref, q_ref, k_ref, v_ref, *refs,
                          page_size: int, pages_per_slot: int,
                          scale: float, quantized: bool):
    """Grid (kv_head, q_block, page-slot); refs:
    q [1, block_rows, d] (this kv head's GQA query rows, rows = token x
    n_rep), k/v [1, page_size, 1, d] — the physical page the index map
    resolved through the slot's page ids. Every prefix position
    (0..base-1) precedes every query row, so no causal mask is needed;
    pages at or past ``base`` (and -1 entries, routed to the scratch
    page) are masked out wholesale. Scratch carries the online softmax
    across the page-slot grid dim; the finalize step emits (o, lse) so
    the caller can LSE-merge with the local causal flash over the
    suffix chunk.

    ``quantized`` (static) inserts two extra refs after v: the int8
    pool's per-vector f32 dequant scales (ks/vs [1, page_size, 1]) on
    the same page-table-indexed BlockSpecs, dequantized in-register —
    one code path for both pool dtypes."""
    if quantized:
        ks_ref, vs_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    else:
        o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    p = pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    base = base_ref[0]
    block_rows = q_ref.shape[1]
    live = p * page_size < base

    @pl.when(live)
    def _compute():
        k = k_ref[0, :, 0, :].astype(jnp.float32)      # [page_size, d]
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        if quantized:
            k = k * ks_ref[0, :, 0][:, None]
            v = v * vs_ref[0, :, 0][:, None]
        _prefill_page_update(q_ref, k, v, m_scr, l_scr, acc_scr,
                             p=p, base=base, page_size=page_size,
                             scale=scale)

    @pl.when(p == pages_per_slot - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = acc_scr[:] / l
        lse_ref[0] = jnp.broadcast_to(m_scr[:] + jnp.log(l),
                                      (block_rows, 8))


@functools.partial(jax.jit, static_argnames=("page_size", "interpret"))
def _paged_prefill_call(q, k_pages, v_pages, page_ids, base,
                        page_size: int, k_scale=None, v_scale=None,
                        interpret=None):
    """q [S, H, D] (one admission's prompt chunk, batch=1) attends over
    the ``base`` prefix tokens stored in pool pages ``page_ids``
    ([pages_per_slot] int32, -1 past the prefix → scratch page) —
    in place, through the page table, never gathered. Returns
    (o [S, H, D] f32, lse [S, H] f32) — one partial softmax state per
    query row, LSE-merged by the caller with the local causal flash over
    the suffix (``merge_softmax_states``)."""
    if interpret is None:
        interpret = not _on_tpu()
    s, h, d = q.shape
    hkv = k_pages.shape[2]
    n_rep = h // hkv
    pages_per_slot = page_ids.shape[0]
    scale = d ** -0.5
    scratch_page = k_pages.shape[0] - 1
    safe_ids = jnp.where(page_ids >= 0, page_ids,
                         scratch_page).astype(jnp.int32)
    base = jnp.asarray(base, jnp.int32).reshape(1)
    quantized = k_scale is not None

    # rows grouped per kv head (head h*n_rep+r is kv head h's GQA group,
    # matching _repeat_kv order): [S, H, D] -> [Hkv, S*n_rep, D]
    rows = s * n_rep
    qg = q.reshape(s, hkv, n_rep, d).transpose(1, 0, 2, 3).reshape(
        hkv, rows, d)
    block_rows = _fit_block(rows, 256)
    pad_rows = (-rows) % block_rows
    if pad_rows:
        qg = jnp.pad(qg, ((0, 0), (0, pad_rows), (0, 0)))
    padded_rows = rows + pad_rows

    kernel = functools.partial(
        _paged_prefill_kernel, page_size=page_size,
        pages_per_slot=pages_per_slot, scale=scale, quantized=quantized)

    def q_map(h_, qb, p, ids, b):
        return (h_, qb, 0)

    def kv_map(h_, qb, p, ids, b):
        return (ids[p], 0, h_, 0)

    def sc_map(h_, qb, p, ids, b):
        return (ids[p], 0, h_)

    in_specs = [
        pl.BlockSpec((1, block_rows, d), q_map),
        pl.BlockSpec((1, page_size, 1, d), kv_map),
        pl.BlockSpec((1, page_size, 1, d), kv_map),
    ]
    operands = [safe_ids, base, qg, k_pages, v_pages]
    if quantized:
        in_specs += [pl.BlockSpec((1, page_size, 1), sc_map),
                     pl.BlockSpec((1, page_size, 1), sc_map)]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(hkv, padded_rows // block_rows, pages_per_slot),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_rows, d), q_map),
            pl.BlockSpec((1, block_rows, 8), q_map),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_rows, 1), jnp.float32),   # running max
            pltpu.VMEM((block_rows, 1), jnp.float32),   # running denom
            pltpu.VMEM((block_rows, d), jnp.float32),   # accumulator
        ],
    )
    o, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((hkv, padded_rows, d), jnp.float32),
            jax.ShapeDtypeStruct((hkv, padded_rows, 8), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)
    o = o[:, :rows].reshape(hkv, s, n_rep, d).transpose(1, 0, 2, 3)
    lse = lse[:, :rows, 0].reshape(hkv, s, n_rep).transpose(1, 0, 2)
    return o.reshape(s, h, d), lse.reshape(s, h)


def merge_softmax_states(o_a, lse_a, o_b, lse_b):
    """LSE-merge two partial attention states over disjoint kv sets:
    ``o_*`` [B, S, H, D] (any float dtype), ``lse_*`` [B, H, S] f32
    (the flash kernels' lse layout). Returns the combined f32 output —
    exactly softmax over the union, up to accumulation-order round-off
    (the documented cold-vs-hit tolerance contract, docs/serving.md
    "Attention kernels")."""
    la = lse_a.transpose(0, 2, 1)[..., None]       # [B, S, H, 1]
    lb = lse_b.transpose(0, 2, 1)[..., None]
    m = jnp.maximum(la, lb)
    wa = jnp.exp(la - m)
    wb = jnp.exp(lb - m)
    return (o_a.astype(jnp.float32) * wa
            + o_b.astype(jnp.float32) * wb) / (wa + wb)


def paged_prefix_part(q, k_pages, v_pages, page_ids, base, *,
                      page_size: int, k_scale=None, v_scale=None,
                      interpret=None):
    """Batch-1 convenience over :func:`_paged_prefill_call`: q
    [1, S, H, D] -> (o [1, S, H, D] f32, lse [1, H, S] f32) in the flash
    lse layout, ready for :func:`merge_softmax_states`."""
    o, lse = _paged_prefill_call(q[0], k_pages, v_pages, page_ids, base,
                                 page_size, k_scale=k_scale,
                                 v_scale=v_scale, interpret=interpret)
    return o[None], lse.T[None]


def paged_prefill_attention(q, k_cache, v_cache, q_start, k_pages,
                            v_pages, page_ids, base, *, page_size: int,
                            k_scale=None, v_scale=None, interpret=None):
    """Merged suffix-prefill attention on a prefix-cache hit: q
    [1, S, H, D] rows at absolute positions ``q_start + i``; local cache
    k_cache/v_cache [1, M, H, D] (kv repeated to q heads, rows valid
    from ``base``); prefix tokens 0..base-1 live in pool pages and are
    attended IN PLACE through ``page_ids``. Returns the merged [1, S, H,
    D] f32 output — the hit-path analog of flash_attention_cached over a
    densely gathered cache, without the gather."""
    o_loc, lse_loc = _flash_fwd_v2_cached_bounded(
        q, k_cache, v_cache, q_start, base, interpret=interpret)
    o_pre, lse_pre = paged_prefix_part(
        q, k_pages, v_pages, page_ids, base, page_size=page_size,
        k_scale=k_scale, v_scale=v_scale, interpret=interpret)
    return merge_softmax_states(o_pre, lse_pre, o_loc, lse_loc)


# ---------------------------------------------------------------------------
# batched multi-row verify: a speculative chunk per slot over the page pool
# ---------------------------------------------------------------------------

def _paged_verify_kernel(ids_ref, base_ref, q_ref, k_ref, v_ref, *refs,
                         page_size: int, pages_per_slot: int,
                         kv_heads: int, scale: float, quantized: bool):
    """Grid (slot x kv_head, q_block, page-slot) — the speculative-verify
    form of :func:`_paged_prefill_kernel`: same per-page prefix update
    (``_prefill_page_update``), but batched over every decode slot at
    once, each row reading ITS OWN page ids and prefix bound from the
    prefetched ``ids_ref [slots, pages_per_slot]`` / ``base_ref [slots]``
    (the leading grid dim collapses slot and kv head so the q blocks stay
    the prefill kernel's 2D row tiles). The chunk's own causal part is
    NOT computed here — the caller LSE-merges it
    (:func:`chunk_causal_part` + :func:`merge_softmax_states`), exactly
    like the prefill hit path merges its local flash."""
    if quantized:
        ks_ref, vs_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    else:
        o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    g = pl.program_id(0)
    p = pl.program_id(2)

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    base = base_ref[g // kv_heads]
    block_rows = q_ref.shape[1]
    live = p * page_size < base

    @pl.when(live)
    def _compute():
        k = k_ref[0, :, 0, :].astype(jnp.float32)      # [page_size, d]
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        if quantized:
            k = k * ks_ref[0, :, 0][:, None]
            v = v * vs_ref[0, :, 0][:, None]
        _prefill_page_update(q_ref, k, v, m_scr, l_scr, acc_scr,
                             p=p, base=base, page_size=page_size,
                             scale=scale)

    @pl.when(p == pages_per_slot - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = acc_scr[:] / l
        lse_ref[0] = jnp.broadcast_to(m_scr[:] + jnp.log(l),
                                      (block_rows, 8))


@functools.partial(jax.jit, static_argnames=("page_size", "interpret"))
def _paged_verify_call(q, k_pages, v_pages, page_table, base,
                       page_size: int, k_scale=None, v_scale=None,
                       interpret=None):
    """q [slots, S, H, D] (every slot's speculative verify chunk) attends
    each row's prefix tokens 0..base[r]-1 IN PLACE through the page table
    — the batched form of :func:`_paged_prefill_call`. Returns
    (o [slots, S, H, D] f32, lse [slots, H, S] f32) partial softmax
    states in the flash lse layout, ready for
    :func:`merge_softmax_states` with the chunk's local causal part."""
    if interpret is None:
        interpret = not _on_tpu()
    r_, s, h, d = q.shape
    hkv = k_pages.shape[2]
    n_rep = h // hkv
    pages_per_slot = page_table.shape[1]
    scale = d ** -0.5
    scratch_page = k_pages.shape[0] - 1
    safe_table = jnp.where(page_table >= 0, page_table,
                           scratch_page).astype(jnp.int32)
    base = base.astype(jnp.int32)
    quantized = k_scale is not None

    # rows grouped per (slot, kv head): [R, S, H, D] ->
    # [R*Hkv, S*n_rep, D] so the q tiles are exactly the prefill
    # kernel's shape class and the leading grid dim carries both ids
    rows = s * n_rep
    qg = q.reshape(r_, s, hkv, n_rep, d).transpose(
        0, 2, 1, 3, 4).reshape(r_ * hkv, rows, d)
    block_rows = _fit_block(rows, 256)
    pad_rows = (-rows) % block_rows
    if pad_rows:
        qg = jnp.pad(qg, ((0, 0), (0, pad_rows), (0, 0)))
    padded_rows = rows + pad_rows

    kernel = functools.partial(
        _paged_verify_kernel, page_size=page_size,
        pages_per_slot=pages_per_slot, kv_heads=hkv, scale=scale,
        quantized=quantized)

    def q_map(g, qb, p, ids, b):
        return (g, qb, 0)

    def kv_map(g, qb, p, ids, b):
        return (ids[g // hkv, p], 0, g % hkv, 0)

    def sc_map(g, qb, p, ids, b):
        return (ids[g // hkv, p], 0, g % hkv)

    in_specs = [
        pl.BlockSpec((1, block_rows, d), q_map),
        pl.BlockSpec((1, page_size, 1, d), kv_map),
        pl.BlockSpec((1, page_size, 1, d), kv_map),
    ]
    operands = [safe_table, base, qg, k_pages, v_pages]
    if quantized:
        in_specs += [pl.BlockSpec((1, page_size, 1), sc_map),
                     pl.BlockSpec((1, page_size, 1), sc_map)]
        operands += [k_scale, v_scale]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(r_ * hkv, padded_rows // block_rows, pages_per_slot),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_rows, d), q_map),
            pl.BlockSpec((1, block_rows, 8), q_map),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_rows, 1), jnp.float32),   # running max
            pltpu.VMEM((block_rows, 1), jnp.float32),   # running denom
            pltpu.VMEM((block_rows, d), jnp.float32),   # accumulator
        ],
    )
    o, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((r_ * hkv, padded_rows, d),
                                 jnp.float32),
            jax.ShapeDtypeStruct((r_ * hkv, padded_rows, 8),
                                 jnp.float32),
        ],
        interpret=interpret,
    )(*operands)
    o = o[:, :rows].reshape(r_, hkv, s, n_rep, d).transpose(
        0, 2, 1, 3, 4).reshape(r_, s, h, d)
    lse = lse[:, :rows, 0].reshape(r_, hkv, s, n_rep).transpose(
        0, 1, 3, 2).reshape(r_, h, s)
    return o, lse


def chunk_causal_part(q, k, v):
    """Closed-form causal partial softmax of a verify chunk over ITSELF:
    q [B, S, H, D], k/v [B, S, Hkv, D] (the chunk's own just-computed
    KV — for int8 pools the caller passes the quantize->dequantize
    round-trip so the chunk attends exactly what the pool stores).
    S is tiny (k draft tokens + 1), so a dense S x S pass beats a flash
    instance. Returns (o [B, S, H, D] f32, lse [B, H, S] f32) for
    :func:`merge_softmax_states` with the paged prefix part."""
    b, s, h, d = q.shape
    n_rep = h // k.shape[2]
    k = _repeat_kv(k.astype(jnp.float32), n_rep)
    v = _repeat_kv(v.astype(jnp.float32), n_rep)
    scale = d ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k,
                        preferred_element_type=jnp.float32) * scale
    i = jnp.arange(s)
    causal = i[None, :] <= i[:, None]                  # [q, kv]
    logits = jnp.where(causal[None, None], logits, NEG_INF)
    m = jnp.max(logits, axis=-1)                       # [B, H, S]
    w = jnp.exp(logits - m[..., None])
    l = jnp.maximum(jnp.sum(w, axis=-1), 1e-30)
    o = jnp.einsum("bhqk,bkhd->bqhd", w / l[..., None], v)
    return o, m + jnp.log(l)


def paged_verify_reference(q, chunk_k, chunk_v, k_pages, v_pages,
                           page_table, base, page_size: int,
                           k_scale=None, v_scale=None):
    """Dense-view verify reference: gather every slot's pages into
    [slots, max_len] (the materialization the verify kernel avoids),
    splice the chunk KV at positions ``base[r] + i``, and run one masked
    softmax with the per-position causal bound ``k_pos <= base[r] + i``.
    Chunk lanes past the view tail drop (see below); lanes past a row's
    accepted length are computed-and-discarded garbage, exactly like the
    kernel path."""
    r_, s, h, d = q.shape
    hkv = k_pages.shape[2]
    n_rep = h // hkv
    safe = jnp.maximum(page_table, 0)
    kd = jnp.take(k_pages, safe, axis=0)     # [slots, pps, ps, hkv, d]
    vd = jnp.take(v_pages, safe, axis=0)
    s_, p_, ps_, hh, dd = kd.shape
    m = p_ * ps_
    kd = kd.reshape(s_, m, hh, dd).astype(jnp.float32)
    vd = vd.reshape(s_, m, hh, dd).astype(jnp.float32)
    if k_scale is not None:
        ksc = jnp.take(k_scale, safe, axis=0).reshape(s_, m, hh)
        vsc = jnp.take(v_scale, safe, axis=0).reshape(s_, m, hh)
        kd = kd * ksc[..., None]
        vd = vd * vsc[..., None]
    positions = base[:, None] + jnp.arange(s)[None, :]   # [B, S]
    rows = jnp.arange(r_)[:, None]
    # mode="drop": a chunk lane past the view tail (row at the very end
    # of its budget speculating fewer than S-1 tokens) must vanish, not
    # clamp onto the row's real final entry
    kd = kd.at[rows, positions].set(chunk_k.astype(jnp.float32),
                                    mode="drop")
    vd = vd.at[rows, positions].set(chunk_v.astype(jnp.float32),
                                    mode="drop")
    kd = _repeat_kv(kd, n_rep)
    vd = _repeat_kv(vd, n_rep)
    scale = d ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), kd,
                        preferred_element_type=jnp.float32) * scale
    k_pos = jnp.arange(m)[None, None, :]
    mask = k_pos <= positions[:, :, None]               # [B, S, M]
    logits = jnp.where(mask[:, None], logits, NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, vd)


def paged_verify_attention(q, chunk_k, chunk_v, k_pages, v_pages,
                           page_table, base, *, page_size: int,
                           impl: str = "auto", k_scale=None,
                           v_scale=None, interpret=None):
    """Speculative multi-token verify attention over the page pool: q
    [slots, S, H, D] are each row's draft positions ``base[r]..base[r] +
    S - 1`` (S = k + 1: the committed last token plus k draft tokens);
    their KV (``chunk_k``/``chunk_v`` [slots, S, Hkv, D]) has already
    been written into the pool. The kernel path attends the prefix pages
    in place — the verify chunk is literally the prefill kernel's
    q-chunk form, batched per slot — and LSE-merges the chunk's local
    causal part; no dense gather, int8 pools included. Returns the
    merged [slots, S, H, D] f32 output."""
    impl = resolve_paged_impl(impl)
    if impl == "reference":
        return paged_verify_reference(
            q, chunk_k, chunk_v, k_pages, v_pages, page_table, base,
            page_size, k_scale=k_scale, v_scale=v_scale)
    o_pre, lse_pre = _paged_verify_call(
        q, k_pages, v_pages, page_table, base, page_size,
        k_scale=k_scale, v_scale=v_scale, interpret=interpret)
    o_loc, lse_loc = chunk_causal_part(q, chunk_k, chunk_v)
    return merge_softmax_states(o_pre, lse_pre, o_loc, lse_loc)


# ---------------------------------------------------------------------------
# gather+dense reference (the pre-kernel engine math)
# ---------------------------------------------------------------------------

def paged_decode_reference(q, k_pages, v_pages, page_table, pos,
                           page_size: int, k_scale=None, v_scale=None):
    """Dense-view reference: gather every slot's pages into
    [slots, max_len] (the materialization the kernel exists to avoid) and
    run masked attention. Used for parity tests and as the CPU path.
    int8 pools pass per-vector ``k_scale``/``v_scale`` ([P+1, page_size,
    Hkv] f32) and dequantize after the gather."""
    slots, h, d = q.shape
    hkv = k_pages.shape[2]
    n_rep = h // hkv
    safe = jnp.maximum(page_table, 0)
    kd = jnp.take(k_pages, safe, axis=0)     # [slots, pps, ps, hkv, d]
    vd = jnp.take(v_pages, safe, axis=0)
    s_, p_, ps_, hh, dd = kd.shape
    kd = kd.reshape(s_, p_ * ps_, hh, dd).astype(jnp.float32)
    vd = vd.reshape(s_, p_ * ps_, hh, dd).astype(jnp.float32)
    if k_scale is not None:
        ksc = jnp.take(k_scale, safe, axis=0).reshape(s_, p_ * ps_, hh)
        vsc = jnp.take(v_scale, safe, axis=0).reshape(s_, p_ * ps_, hh)
        kd = kd * ksc[..., None]
        vd = vd * vsc[..., None]
    kd = _repeat_kv(kd, n_rep)
    vd = _repeat_kv(vd, n_rep)
    scale = d ** -0.5
    logits = jnp.einsum("bhd,bkhd->bhk", q.astype(jnp.float32), kd,
                        preferred_element_type=jnp.float32) * scale
    k_pos = jnp.arange(p_ * ps_)[None, None, :]
    logits = jnp.where(k_pos <= pos[:, None, None], logits, NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhk,bkhd->bhd", weights, vd).astype(q.dtype)


def paged_attention(q, k_pages, v_pages, page_table, pos, *,
                    page_size: int, impl: str = "auto",
                    k_scale=None, v_scale=None, interpret=None):
    """Dispatching paged-decode attention (see module docstring).
    ``k_scale``/``v_scale`` select the int8 path in both impls."""
    impl = resolve_paged_impl(impl)
    if impl == "reference":
        return paged_decode_reference(q, k_pages, v_pages, page_table,
                                      pos, page_size, k_scale=k_scale,
                                      v_scale=v_scale)
    return _paged_decode_call(q, k_pages, v_pages, page_table, pos,
                              page_size, k_scale=k_scale,
                              v_scale=v_scale, interpret=interpret)
