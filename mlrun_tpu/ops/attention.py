"""Attention ops: reference, fused pallas flash kernel, and dispatch.

The MXU wants large fused matmuls; the HBM wants O(S) memory — flash-style
blockwise softmax delivers both. Three implementations:

- ``attention_reference``: pure jnp (einsum), GQA, causal — differentiable
  everywhere (CPU mesh tests, small shapes, fallback).
- ``flash_attention_mlt``: our pallas TPU kernel (forward) with a custom-vjp
  blockwise backward (lax.scan recompute, O(S·D) residual memory).
- ``attention``: dispatcher — on TPU training paths prefers the jax pallas
  library kernels (which include tuned fwd+bwd), otherwise reference.

No reference-repo analog: the reference has no attention code at all
(SURVEY.md §5.7) — this capability is TPU-native new work.
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -2.0**30


def interpret_forced() -> bool:
    """``MLT_ATTN_INTERPRET=1`` makes every ``auto`` dispatcher pick the
    Pallas kernels even off-TPU (interpret mode) — how tier-1 exercises
    the real kernel code paths on the CPU mesh."""
    return os.environ.get("MLT_ATTN_INTERPRET", "").strip().lower() in (
        "1", "true", "yes", "on")


def _repeat_kv(k: jax.Array, n_rep: int) -> jax.Array:
    """GQA: repeat kv heads to match q heads. [B, S, Hkv, D] -> [B, S, Hkv*n, D]."""
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, h, n_rep, d)
                            ).reshape(b, s, h * n_rep, d)


def attention_reference(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = True,
                        positions_q: jax.Array | None = None,
                        positions_k: jax.Array | None = None,
                        softmax_scale: float | None = None) -> jax.Array:
    """[B, Sq, Hq, D] x [B, Sk, Hkv, D] -> [B, Sq, Hq, D]; f32 softmax."""
    n_rep = q.shape[2] // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    scale = softmax_scale or (q.shape[-1] ** -0.5)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        if positions_q is None:
            positions_q = jnp.arange(q.shape[1])
        if positions_k is None:
            positions_k = jnp.arange(k.shape[1])
        mask = positions_q[:, None] >= positions_k[None, :]
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


# ---------------------------------------------------------------------------
# our pallas flash kernel (forward), causal, MHA/GQA via pre-repeated kv
# ---------------------------------------------------------------------------

def _fit_block(n: int, preferred: int) -> int:
    """Block size for a sequence of length ``n``: ``preferred`` for long
    sequences (a sub-block tail just pads — big MXU blocks beat the
    <1-block padding, measured 12x at head_dim 64; see
    ``_tuned_block_sizes``); below ``preferred``, the largest of
    (256, 128) that divides n, else the length itself — a short-prompt
    prefill no longer rounds up to the 512 block minimum."""
    if n >= preferred:
        return preferred
    for c in (256, 128):
        if c < preferred and n >= c and n % c == 0:
            return c
    return n

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                      block_k: int, seq_k: int, kv_len: int, scale: float,
                      causal: bool):
    # grid: (batch*heads, q_blocks); refs (leading block dim of 1 retained):
    #   q: [1, block_q, d], k/v: [1, seq_k, d] (full kv in VMEM per program)
    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    num_kb = seq_k // block_k

    def body(kb, carry):
        m_prev, l_prev, acc = carry
        k_blk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        k_pos = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        if kv_len != seq_k:  # mask padded kv tail
            s = jnp.where(k_pos < kv_len, s, NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.dot(p, v_blk,
                                    preferred_element_type=jnp.float32)
        return m_new, l_new, acc

    if causal:
        # only blocks with k_start <= q_end contribute
        last_kb = jnp.minimum(((qi + 1) * block_q - 1) // block_k + 1, num_kb)
    else:
        last_kb = num_kb
    m, l, acc = jax.lax.fori_loop(0, last_kb, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    # lse laid out [block_q, 8] (last dim = full array dim) to satisfy the
    # TPU (8, 128)-tiling rule on output block shapes
    lse_ref[0] = jnp.broadcast_to(m + jnp.log(l), (block_q, 8))


try:  # pallas imports kept lazy-safe for docs tooling
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _PALLAS_OK = True
except Exception:  # noqa: BLE001
    _PALLAS_OK = False


def _flash_v2_body(q_off, k_lo, q_ref, k_ref, v_ref, o_ref, lse_ref,
                   m_scr, l_scr, acc_scr, *,
                   num_kb: int, kv_len: int, scale: float, causal: bool):
    """Grid-pipelined flash forward body: grid (bh, q_blocks, k_blocks).

    Unlike the v1 kernel (full KV resident in VMEM), each program sees one
    (q_block, k_block) tile — pallas double-buffers the HBM→VMEM streams
    across the innermost grid dim, so sequence length is bounded by HBM,
    not VMEM. Running max/denominator/accumulator live in scratch that
    persists across the k grid steps of a fixed (bh, qi).

    ``q_off`` shifts every q position by an absolute offset: 0 (a static
    python int — the training/self-attention form) or a traced scalar
    (the cached-prefill form, where q rows sit at ``start + i`` against a
    KV cache whose rows start at position 0).

    ``k_lo`` masks kv positions BELOW a lower bound: 0 (static — the
    plain forms) or a traced scalar (the paged-prefill-merge form, where
    cache rows < k_lo belong to shared prefix pages attended separately
    by the paged prefill kernel and LSE-merged afterwards —
    ops/paged_attention.py).
    """
    qi = pl.program_id(1)
    kb = pl.program_id(2)
    block_q = q_ref.shape[1]
    block_k = k_ref.shape[1]
    bounded = not (isinstance(k_lo, int) and k_lo == 0)

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_start = qi * block_q
    k_start = kb * block_k
    # causal: whole tile masked out when every k is beyond every q
    # (python bool when q_off is the static 0, a traced predicate when it
    # is the dynamic cached-prefill offset — pl.when takes both)
    live = (not causal) or (k_start <= q_off + q_start + block_q - 1)
    if bounded:
        # tiles wholly below the lower bound contribute nothing
        live = live & (k_start + block_k - 1 >= k_lo)

    @pl.when(live)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        k_pos = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            q_pos = q_off + q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        if bounded:
            s = jnp.where(k_pos >= k_lo, s, NEG_INF)
        s = jnp.where(k_pos < kv_len, s, NEG_INF)
        m_prev = m_scr[:]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = l_scr[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        m_scr[:] = m_new

    @pl.when(kb == num_kb - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0] = jnp.broadcast_to(m_scr[:] + jnp.log(l), (block_q, 8))


def _flash_fwd_kernel_v2(q_ref, k_ref, v_ref, o_ref, lse_ref,
                         m_scr, l_scr, acc_scr, **kw):
    """Self-attention form: q positions aligned with kv position 0."""
    _flash_v2_body(0, 0, q_ref, k_ref, v_ref, o_ref, lse_ref,
                   m_scr, l_scr, acc_scr, **kw)


def _flash_fwd_kernel_v2_cached(q_off_ref, q_ref, k_ref, v_ref, o_ref,
                                lse_ref, m_scr, l_scr, acc_scr, **kw):
    """Cached-prefill form: q rows live at absolute positions
    ``q_off + i`` against a KV cache indexed from 0 (serving engines'
    chunked/suffix prefill — ops/attention.flash_attention_cached)."""
    _flash_v2_body(q_off_ref[0], 0, q_ref, k_ref, v_ref, o_ref, lse_ref,
                   m_scr, l_scr, acc_scr, **kw)


def _flash_fwd_kernel_v2_bounded(q_off_ref, k_lo_ref, q_ref, k_ref, v_ref,
                                 o_ref, lse_ref, m_scr, l_scr, acc_scr,
                                 **kw):
    """Bounded cached form: like the cached form, but kv rows below
    ``k_lo`` are masked out — they hold zeros where a shared prefix
    lives in pool pages instead, attended by the paged prefill kernel
    and LSE-merged with this kernel's partial state
    (ops/paged_attention.paged_prefill_attention)."""
    _flash_v2_body(q_off_ref[0], k_lo_ref[0], q_ref, k_ref, v_ref, o_ref,
                   lse_ref, m_scr, l_scr, acc_scr, **kw)


def _flash_v2_call(q, k, v, causal, block_q, block_k, interpret, q_offset,
                   k_lo=None):
    """Shared v2 plumbing (block fit, padding, fold batch*heads, grid,
    scratch) for the self-attention and cached-prefill forms — one body,
    so the two can never diverge (the cold-vs-hit parity contract rides
    on identical block/padding choices). ``q_offset=None`` selects
    the static-zero kernel; otherwise the offset rides a (1,) SMEM
    operand. ``k_lo`` (requires ``q_offset``) additionally masks kv
    rows below a traced lower bound — the paged-prefill-merge form."""
    if interpret is None:
        interpret = not _on_tpu()
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = _fit_block(sq, block_q)
    block_k = _fit_block(sk, block_k)
    orig_sq, orig_sk = sq, sk
    pad_q = (-sq) % block_q
    pad_k = (-sk) % block_k
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
        sq += pad_q
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        sk += pad_k
    scale = d ** -0.5
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    num_kb = sk // block_k
    grid = (b * h, sq // block_q, num_kb)
    static = dict(num_kb=num_kb, kv_len=orig_sk, scale=scale, causal=causal)
    if q_offset is None:
        kernel = functools.partial(_flash_fwd_kernel_v2, **static)
        off_specs, off_args = [], ()
    elif k_lo is None:
        kernel = functools.partial(_flash_fwd_kernel_v2_cached, **static)
        off_specs = [pl.BlockSpec((1,), lambda bh, i, j: (0,),
                                  memory_space=pltpu.SMEM)]
        off_args = (jnp.asarray(q_offset, jnp.int32).reshape(1),)
    else:
        kernel = functools.partial(_flash_fwd_kernel_v2_bounded, **static)
        off_specs = [pl.BlockSpec((1,), lambda bh, i, j: (0,),
                                  memory_space=pltpu.SMEM)] * 2
        off_args = (jnp.asarray(q_offset, jnp.int32).reshape(1),
                    jnp.asarray(k_lo, jnp.int32).reshape(1))
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=off_specs + [
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 8), lambda bh, i, j: (bh, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, 8), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),   # running max
            pltpu.VMEM((block_q, 1), jnp.float32),   # running denom
            pltpu.VMEM((block_q, d), jnp.float32),   # accumulator
        ],
        interpret=interpret,
    )(*off_args, qt, kt, vt)
    o = o.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    lse = lse[:, :, 0].reshape(b, h, sq)
    if pad_q:
        o = o[:, :orig_sq]
        lse = lse[:, :, :orig_sq]
    return o, lse


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret"))
def _flash_fwd_v2(q, k, v, causal=True, block_q=512, block_k=512,
                  interpret=None):
    """Grid-pipelined flash forward; q,k,v [B, S, H, D] (kv pre-repeated)."""
    return _flash_v2_call(q, k, v, causal, block_q, block_k, interpret,
                          None)


@functools.partial(jax.jit, static_argnames=("block_q", "block_k",
                                             "interpret"))
def _flash_fwd_v2_cached(q, k, v, q_offset, block_q=512, block_k=512,
                         interpret=None):
    """Causal grid-pipelined flash where q rows sit at absolute positions
    ``q_offset + i`` against kv rows indexed from 0 — the serving prefill
    form (q is a prompt chunk, k/v the full KV cache with the chunk
    already written at ``q_offset``..). kv pre-repeated to q heads.
    Returns (o, lse). The k-block accumulation order for a given q row is
    identical whatever ``q_offset``/``block_q`` split the prompt arrived
    under — chunked and unchunked prefills of the same gathered cache
    stay bit-identical; the paged prefix-hit path merges a SEPARATE
    prefix state instead and carries a tolerance contract
    (docs/serving.md "Attention kernels")."""
    return _flash_v2_call(q, k, v, True, block_q, block_k, interpret,
                          q_offset)


@functools.partial(jax.jit, static_argnames=("block_q", "block_k",
                                             "interpret"))
def _flash_fwd_v2_cached_bounded(q, k, v, q_offset, k_lo, block_q=512,
                                 block_k=512, interpret=None):
    """Causal cached flash with a kv lower bound: rows < ``k_lo`` are
    masked out (the serving engines' suffix-prefill form on a paged
    prefix-cache hit — those positions live in shared pool pages, not
    the local cache, and are attended by the paged prefill kernel).
    Returns (o, lse) so the caller can LSE-merge the two partial
    softmax states (ops/paged_attention.merge_softmax_states)."""
    return _flash_v2_call(q, k, v, True, block_q, block_k, interpret,
                          q_offset, k_lo=k_lo)


def flash_attention_cached(q, k, v, q_start) -> jax.Array:
    """Forward-only flash over a KV cache: q [B, S, H, D] rows at
    positions ``q_start + i``; k/v [B, M, H, D] the cache (kv already
    repeated to q heads, current rows written at q_start..q_start+S).
    Rows past ``q_start + S - 1`` are excluded by the causal mask, so the
    cache tail needs no explicit length."""
    o, _ = _flash_fwd_v2_cached(q, k, v, q_start)
    return o


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k",
                                             "interpret"))
def _flash_fwd(q, k, v, causal=True, block_q=256, block_k=256,
               interpret=None):
    """q,k,v: [B, S, H, D] (kv already repeated to H heads). Returns (o, lse)."""
    if interpret is None:
        interpret = not _on_tpu()  # CPU backend only supports interpret mode
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    # pad seq dims to block multiples; padded k rows are masked out by
    # position (causal) or an explicit kv-length bound in the kernel
    orig_sq, orig_sk = sq, sk
    pad_q = (-sq) % block_q
    pad_k = (-sk) % block_k
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0), (0, 0)))
        sq += pad_q
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        sk += pad_k
    scale = d ** -0.5
    # layout: fold batch*heads, move seq to row dim
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    grid = (b * h, pl.cdiv(sq, block_q))
    kernel = functools.partial(
        _flash_fwd_kernel, block_k=block_k, seq_k=sk, kv_len=orig_sk,
        scale=scale, causal=causal)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, sk, d), lambda bh, i: (bh, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, sk, d), lambda bh, i: (bh, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, 8), lambda bh, i: (bh, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, 8), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    o = o.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    lse = lse[:, :, 0].reshape(b, h, sq)
    if pad_q:
        o = o[:, :orig_sq]
        lse = lse[:, :, :orig_sq]
    return o, lse


def _blockwise_bwd(q, k, v, o, lse, g, causal: bool, block: int = 512):
    """Memory-efficient backward: recompute attention blockwise over k."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = d ** -0.5
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    of = o.astype(jnp.float32)
    delta = jnp.sum(of * gf, axis=-1)  # [B, Sq, H]

    orig_sk = sk
    pad_k = (-sk) % min(block, sk)
    if pad_k:
        kf = jnp.pad(kf, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, pad_k), (0, 0), (0, 0)))
        sk += pad_k
    num_kb = max(1, sk // min(block, sk))
    kb_size = sk // num_kb

    def body(carry, kb):
        dq = carry
        ks = jax.lax.dynamic_slice_in_dim(kf, kb * kb_size, kb_size, axis=1)
        vs = jax.lax.dynamic_slice_in_dim(vf, kb * kb_size, kb_size, axis=1)
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, ks,
                       preferred_element_type=jnp.float32) * scale
        k_pos = kb * kb_size + jnp.arange(kb_size)[None, :]
        if causal:
            q_pos = jnp.arange(sq)[:, None]
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        s = jnp.where(k_pos[None, None] < orig_sk, s, NEG_INF)
        p = jnp.exp(s - lse[:, :, :, None])  # [B,H,Sq,Kb]
        dp = jnp.einsum("bqhd,bkhd->bhqk", gf, vs)
        ds = p * (dp - delta.transpose(0, 2, 1)[:, :, :, None]) * scale
        dq = dq + jnp.einsum("bhqk,bkhd->bqhd", ds, ks)
        dk = jnp.einsum("bhqk,bqhd->bkhd", ds, qf)
        dv = jnp.einsum("bhqk,bqhd->bkhd", p, gf)
        return dq, (dk, dv)

    dq0 = jnp.zeros_like(qf)
    dq, (dks, dvs) = jax.lax.scan(body, dq0, jnp.arange(num_kb))
    dk = dks.transpose(1, 0, 2, 3, 4).reshape(b, sk, h, d)
    dv = dvs.transpose(1, 0, 2, 3, 4).reshape(b, sk, h, d)
    if pad_k:
        dk = dk[:, :orig_sk]
        dv = dv[:, :orig_sk]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention_mlt(q, k, v, causal: bool = True):
    """Our pallas flash attention (kv must already match q heads); forward
    is the grid-pipelined v2 kernel."""
    o, _ = _flash_fwd_v2(q, k, v, causal=causal)
    return o


def _flash_mlt_fwd(q, k, v, causal):
    o, lse = _flash_fwd_v2(q, k, v, causal=causal)
    return o, (q, k, v, o, lse)


def _flash_mlt_bwd(causal, residuals, g):
    q, k, v, o, lse = residuals
    return _blockwise_bwd(q, k, v, o, lse, g, causal)


flash_attention_mlt.defvjp(_flash_mlt_fwd, _flash_mlt_bwd)


# ---------------------------------------------------------------------------
# library pallas kernels (tuned fwd+bwd) and the dispatcher
# ---------------------------------------------------------------------------

def _tuned_block_sizes(sq: int, sk: int):
    """Big (512) pallas blocks for the library flash kernel.

    The library default is 128x128 blocks, which at head_dim 64 leaves the
    MXU ~12x under-utilized at bench shapes (measured on v5e: 49ms/layer at
    128-blocks vs 4.1ms at 512-blocks for b16 s2048 h32 d64). Pick the
    largest of 512/256/128 that divides each sequence length, for both the
    forward and the dq/dkv backward passes. ``pick`` only ever returns a
    divisor of the length (the library kernel requires block | seq), so
    blocks are inherently clamped to the sequence; the short-prompt
    block clamping for OUR v2 kernel path lives in ``_fit_block``.
    """
    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes

    def pick(n: int) -> int:
        for c in (512, 256, 128):
            if n % c == 0:
                return c
        return n

    bq, bk = pick(sq), pick(sk)
    return BlockSizes(
        block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk, block_k_dkv=bk,
        block_q_dkv=bq,
        block_k_major_dq=bk, block_k_dq=bk, block_q_dq=bq)


def _jax_flash(q, k, v, causal: bool):
    """jax pallas library flash attention: expects [B, H, S, D]."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as _fa,
    )

    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = _fa(qt, kt, vt, causal=causal, sm_scale=q.shape[-1] ** -0.5,
              block_sizes=_tuned_block_sizes(q.shape[1], k.shape[1]))
    return out.transpose(0, 2, 1, 3)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:  # noqa: BLE001
        return False


def resolve_prefill_impl(impl: str = "auto") -> str:
    """Resolve a serving ``attention_impl`` knob to the engines' prefill
    attention path: ``flash`` (flash_attention_cached — interpret mode
    off-TPU) or ``dense`` (the masked-softmax `_cached_attention`).
    ``kernel`` is the full kernel stack — paged decode kernel AND flash/
    paged prefill (a prefix-hit admission must never fall back to the
    dense gather; docs/serving.md "Attention kernels"). Explicit kernel
    requests that cannot be honored (pallas unavailable) raise typed
    (ops/paged_attention.KernelUnavailableError)."""
    if impl in ("flash", "kernel"):
        if not _PALLAS_OK:
            from .paged_attention import KernelUnavailableError

            raise KernelUnavailableError(
                f"attention_impl='{impl}' requested but Pallas is "
                "unavailable in this jax build — use 'auto' (falls back "
                "to the dense reference) or 'reference'")
        return "flash"
    if impl in ("reference", "dense"):
        return "dense"
    if impl != "auto":
        raise ValueError(
            f"unknown prefill attention impl '{impl}' "
            "(auto | flash | kernel | reference | dense)")
    if _PALLAS_OK and (_on_tpu() or interpret_forced()):
        return "flash"
    return "dense"


def attention(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = True,
              impl: str = "auto") -> jax.Array:
    """Dispatching attention: [B, S, H|Hkv, D] in, [B, S, H, D] out."""
    n_rep = q.shape[2] // k.shape[2]
    if impl == "reference":
        return attention_reference(q, k, v, causal=causal)
    if impl == "auto":
        min_dim = 128
        use_kernel = (
            _PALLAS_OK and _on_tpu()
            and q.shape[1] >= min_dim and k.shape[1] >= min_dim
            and q.shape[1] % 128 == 0 and k.shape[1] % 128 == 0
        )
        if use_kernel:
            impl = "flash"
        elif _PALLAS_OK and not _on_tpu() and interpret_forced():
            # forced interpret mode: run our pallas kernel (fwd + blockwise
            # custom-vjp bwd) so CPU test runs cover the real kernel path
            impl = "mlt_flash"
        else:
            impl = "reference"
    if impl == "reference":
        return attention_reference(q, k, v, causal=causal)
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    if impl == "flash":
        return _jax_flash(q, k, v, causal)
    if impl == "mlt_flash":
        return flash_attention_mlt(q, k, v, causal)
    raise ValueError(f"unknown attention impl '{impl}'")
