"""Ulysses-style sequence parallelism — all-to-all head/sequence exchange.

Complement to ring attention (ops/ring_attention.py): instead of rotating kv
around a ring, each device holds a sequence shard and the attention heads
are redistributed with ``jax.lax.all_to_all`` so every device computes FULL
attention for a subset of heads, then a second all-to-all restores sequence
sharding. Two collectives per attention instead of P-1 ppermutes — better
when heads >= devices and the interconnect favors large all-to-alls (TPU
ICI), while ring attention wins at extreme sequence lengths (no full-seq
materialization). Both are exact.

Use inside shard_map with q/k/v sharded P(batch, seq_axis, None, None).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .attention import attention_reference


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      axis_name: str = "seq", causal: bool = True,
                      q_offset=None) -> jax.Array:
    """Exact attention over a sequence-sharded axis via all-to-all.

    q,k,v: local shards [B, S_local, H, D] (kv heads already repeated to H;
    H must be divisible by the axis size). Returns [B, S_local, H, D].
    """
    axis_size = jax.lax.psum(1, axis_name)
    b, s_local, h, d = q.shape
    if h % axis_size:
        raise ValueError(
            f"heads {h} not divisible by sequence-parallel size {axis_size}")

    h_per = h // axis_size

    # same-axis all_to_all + explicit transposes: the exchanged axis always
    # indexes the SOURCE device afterwards, which keeps the layout
    # unambiguous (cross-axis split/concat interleaving is implementation-
    # defined).
    def scatter_heads(x):
        # [B, s, H, D] -> [B, s, P(group), h', D]; send group g to device g
        x = x.reshape(b, s_local, axis_size, h_per, d)
        x = jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=2)
        # now axis2 = source device i, rows are i's local seq shard:
        # [B, s, P(src), h', D] -> global seq source-major
        x = x.transpose(0, 2, 1, 3, 4)  # [B, P(src), s, h', D]
        return x.reshape(b, axis_size * s_local, h_per, d)

    def gather_heads(x):
        # [B, S_global, h', D] -> [B, P(dest), s, h', D]; send shard i to i
        x = x.reshape(b, axis_size, s_local, h_per, d)
        x = jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=1)
        # axis1 = source device g = head-group owner: restore g-major heads
        x = x.transpose(0, 2, 1, 3, 4)  # [B, s, P(g), h', D]
        return x.reshape(b, s_local, h, d)

    q_full = scatter_heads(q)
    k_full = scatter_heads(k)
    v_full = scatter_heads(v)
    out_full = attention_reference(q_full, k_full, v_full, causal=causal)
    return gather_heads(out_full)


def make_ulysses_attention(mesh, seq_axis: str = "seq", causal: bool = True):
    """Wrap in shard_map: fn(q, k, v) on arrays sharded
    P(batch_axes, seq_axis, None, None)."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.compat import shard_map

    batch_axes = tuple(a for a in ("data", "fsdp") if a in mesh.axis_names
                       and mesh.shape[a] > 1) or None
    spec = P(batch_axes, seq_axis, None, None)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec, check_vma=False)
    def _ulysses(q, k, v):
        return ulysses_attention(q, k, v, axis_name=seq_axis, causal=causal)

    return _ulysses
