"""Type-hint parsing for handler IO (reference analog:
mlrun/package/utils/type_hint_utils.py — string-hint resolution and
typing-construct reduction, re-implemented compactly).

``reduce_hint`` turns any annotation — a concrete type, a string like
"pandas.DataFrame", or a typing construct (Optional[X], Union[A, B],
List[int], Annotated[X, ...]) — into the list of concrete candidate types a
packager can match against.
"""

from __future__ import annotations

import builtins
import importlib
import typing
from typing import Any, Union

_SHORTHAND_MODULES = {
    "np": "numpy", "pd": "pandas", "jnp": "jax.numpy", "plt":
    "matplotlib.pyplot",
}


def parse_string_hint(hint: str):
    """Resolve "module.Type" / builtin-name strings to the actual type.
    Handles shorthand module names and nested classes
    ("module.Outer.Inner" — the walk drops path segments from the right
    until a module imports). Returns None when nothing resolves; only
    already-importable modules load, so a hint string cannot trigger
    arbitrary code beyond the named module's import."""
    hint = hint.strip()
    if "." not in hint:
        return getattr(builtins, hint, None)
    module_name, _, attr = hint.rpartition(".")
    module_name = _SHORTHAND_MODULES.get(module_name, module_name)
    while module_name:
        try:
            module = importlib.import_module(module_name)
        except ImportError:
            # peel one segment off the module path onto the qualname
            # (nested class case)
            if "." not in module_name:
                return None
            module_name, _, head = module_name.rpartition(".")
            attr = f"{head}.{attr}"
            continue
        obj = module
        for part in attr.split("."):
            obj = getattr(obj, part, None)
            if obj is None:
                return None
        return obj
    return None


def reduce_hint(hint: Any) -> list:
    """Reduce an annotation to concrete candidate types (ordered; empty
    when nothing concrete can be derived)."""
    if hint is None or hint is Any or hint is typing.Any:
        return []
    if isinstance(hint, str):
        resolved = parse_string_hint(hint)
        return reduce_hint(resolved) if resolved is not None else []
    origin = typing.get_origin(hint)
    if origin is None:
        return [hint] if isinstance(hint, type) else []
    if origin is Union:  # Optional[X] is Union[X, None]
        out = []
        for arg in typing.get_args(hint):
            if arg is type(None):
                continue
            out.extend(reduce_hint(arg))
        return out
    if origin is getattr(typing, "Annotated", object()):
        args = typing.get_args(hint)
        return reduce_hint(args[0]) if args else []
    # parameterized generic (List[int], Dict[str, float], ...) → its origin
    return [origin] if isinstance(origin, type) else []
