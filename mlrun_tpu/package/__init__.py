from .context_handler import ContextHandler  # noqa: F401
from .packagers_manager import Packager, PackagersManager  # noqa: F401
