"""Handler I/O mediation (reference analog: mlrun/package/context_handler.py:30).

Parses the user handler's signature + type hints, converts incoming ``DataItem``
inputs to the hinted types, injects the context, and packages returned values
into results/artifacts via the packagers manager.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, get_type_hints

from ..datastore.base import DataItem
from ..execution import MLClientCtx
from .packagers_manager import PackagersManager


class ContextHandler:
    def __init__(self):
        self._manager = PackagersManager()

    def look_for_context(self, args: tuple, kwargs: dict) -> MLClientCtx | None:
        for value in list(args) + list(kwargs.values()):
            if isinstance(value, MLClientCtx):
                return value
        return None

    def parse_inputs(self, handler: Callable, context: MLClientCtx,
                     runobj) -> dict:
        """Build handler kwargs from run params + inputs, honoring type hints."""
        sig = inspect.signature(handler)
        try:
            hints = get_type_hints(handler)
        except Exception:  # noqa: BLE001 - unresolvable hints are non-fatal
            hints = {}
        params = runobj.spec.parameters or {}
        inputs = runobj.spec.inputs or {}
        kwargs: dict[str, Any] = {}
        for name, param in sig.parameters.items():
            if param.kind in (param.VAR_POSITIONAL, param.VAR_KEYWORD):
                continue
            hint = hints.get(name)
            if hint is MLClientCtx or name == "context" or name == "ctx":
                kwargs[name] = context
            elif name in inputs:
                item = context.get_input(name, inputs[name])
                kwargs[name] = self._manager.unpack(item, hint)
            elif name in params:
                kwargs[name] = params[name]
            elif param.default is not param.empty:
                kwargs[name] = param.default
        # pass through extra params the signature accepts via **kwargs
        if any(p.kind == p.VAR_KEYWORD for p in sig.parameters.values()):
            for key, value in params.items():
                kwargs.setdefault(key, value)
        return kwargs

    def package_results(self, context: MLClientCtx, results: Any,
                        returns: list | None):
        """Log returned values (reference: PackagersManager packaging flow)."""
        if results is None:
            return
        returns = returns or []
        if not isinstance(results, tuple):
            results = (results,)
        for index, value in enumerate(results):
            log_hint = self._log_hint(returns, index)
            self._manager.pack(context, value, log_hint)

    @staticmethod
    def _log_hint(returns: list, index: int) -> dict:
        if index < len(returns):
            hint = returns[index]
            if isinstance(hint, str):
                # "key" or "key:artifact_type"
                if ":" in hint:
                    key, artifact_type = hint.split(":", 1)
                    return {"key": key, "artifact_type": artifact_type}
                return {"key": hint}
            return dict(hint)
        return {"key": f"return_{index}" if index else "return"}
