"""Type → artifact codec system (reference analog:
mlrun/package/packagers_manager.py:37 + mlrun/package/packagers/).

``pack`` routes a returned python object to log_result / log_dataset /
log_artifact / log_model by type family; ``unpack`` converts a DataItem to
the type hinted on the handler parameter. Families live in
``package/packagers/`` (stdlib, numpy, pandas, jax) ordered by priority;
type hints may be concrete types, strings ("pandas.DataFrame"), or typing
constructs (Optional/Union/List[...] — see type_hints.reduce_hint). JAX
pytrees and numpy arrays are first-class.
"""

from __future__ import annotations

from typing import Any

from .packagers import DEFAULT_PACKAGERS
from .packagers.default import DefaultPackager
from .type_hints import reduce_hint

# re-exported names kept from the round-1 flat module (tests/user code may
# subclass these)
from .packagers import (  # noqa: F401  (re-exports)
    CollectionPackager,
    JaxArrayPackager as JaxPackager,
    NumpyArrayPackager as NumpyPackager,
    PandasDataFramePackager as PandasPackager,
    PathPackager,
    PrimitivePackager,
)

Packager = DefaultPackager  # round-1 name for the base class


class PackagersManager:
    def __init__(self):
        self._packagers: list[DefaultPackager] = sorted(
            (cls() for cls in DEFAULT_PACKAGERS),
            key=lambda p: p.priority)

    def register(self, packager: DefaultPackager, first: bool = True):
        if first:
            self._packagers.insert(0, packager)
        else:
            self._packagers.append(packager)

    def pack(self, context, obj: Any, log_hint: dict):
        key = log_hint.get("key", "return")
        artifact_type = log_hint.get("artifact_type") or ""
        if artifact_type == "result":
            # explicit result hint wins regardless of family
            if _jsonable(obj):
                context.log_result(key, obj)
                return
        if artifact_type == "model":
            context.log_model(
                key, body=obj if isinstance(obj, (bytes, str)) else None)
            return
        cfg = {k: v for k, v in log_hint.items()
               if k not in ("key", "artifact_type")}
        for packager in self._packagers:
            try:
                if packager.can_pack(obj):
                    try:
                        packager.pack(context, obj, key,
                                      artifact_type=artifact_type, **cfg)
                    finally:
                        packager.cleanup()
                    return
            except ImportError:
                continue
        # fallback: stringify into a result
        context.log_result(key, str(obj))

    def unpack(self, data_item, hint):
        from ..datastore.base import DataItem

        candidates = reduce_hint(hint)
        if not candidates or DataItem in candidates:
            return data_item
        if str in candidates and data_item.kind == "file":
            # mirror the reference convention: str hint on an input = local
            # path
            return data_item.local()
        for candidate in candidates:
            for packager in self._packagers:
                try:
                    if packager.can_unpack(candidate):
                        return packager.unpack(data_item, candidate)
                except ImportError:
                    continue
        return data_item


def _jsonable(obj) -> bool:
    import json

    try:
        json.dumps(obj)
        return True
    except (TypeError, ValueError):
        return False
