"""Type → artifact codec system (reference analog:
mlrun/package/packagers_manager.py:37 and mlrun/package/packagers/).

``pack`` routes a returned python object to log_result / log_dataset /
log_artifact / log_model by type; ``unpack`` converts a DataItem to the type
hinted on the handler parameter. JAX pytrees and numpy arrays are first-class.
"""

from __future__ import annotations

import json
import os
import pathlib
import tempfile
from typing import Any, Optional


class Packager:
    """One type family's pack/unpack logic."""

    handled_types: tuple = ()
    artifact_type = "artifact"

    def can_pack(self, obj: Any) -> bool:
        return isinstance(obj, self.handled_types)

    def can_unpack(self, hint) -> bool:
        return hint in self.handled_types

    def pack(self, context, obj, key: str, **cfg):
        raise NotImplementedError

    def unpack(self, data_item, hint):
        raise NotImplementedError


class PrimitivePackager(Packager):
    handled_types = (int, float, str, bool, bytes)

    def pack(self, context, obj, key, **cfg):
        if isinstance(obj, bytes):
            context.log_artifact(key, body=obj)
        else:
            context.log_result(key, obj)

    def unpack(self, data_item, hint):
        raw = data_item.get()
        if hint is bytes:
            return raw
        text = raw.decode() if isinstance(raw, bytes) else raw
        if hint is str:
            return text
        return hint(text)


class CollectionPackager(Packager):
    handled_types = (dict, list, tuple, set)

    def pack(self, context, obj, key, **cfg):
        if isinstance(obj, (set, tuple)):
            obj = list(obj)
        # small collections → results; big → json artifact
        blob = json.dumps(obj, default=str)
        if len(blob) <= 1024:
            context.log_result(key, obj)
        else:
            context.log_artifact(key, body=blob, format="json")

    def unpack(self, data_item, hint):
        raw = data_item.get()
        text = raw.decode() if isinstance(raw, bytes) else raw
        obj = json.loads(text)
        if hint in (tuple, set):
            return hint(obj)
        return obj


class NumpyPackager(Packager):
    artifact_type = "artifact"

    def can_pack(self, obj):
        import numpy as np

        return isinstance(obj, np.ndarray)

    def can_unpack(self, hint):
        import numpy as np

        return hint is np.ndarray

    def pack(self, context, obj, key, **cfg):
        if obj.ndim == 0:
            context.log_result(key, obj.item())
            return
        import numpy as np

        tmp = tempfile.NamedTemporaryFile(suffix=".npy", delete=False)
        np.save(tmp.name, obj)
        context.log_artifact(key, local_path=tmp.name, format="npy")

    def unpack(self, data_item, hint):
        import numpy as np

        return np.load(data_item.local())


class JaxPackager(Packager):
    """JAX arrays/pytrees — device arrays land as npy artifacts, scalars as
    results (TPU-native addition; no reference analog)."""

    def can_pack(self, obj):
        try:
            import jax

            return isinstance(obj, jax.Array)
        except Exception:  # noqa: BLE001
            return False

    def can_unpack(self, hint):
        try:
            import jax

            return hint is jax.Array
        except Exception:  # noqa: BLE001
            return False

    def pack(self, context, obj, key, **cfg):
        import numpy as np

        host = np.asarray(obj)
        if host.ndim == 0:
            context.log_result(key, host.item())
            return
        tmp = tempfile.NamedTemporaryFile(suffix=".npy", delete=False)
        np.save(tmp.name, host)
        context.log_artifact(key, local_path=tmp.name, format="npy")

    def unpack(self, data_item, hint):
        import jax.numpy as jnp
        import numpy as np

        return jnp.asarray(np.load(data_item.local()))


class PandasPackager(Packager):
    artifact_type = "dataset"

    def can_pack(self, obj):
        import pandas as pd

        return isinstance(obj, (pd.DataFrame, pd.Series))

    def can_unpack(self, hint):
        import pandas as pd

        return hint in (pd.DataFrame, pd.Series)

    def pack(self, context, obj, key, **cfg):
        import pandas as pd

        if isinstance(obj, pd.Series):
            obj = obj.to_frame()
        context.log_dataset(key, df=obj, format=cfg.get("file_format", "parquet"))

    def unpack(self, data_item, hint):
        import pandas as pd

        df = data_item.as_df()
        if hint is pd.Series:
            return df.iloc[:, 0]
        return df


class PathPackager(Packager):
    def can_pack(self, obj):
        return isinstance(obj, pathlib.Path)

    def can_unpack(self, hint):
        return hint in (pathlib.Path,)

    def pack(self, context, obj, key, **cfg):
        context.log_artifact(key, local_path=str(obj))

    def unpack(self, data_item, hint):
        return pathlib.Path(data_item.local())


class PackagersManager:
    def __init__(self):
        self._packagers: list[Packager] = [
            PandasPackager(), NumpyPackager(), JaxPackager(),
            PrimitivePackager(), CollectionPackager(), PathPackager(),
        ]

    def register(self, packager: Packager, first: bool = True):
        if first:
            self._packagers.insert(0, packager)
        else:
            self._packagers.append(packager)

    def pack(self, context, obj: Any, log_hint: dict):
        key = log_hint.get("key", "return")
        artifact_type = log_hint.get("artifact_type")
        if artifact_type == "result":
            context.log_result(key, obj)
            return
        if artifact_type == "model":
            context.log_model(key, body=obj if isinstance(obj, (bytes, str)) else None)
            return
        for packager in self._packagers:
            try:
                if packager.can_pack(obj):
                    packager.pack(context, obj, key, **{
                        k: v for k, v in log_hint.items()
                        if k not in ("key", "artifact_type")})
                    return
            except ImportError:
                continue
        # fallback: stringify into a result
        context.log_result(key, str(obj))

    def unpack(self, data_item, hint):
        if hint is None or hint is Any:
            return data_item
        from ..datastore.base import DataItem

        if hint is DataItem:
            return data_item
        if hint in (str,) and data_item.kind == "file":
            # mirror the reference convention: str hint on an input = local path
            return data_item.local()
        for packager in self._packagers:
            try:
                if packager.can_unpack(hint):
                    return packager.unpack(data_item, hint)
            except ImportError:
                continue
        return data_item
