"""Type → artifact codec system (reference analog:
mlrun/package/packagers_manager.py:37 + mlrun/package/packagers/).

``pack`` routes a returned python object to log_result / log_dataset /
log_artifact / log_model by type family; ``unpack`` converts a DataItem to
the type hinted on the handler parameter. Families live in
``package/packagers/`` (stdlib, numpy, pandas, jax) ordered by priority;
type hints may be concrete types, strings ("pandas.DataFrame"), or typing
constructs (Optional/Union/List[...] — see type_hints.reduce_hint). JAX
pytrees and numpy arrays are first-class.
"""

from __future__ import annotations

from typing import Any

from ..utils import logger
from .packagers import DEFAULT_PACKAGERS
from .packagers.default import DefaultPackager
from .type_hints import reduce_hint

# re-exported names kept from the round-1 flat module (tests/user code may
# subclass these)
from .packagers import (  # noqa: F401  (re-exports)
    CollectionPackager,
    JaxArrayPackager as JaxPackager,
    NumpyArrayPackager as NumpyPackager,
    PandasDataFramePackager as PandasPackager,
    PathPackager,
    PrimitivePackager,
)

Packager = DefaultPackager  # round-1 name for the base class


class PackagersManager:
    def __init__(self):
        self._packagers: list[DefaultPackager] = sorted(
            (cls() for cls in DEFAULT_PACKAGERS),
            key=lambda p: p.priority)

    def register(self, packager: DefaultPackager, first: bool = True):
        if first:
            self._packagers.insert(0, packager)
        else:
            self._packagers.append(packager)

    def pack(self, context, obj: Any, log_hint: dict):
        key = log_hint.get("key", "return")
        artifact_type = log_hint.get("artifact_type") or ""
        if artifact_type == "result":
            # explicit result hint wins regardless of family
            if _jsonable(obj):
                context.log_result(key, obj)
                return
        if artifact_type == "model":
            context.log_model(
                key, body=obj if isinstance(obj, (bytes, str)) else None)
            return
        cfg = {k: v for k, v in log_hint.items()
               if k not in ("key", "artifact_type")}
        for packager in self._packagers:
            try:
                if packager.can_pack(obj):
                    # unpackaging instructions ride the artifact's FIRST
                    # store (reference packagers_manager records the same,
                    # so a hint-free downstream handler gets the original
                    # type back); the stamping proxy injects them into the
                    # packager's log_artifact call — no re-store
                    obj_type = type(obj)
                    stamping = _StampingContext(context, {
                        "packager": type(packager).__name__,
                        "object_type": f"{obj_type.__module__}."
                                       f"{obj_type.__qualname__}",
                        "artifact_type": artifact_type or "",
                    })
                    try:
                        packager.pack(stamping, obj, key,
                                      artifact_type=artifact_type, **cfg)
                    finally:
                        packager.cleanup()
                    return
            except ImportError:
                continue
        # fallback: stringify into a result
        context.log_result(key, str(obj))

    def unpack(self, data_item, hint):
        from ..datastore.base import DataItem

        candidates = reduce_hint(hint)
        if not candidates:
            # no hint: honor recorded unpackaging instructions, so the
            # handler receives the ORIGINAL packed type end-to-end
            unpacked = self._unpack_by_instructions(data_item)
            if unpacked is not _NO_INSTRUCTIONS:
                return unpacked
            return data_item
        if DataItem in candidates:
            return data_item
        if str in candidates and data_item.kind == "file":
            # mirror the reference convention: str hint on an input = local
            # path
            return data_item.local()
        for candidate in candidates:
            for packager in self._packagers:
                try:
                    if packager.can_unpack(candidate):
                        return packager.unpack(data_item, candidate)
                except ImportError:
                    continue
        return data_item

    def _unpack_by_instructions(self, data_item):
        """Reconstruct the packed object from the artifact spec's recorded
        unpackaging_instructions (written by ``_record_instructions``)."""
        meta = getattr(data_item, "meta", None) or {}
        instructions = (meta.get("spec") or {}).get(
            "unpackaging_instructions") or {}
        obj_path = instructions.get("object_type", "")
        if not obj_path:
            return _NO_INSTRUCTIONS
        obj_type = _resolve_type(obj_path, trusted=False)
        if obj_type is None:
            logger.warning("unpackaging instructions name an unresolvable "
                           "type — handing back the DataItem",
                           object_type=obj_path)
            return _NO_INSTRUCTIONS
        # prefer the recorded packager; fall back to can_unpack dispatch
        name = instructions.get("packager", "")
        ordered = sorted(self._packagers,
                         key=lambda p: type(p).__name__ != name)
        for packager in ordered:
            try:
                if packager.can_unpack(obj_type):
                    return packager.unpack(data_item, obj_type)
            except ImportError:
                continue
        return _NO_INSTRUCTIONS


def _jsonable(obj) -> bool:
    import json

    try:
        json.dumps(obj)
        return True
    except (TypeError, ValueError):
        return False


class _StampingContext:
    """Context proxy: adds the unpackaging instructions to artifacts the
    wrapped packager logs (everything else passes straight through)."""

    def __init__(self, context, instructions: dict):
        self._context = context
        self._instructions = instructions

    def log_artifact(self, *args, **kwargs):
        kwargs.setdefault("unpackaging_instructions", self._instructions)
        return self._context.log_artifact(*args, **kwargs)

    def log_dataset(self, *args, **kwargs):
        # pandas packagers log through log_dataset — it forwards **kwargs
        # to the artifact manager, so dataset artifacts get stamped too
        kwargs.setdefault("unpackaging_instructions", self._instructions)
        return self._context.log_dataset(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._context, name)


_NO_INSTRUCTIONS = object()  # sentinel: no usable recorded instructions


def _allowed_instruction_module(path: str) -> bool:
    """Unpackaging instructions are *artifact metadata* — attacker-shaped
    input, unlike handler type hints the user wrote. Restrict the module
    an instruction may name to builtins, ``mlrun_tpu`` itself, and
    modules this process ALREADY imported, so a crafted artifact spec
    cannot trigger an arbitrary import (and its module-level code)."""
    import sys

    if "." not in path:
        return True  # bare builtin name; parse_string_hint checks builtins
    from .type_hints import _SHORTHAND_MODULES

    root = path.split(".", 1)[0]
    root = _SHORTHAND_MODULES.get(root, root).split(".", 1)[0]
    return root == "mlrun_tpu" or root in sys.modules


def _resolve_type(path: str, trusted: bool = True):
    """'module.Qualified.Name' -> type via the shared string-hint
    resolver (type_hints.parse_string_hint handles shorthand modules and
    nested classes for both paths). ``trusted=False`` applies the
    instruction-metadata allowlist first."""
    from .type_hints import parse_string_hint

    if not trusted and not _allowed_instruction_module(path):
        logger.warning("unpackaging instructions name a module outside "
                       "the allowlist — refusing to import it",
                       object_type=path)
        return None
    resolved = parse_string_hint(path)
    return resolved if isinstance(resolved, type) else None
