"""Packager base (reference analog: mlrun/package/packagers/default.py
DefaultPackager — priority ordering, artifact-type dispatch, temp-file
management)."""

from __future__ import annotations

import os
import tempfile
from typing import Any


class DefaultPackager:
    """One type family's pack/unpack logic.

    - ``handled_types``/``can_pack``/``can_unpack`` decide routing;
    - ``priority`` orders the registry (lower = earlier);
    - ``artifact_types`` lists the ``key:artifact_type`` spellings this
      family supports; ``pack`` may branch on the requested one;
    - ``new_file`` hands out temp files the manager cleans up after the
      artifact layer has uploaded them.
    """

    handled_types: tuple = ()
    artifact_types: tuple = ("artifact", "result")
    default_artifact_type = "artifact"
    priority = 5

    def __init__(self):
        self._tmp_paths: list[str] = []

    def can_pack(self, obj: Any) -> bool:
        return isinstance(obj, self.handled_types) \
            if self.handled_types else False

    def can_unpack(self, hint) -> bool:
        return hint in self.handled_types

    def pack(self, context, obj, key: str, artifact_type: str = "", **cfg):
        raise NotImplementedError

    def unpack(self, data_item, hint):
        raise NotImplementedError

    def new_file(self, suffix: str) -> str:
        handle = tempfile.NamedTemporaryFile(suffix=suffix, delete=False)
        handle.close()
        self._tmp_paths.append(handle.name)
        return handle.name

    def cleanup(self):
        for path in self._tmp_paths:
            try:
                os.unlink(path)
            except OSError:
                pass
        self._tmp_paths.clear()
