"""NumPy packagers (reference analog:
mlrun/package/packagers/numpy_packagers.py — ndarray/scalar/dict-of-arrays/
list-of-arrays families with npy/npz/csv formats)."""

from __future__ import annotations

from .default import DefaultPackager


class NumpyArrayPackager(DefaultPackager):
    artifact_types = ("artifact", "result", "file")
    priority = 3

    def can_pack(self, obj):
        import numpy as np

        return isinstance(obj, np.ndarray)

    def can_unpack(self, hint):
        import numpy as np

        return hint is np.ndarray

    def pack(self, context, obj, key, artifact_type="", **cfg):
        import numpy as np

        if obj.ndim == 0 or artifact_type == "result":
            value = obj.item() if obj.ndim == 0 else obj.tolist()
            context.log_result(key, value)
            return
        file_format = cfg.get("file_format", "npy")
        path = self.new_file(f".{file_format}")
        if file_format == "csv":
            np.savetxt(path, obj, delimiter=",")
        else:
            np.save(path, obj)
        context.log_artifact(key, local_path=path, format=file_format)

    def unpack(self, data_item, hint):
        import numpy as np

        local = data_item.local()
        if local.endswith(".csv"):
            return np.loadtxt(local, delimiter=",")
        return np.load(local)


class NumpyScalarPackager(DefaultPackager):
    default_artifact_type = "result"
    priority = 3

    def can_pack(self, obj):
        import numpy as np

        return isinstance(obj, np.generic)

    def can_unpack(self, hint):
        import numpy as np

        return isinstance(hint, type) and issubclass(hint, np.generic)

    def pack(self, context, obj, key, artifact_type="", **cfg):
        context.log_result(key, obj.item())

    def unpack(self, data_item, hint):
        raw = data_item.get()
        text = raw.decode() if isinstance(raw, bytes) else raw
        return hint(text)


class NumpyArrayDictPackager(DefaultPackager):
    """{name: ndarray} → one .npz artifact."""

    priority = 3

    def can_pack(self, obj):
        import numpy as np

        return (isinstance(obj, dict) and len(obj) > 0
                and all(isinstance(v, np.ndarray) for v in obj.values()))

    def can_unpack(self, hint):
        return False  # dict hints route to the collection packager

    def pack(self, context, obj, key, artifact_type="", **cfg):
        import numpy as np

        path = self.new_file(".npz")
        np.savez(path, **obj)
        context.log_artifact(key, local_path=path, format="npz")

    def unpack(self, data_item, hint):  # pragma: no cover - can_unpack False
        import numpy as np

        return dict(np.load(data_item.local()))


class NumpyArrayListPackager(DefaultPackager):
    """[ndarray, ...] → one .npz artifact (arr_0..arr_n)."""

    priority = 3

    def can_pack(self, obj):
        import numpy as np

        return (isinstance(obj, list) and len(obj) > 0
                and all(isinstance(v, np.ndarray) for v in obj))

    def can_unpack(self, hint):
        return False

    def pack(self, context, obj, key, artifact_type="", **cfg):
        import numpy as np

        path = self.new_file(".npz")
        np.savez(path, *obj)
        context.log_artifact(key, local_path=path, format="npz")

    def unpack(self, data_item, hint):  # pragma: no cover - can_unpack False
        import numpy as np

        loaded = np.load(data_item.local())
        return [loaded[name] for name in loaded.files]
