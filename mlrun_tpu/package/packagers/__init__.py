"""Per-type-family packagers (reference analog: mlrun/package/packagers/ —
default.py, python_standard_library_packagers.py, numpy_packagers.py,
pandas_packagers.py; plus a TPU-native jax family)."""

from .default import DefaultPackager  # noqa: F401
from .jax_packagers import JaxArrayPackager, JaxPytreePackager  # noqa: F401
from .numpy_packagers import (  # noqa: F401
    NumpyArrayDictPackager,
    NumpyArrayListPackager,
    NumpyArrayPackager,
    NumpyScalarPackager,
)
from .pandas_packagers import (  # noqa: F401
    PandasDataFramePackager,
    PandasSeriesPackager,
)
from .python_standard_library import (  # noqa: F401
    BytesPackager,
    CollectionPackager,
    DataclassPackager,
    DatetimePackager,
    PathPackager,
    PrimitivePackager,
)

DEFAULT_PACKAGERS = (
    # highest priority first: specific families before generic fallbacks
    PandasDataFramePackager,
    PandasSeriesPackager,
    NumpyArrayPackager,
    NumpyScalarPackager,
    NumpyArrayDictPackager,
    NumpyArrayListPackager,
    JaxArrayPackager,
    JaxPytreePackager,
    DataclassPackager,
    DatetimePackager,
    PathPackager,
    BytesPackager,
    PrimitivePackager,
    CollectionPackager,
)
