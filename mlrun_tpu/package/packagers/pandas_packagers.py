"""Pandas packagers (reference analog:
mlrun/package/packagers/pandas_packagers.py — DataFrame/Series with
parquet/csv/json file formats)."""

from __future__ import annotations

from .default import DefaultPackager


class PandasDataFramePackager(DefaultPackager):
    artifact_types = ("dataset", "artifact", "file", "result")
    default_artifact_type = "dataset"
    priority = 2

    def can_pack(self, obj):
        import pandas as pd

        return isinstance(obj, pd.DataFrame)

    def can_unpack(self, hint):
        import pandas as pd

        return hint is pd.DataFrame

    def pack(self, context, obj, key, artifact_type="", **cfg):
        if artifact_type == "result":
            context.log_result(key, obj.to_dict(orient="list"))
            return
        context.log_dataset(key, df=obj,
                            format=cfg.get("file_format", "parquet"))

    def unpack(self, data_item, hint):
        return data_item.as_df()


class PandasSeriesPackager(DefaultPackager):
    artifact_types = ("dataset", "result")
    default_artifact_type = "dataset"
    priority = 2

    def can_pack(self, obj):
        import pandas as pd

        return isinstance(obj, pd.Series)

    def can_unpack(self, hint):
        import pandas as pd

        return hint is pd.Series

    def pack(self, context, obj, key, artifact_type="", **cfg):
        if artifact_type == "result":
            context.log_result(key, obj.tolist())
            return
        context.log_dataset(key, df=obj.to_frame(),
                            format=cfg.get("file_format", "parquet"))

    def unpack(self, data_item, hint):
        return data_item.as_df().iloc[:, 0]
