"""JAX packagers — device arrays and pytrees (TPU-native addition; the
reference has no jax family). Scalars land as results, arrays as npy
artifacts, pytrees-of-arrays as one npz keyed by flattened tree paths so
``unpack`` can rebuild the structure."""

from __future__ import annotations

from .default import DefaultPackager


def _is_jax_array(obj) -> bool:
    try:
        import jax

        return isinstance(obj, jax.Array)
    except Exception:  # noqa: BLE001 - no jax, no match
        return False


class JaxArrayPackager(DefaultPackager):
    artifact_types = ("artifact", "result", "file")
    priority = 3

    def can_pack(self, obj):
        return _is_jax_array(obj)

    def can_unpack(self, hint):
        try:
            import jax

            return hint is jax.Array
        except Exception:  # noqa: BLE001
            return False

    def pack(self, context, obj, key, artifact_type="", **cfg):
        import numpy as np

        host = np.asarray(obj)
        if host.ndim == 0 or artifact_type == "result":
            context.log_result(
                key, host.item() if host.ndim == 0 else host.tolist())
            return
        path = self.new_file(".npy")
        np.save(path, host)
        context.log_artifact(key, local_path=path, format="npy")

    def unpack(self, data_item, hint):
        import jax.numpy as jnp
        import numpy as np

        return jnp.asarray(np.load(data_item.local()))


class JaxPytreePackager(DefaultPackager):
    """Nested dict/list pytrees whose leaves are jax arrays → one npz with
    '/'-joined key paths."""

    priority = 3

    def can_pack(self, obj):
        if not isinstance(obj, (dict, list)) or not obj:
            return False
        import jax

        leaves = jax.tree_util.tree_leaves(obj)
        return bool(leaves) and all(_is_jax_array(x) for x in leaves)

    def can_unpack(self, hint):
        return False  # dict/list hints route to the collection packager

    def pack(self, context, obj, key, artifact_type="", **cfg):
        import jax
        import numpy as np

        flat = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(obj)[0]:
            name = "/".join(
                str(getattr(p, "key", getattr(p, "idx", p))) for p in path)
            flat[name] = np.asarray(leaf)
        out = self.new_file(".npz")
        np.savez(out, **flat)
        context.log_artifact(key, local_path=out, format="npz")

    def unpack(self, data_item, hint):  # pragma: no cover - can_unpack False
        import numpy as np

        return dict(np.load(data_item.local()))
