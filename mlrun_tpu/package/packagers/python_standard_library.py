"""Standard-library packagers (reference analog:
mlrun/package/packagers/python_standard_library_packagers.py — int/float/
str/bool/bytes/collections/pathlib, re-implemented compactly)."""

from __future__ import annotations

import datetime
import json
import pathlib

from .default import DefaultPackager


class PrimitivePackager(DefaultPackager):
    handled_types = (int, float, str, bool)
    artifact_types = ("result", "artifact")
    default_artifact_type = "result"

    def can_pack(self, obj):
        # bool is int's subclass; isinstance covers both deliberately
        return isinstance(obj, self.handled_types)

    def pack(self, context, obj, key, artifact_type="", **cfg):
        if artifact_type == "artifact":
            context.log_artifact(key, body=str(obj))
        else:
            context.log_result(key, obj)

    def unpack(self, data_item, hint):
        raw = data_item.get()
        text = raw.decode() if isinstance(raw, bytes) else raw
        if hint is str:
            return text
        if hint is bool:
            return text.strip().lower() in ("1", "true", "yes")
        return hint(text)


class BytesPackager(DefaultPackager):
    handled_types = (bytes, bytearray)
    priority = 4

    def pack(self, context, obj, key, artifact_type="", **cfg):
        context.log_artifact(key, body=bytes(obj))

    def unpack(self, data_item, hint):
        raw = data_item.get()
        data = raw if isinstance(raw, (bytes, bytearray)) else \
            str(raw).encode()
        return hint(data)


class CollectionPackager(DefaultPackager):
    handled_types = (dict, list, tuple, set, frozenset)
    artifact_types = ("result", "artifact", "file")

    def pack(self, context, obj, key, artifact_type="", **cfg):
        if isinstance(obj, (set, tuple, frozenset)):
            obj = list(obj)
        blob = json.dumps(obj, default=str)
        # small collections → results; big (or explicit) → json artifact
        if artifact_type in ("artifact", "file") or len(blob) > 1024:
            context.log_artifact(key, body=blob, format="json")
        else:
            context.log_result(key, obj)

    def unpack(self, data_item, hint):
        raw = data_item.get()
        text = raw.decode() if isinstance(raw, bytes) else raw
        obj = json.loads(text)
        if hint in (tuple, set, frozenset):
            return hint(obj)
        return obj


class PathPackager(DefaultPackager):
    handled_types = (pathlib.Path, pathlib.PurePath)
    priority = 4

    def can_unpack(self, hint):
        return hint in (pathlib.Path, pathlib.PurePath)

    def pack(self, context, obj, key, artifact_type="", **cfg):
        context.log_artifact(key, local_path=str(obj))

    def unpack(self, data_item, hint):
        return pathlib.Path(data_item.local())


class DataclassPackager(DefaultPackager):
    """Dataclasses round-trip as json artifacts: pack via asdict; unpack
    reconstructs the hinted (or instruction-recorded) dataclass type.
    Nested dataclass fields are re-inflated when the field annotation is
    itself a dataclass."""

    priority = 3  # before CollectionPackager would see asdict-able types

    def can_pack(self, obj):
        import dataclasses

        return dataclasses.is_dataclass(obj) and not isinstance(obj, type)

    def can_unpack(self, hint):
        import dataclasses

        return isinstance(hint, type) and dataclasses.is_dataclass(hint)

    def pack(self, context, obj, key, artifact_type="", **cfg):
        import dataclasses

        context.log_artifact(
            key, body=json.dumps(dataclasses.asdict(obj), default=str),
            format="json")

    def unpack(self, data_item, hint):
        raw = data_item.get()
        data = json.loads(raw.decode() if isinstance(raw, bytes) else raw)
        return self.unpack_dict(data, hint)

    @classmethod
    def unpack_dict(cls, data: dict, hint):
        import dataclasses
        import typing

        try:
            # field.type is a plain STRING under PEP 563 (`from __future__
            # import annotations`) — resolve through get_type_hints so
            # nested dataclasses re-inflate either way
            resolved = typing.get_type_hints(hint)
        except Exception:  # noqa: BLE001 - unresolvable forward refs
            resolved = {}
        kwargs = {}
        for field in dataclasses.fields(hint):
            if field.name not in data:
                continue
            value = data[field.name]
            field_type = resolved.get(field.name, field.type)
            if isinstance(field_type, type) \
                    and dataclasses.is_dataclass(field_type) \
                    and isinstance(value, dict):
                value = cls.unpack_dict(value, field_type)
            kwargs[field.name] = value
        return hint(**kwargs)


class DatetimePackager(DefaultPackager):
    handled_types = (datetime.datetime, datetime.date, datetime.time)
    default_artifact_type = "result"
    priority = 4

    def pack(self, context, obj, key, artifact_type="", **cfg):
        context.log_result(key, obj.isoformat())

    def unpack(self, data_item, hint):
        raw = data_item.get()
        text = (raw.decode() if isinstance(raw, bytes) else raw).strip()
        if hint is datetime.date:
            return datetime.date.fromisoformat(text)
        if hint is datetime.time:
            return datetime.time.fromisoformat(text)
        return datetime.datetime.fromisoformat(text)
