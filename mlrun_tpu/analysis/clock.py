"""MLT003 — explicit-now discipline in control loops.

Every interval-evaluator in this codebase takes an explicit ``now``
(``FleetAutoscaler.tick(now)``, ``ContinuousTuningController.tick(now)``,
``SLOEvaluator.evaluate(at)``, the canary hash split) so fake-clock
tests can drive hours of control-loop behavior in milliseconds — the
property every closed-loop test (scale ramp, promote/rollback,
burn-rate windows) rests on. One ``time.time()`` inside a tick body
silently re-couples the loop to the wall clock and the fake-clock
suite starts passing for the wrong reason.

The check: in the control-loop modules listed below, no call to
``time.time / time.monotonic / time.perf_counter / datetime.now /
datetime.utcnow`` anywhere — the clock must arrive as an argument.
Legitimate wall-clock sites (entrypoints that SOURCE the clock before
threading it down) go in the per-module allowlist with a rationale.
"""

from __future__ import annotations

import ast
import os

from .core import Checker, Finding, qualname_parts, walk_functions, walk_own

CODE = "MLT003"

#: module (repo-relative) -> why it is clock-disciplined
CONTROL_LOOP_MODULES = {
    "mlrun_tpu/service/autoscaler.py":
        "FleetAutoscaler.tick(now) — fake-clock scale-ramp tests",
    "mlrun_tpu/model_monitoring/controller.py":
        "ContinuousTuningController.tick(now) — fake-clock closed loop",
    "mlrun_tpu/model_monitoring/stream_processing.py":
        "AdapterTrafficMonitor.evaluate(adapter, now) — drift windows",
    "mlrun_tpu/obs/health.py":
        "ReplicaHealthScorer.tick(now) — fake-clock fail-slow "
        "detection drills",
    "mlrun_tpu/obs/slo.py":
        "SLOEvaluator.evaluate(at) — burn-rate window arithmetic",
    "mlrun_tpu/obs/timeseries.py":
        "windowed store: record/rate/quantile all take explicit times",
    "mlrun_tpu/serving/canary.py":
        "CanaryRouter: deterministic hash split, no time dependence",
    "mlrun_tpu/training/elastic.py":
        "ElasticGuard.poll — chaos-driven slice failures, fake-clock",
    "mlrun_tpu/common/journal.py":
        "intent journal: records carry caller-provided times only — a "
        "journal-stamped wall clock would diverge from the fake clock "
        "the recovery drills replay under",
    "mlrun_tpu/serving/podfleet.py":
        "ServingPodFleet.tick(now)/reconcile(now) — fake-clock restart "
        "and preemption drills",
}

#: (module, function qualname) -> rationale for a legitimate
#: wall-clock read inside a clock-disciplined module. Entrypoints that
#: SOURCE the clock belong here; tick/evaluate bodies never do.
ALLOWLIST: dict[tuple[str, str], str] = {
    ("mlrun_tpu/serving/podfleet.py",
     "ServingPodFleet._advance_warming"):
        "perf_counter measures the REAL pre-warm wall (compile + KV "
        "replay work) for the prewarm histogram — real work, not "
        "control-loop scheduling, so the fake clock must not apply",
    ("mlrun_tpu/serving/podfleet.py", "ServingPodFleet.reconcile"):
        "perf_counter measures the real recovery wall (journal replay "
        "+ world listing + adoption) for mlt_reconcile_seconds — same "
        "real-work rule as _advance_warming",
}

_CLOCK_CALLS = {
    ("time", "time"), ("time", "monotonic"), ("time", "perf_counter"),
    ("datetime", "now"), ("datetime", "utcnow"),
    ("datetime", "datetime", "now"), ("datetime", "datetime", "utcnow"),
}
_BARE_CLOCK_IMPORTS = {"time", "monotonic", "perf_counter"}


class ExplicitNowChecker(Checker):
    code = CODE
    name = "explicit-now"

    def begin(self, root: str) -> None:
        self._root = root

    def visit(self, tree, source: str, path: str) -> list[Finding]:
        rel = os.path.relpath(path, self._root).replace(os.sep, "/")
        if rel not in CONTROL_LOOP_MODULES:
            return []
        findings: list[Finding] = []
        # names bound by ``from time import time`` style imports
        bare: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) \
                    and node.module in ("time", "datetime"):
                for alias in node.names:
                    if alias.name in _BARE_CLOCK_IMPORTS | {"now"}:
                        bare.add(alias.asname or alias.name)
        for func, qual in walk_functions(tree):
            if (rel, qual) in ALLOWLIST:
                continue
            for node in walk_own(func):
                if not isinstance(node, ast.Call):
                    continue
                if self._is_clock_call(node, bare):
                    findings.append(Finding(
                        CODE, path, node.lineno,
                        f"wall-clock read inside {qual} of a "
                        f"clock-disciplined module "
                        f"({CONTROL_LOOP_MODULES[rel]})",
                        "take `now` as a parameter (the interval "
                        "evaluator convention) or add an ALLOWLIST "
                        "entry with a rationale"))
        # import-time clock reads: module level AND class bodies
        # (a class attribute default like `_epoch = time.time()` runs
        # at import and re-couples the module to the wall clock just
        # as surely as a call inside tick())
        for sub in _walk_outside_functions(tree):
            if isinstance(sub, ast.Call) \
                    and self._is_clock_call(sub, bare):
                findings.append(Finding(
                    CODE, path, sub.lineno,
                    "import-time wall-clock read in a "
                    "clock-disciplined module",
                    "thread the clock in as an argument"))
        return findings

    @staticmethod
    def _is_clock_call(node: ast.Call, bare: set[str]) -> bool:
        parts = qualname_parts(node.func)
        if parts is None:
            return False
        if tuple(parts) in _CLOCK_CALLS:
            return True
        return len(parts) == 1 and parts[0] in bare


def _walk_outside_functions(tree):
    """Every node that executes at import time: descends into class
    bodies but not into function/lambda bodies."""
    stack = list(ast.iter_child_nodes(tree))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


