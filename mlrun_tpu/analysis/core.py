"""Core types for the invariant checker (docs/static_analysis.md).

Stdlib-only by contract (``ast`` + ``tokenize``): the analyzer must run
in any environment that can parse the source tree — no jax, no yaml, no
third-party linter framework. Checkers are plugins over one shared
shape:

- :class:`Finding`: one violation — ``MLT0xx`` code, file:line, a
  one-line message, and a one-line remedy (what to change, not just
  what is wrong).
- :class:`Checker`: ``begin(root)`` once per run (load cross-file
  contract sources: the FaultPoints registry, the config defaults
  tree, the docs tables), ``visit(tree, source, path)`` once per file,
  ``finish()`` once at the end for cross-file invariants
  (declared-but-never-fired, family-not-in-docs).
- suppressions: ``# mlt: ignore[MLT004]: <reason>`` on the offending
  line. The reason is REQUIRED — a bare ignore is itself a finding
  (MLT000), because an unexplained suppression is exactly the
  convention rot this tool exists to stop. Checker-level allowlists
  (module tables with one-line rationales) are preferred over inline
  ignores for anything structural; inline ignores are for one-off
  sites.
"""

from __future__ import annotations

import io
import re
import tokenize
from dataclasses import dataclass, field

#: code for broken suppression comments (missing reason / bad syntax)
SUPPRESSION_CODE = "MLT000"

_CODE_RE = re.compile(r"^MLT\d{3}$")
# the marker must BE the comment (anchored at its start), not merely
# appear inside one — prose mentioning the syntax must not arm it
_IGNORE_RE = re.compile(
    r"^#\s*mlt:\s*ignore\[(?P<codes>[^\]]*)\](?P<rest>.*)$")


@dataclass(frozen=True)
class Finding:
    """One invariant violation at a source location."""

    code: str          # MLT0xx
    path: str          # repo-relative where possible
    line: int          # 1-based
    message: str       # what is wrong, one line
    remedy: str = ""   # how to fix it, one line

    def sort_key(self):
        return (self.path, self.line, self.code, self.message)

    def to_dict(self) -> dict:
        return {
            "code": self.code,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "remedy": self.remedy,
        }

    def render(self) -> str:
        text = f"{self.path}:{self.line}: {self.code} {self.message}"
        if self.remedy:
            text += f" [fix: {self.remedy}]"
        return text


class Checker:
    """Checker plugin base. Subclasses set ``code`` + ``name`` and
    override any of the three hooks; all default to no-ops so a purely
    per-file checker only implements ``visit``."""

    code: str = "MLT999"
    name: str = "base"

    def begin(self, root: str) -> None:
        """Called once before any file, with the repo root (the
        directory containing the ``mlrun_tpu`` package). Load
        cross-file contract sources here."""

    def visit(self, tree, source: str, path: str) -> list[Finding]:
        """Called once per parsed file; return per-file findings."""
        return []

    def finish(self) -> list[Finding]:
        """Called once after every file; return cross-file findings."""
        return []


@dataclass
class Suppression:
    """A parsed ``# mlt: ignore[...]`` comment."""

    line: int
    codes: tuple[str, ...]
    reason: str
    used: bool = field(default=False, compare=False)

    def matches(self, finding: Finding) -> bool:
        return finding.line == self.line and finding.code in self.codes


def parse_suppressions(source: str, path: str
                       ) -> tuple[list[Suppression], list[Finding]]:
    """Extract suppression comments via tokenize (never fooled by
    strings that look like comments). Returns (suppressions, findings)
    where findings are MLT000 malformed-suppression violations:
    missing reason, empty/invalid code list."""
    suppressions: list[Suppression] = []
    findings: list[Finding] = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        comments = [(tok.start[0], tok.string) for tok in tokens
                    if tok.type == tokenize.COMMENT]
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return [], []
    for line, text in comments:
        match = _IGNORE_RE.match(text)
        if not match:
            continue
        codes = tuple(c.strip() for c in match.group("codes").split(",")
                      if c.strip())
        rest = match.group("rest").strip()
        reason = rest[1:].strip() if rest.startswith(":") else ""
        bad = [c for c in codes if not _CODE_RE.match(c)]
        if not codes or bad:
            findings.append(Finding(
                SUPPRESSION_CODE, path, line,
                f"malformed suppression {text.strip()!r}: "
                f"expected mlt: ignore[MLT0xx]: <reason>",
                "use '# mlt: ignore[MLT0xx]: reason' with a real code"))
            continue
        if not reason:
            findings.append(Finding(
                SUPPRESSION_CODE, path, line,
                f"suppression for {','.join(codes)} has no reason",
                "append ': <one-line reason>' — unexplained ignores "
                "are the drift this tool exists to stop"))
            continue
        suppressions.append(Suppression(line, codes, reason))
    return suppressions, findings


def walk_functions(tree):
    """Yield (FunctionDef, qualname) for every function in a module,
    methods qualified as ``Class.method``, nested defs as
    ``outer.inner``."""
    import ast

    def rec(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                yield child, qual
                yield from rec(child, f"{qual}.")
            elif isinstance(child, ast.ClassDef):
                yield from rec(child, f"{prefix}{child.name}.")
    yield from rec(tree, "")


def walk_own(node):
    """Walk a node's subtree WITHOUT descending into nested
    defs/lambdas/classes — their bodies run later, under their own
    scope, not here."""
    import ast

    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(child))


def qualname_parts(node) -> list[str] | None:
    """Flatten an Attribute/Name chain (``a.b.c``) into parts, or None
    when the chain is rooted in something dynamic (a call, a
    subscript)."""
    import ast

    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return None
