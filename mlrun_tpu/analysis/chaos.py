"""MLT001 — chaos coherence (docs/fault_tolerance.md).

The fault-injection registry is only a safety net if the three views
of it stay coherent:

1. every ``fire("x")`` / ``chaos_fire("x")`` / ``chaos.inject("x")``
   string literal resolves to a declared ``FaultPoints`` attribute
   (and every ``FaultPoints.attr`` read exists) — a typo'd point is
   armed by nobody and fires into the void;
2. every declared point is fired somewhere (production code or the
   tests/ fakes) — a declared-but-unfired point is dead contract;
3. the docs/fault_tolerance.md point table lists every point — the
   table is what operators arm against.

Cross-file by nature: declarations load from chaos/registry.py in
``begin``, fires accumulate per file, coherence is judged in
``finish``. Test files (tests/…) count toward the "fired somewhere"
set but are never flagged — tests fire synthetic points ("p") on
purpose.
"""

from __future__ import annotations

import ast
import os

from .core import Checker, Finding

CODE = "MLT001"

#: call names whose first string-literal argument is a chaos point
_FIRE_NAMES = {"fire", "chaos_fire"}
_INJECT_NAMES = {"inject"}

#: modules where raw point literals are part of the implementation,
#: not call sites (rationale per entry — the checker allowlist policy)
ALLOWLIST_MODULES = {
    "mlrun_tpu/chaos/registry.py":
        "the registry itself: docstring examples and matching internals",
    "mlrun_tpu/analysis/chaos.py":
        "this checker's own examples",
}


def _load_declared(root: str
                   ) -> tuple[dict[str, int], dict[str, str], set[str],
                              set[str], str]:
    """Parse FaultPoints out of chaos/registry.py WITHOUT importing it.
    Returns ({point value -> decl line}, {attr -> point value},
    {every attr incl. methods}, {attrs listed in all()},
    registry path)."""
    reg_path = os.path.join(root, "mlrun_tpu", "chaos", "registry.py")
    declared: dict[str, int] = {}
    by_attr: dict[str, str] = {}
    attrs: set[str] = set()
    in_all: set[str] = set()
    try:
        with open(reg_path, encoding="utf-8") as fp:
            tree = ast.parse(fp.read())
    except (OSError, SyntaxError):
        return declared, by_attr, attrs, in_all, reg_path
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "FaultPoints":
            for stmt in node.body:
                if isinstance(stmt, ast.Assign):
                    for target in stmt.targets:
                        if isinstance(target, ast.Name):
                            attrs.add(target.id)
                            if (isinstance(stmt.value, ast.Constant)
                                    and isinstance(stmt.value.value,
                                                   str)):
                                declared[stmt.value.value] = stmt.lineno
                                by_attr[target.id] = stmt.value.value
                elif isinstance(stmt, ast.FunctionDef):
                    attrs.add(stmt.name)
                    if stmt.name == "all":
                        for sub in ast.walk(stmt):
                            if (isinstance(sub, ast.Attribute)
                                    and isinstance(sub.value, ast.Name)
                                    and sub.value.id == "FaultPoints"):
                                in_all.add(sub.attr)
    return declared, by_attr, attrs, in_all, reg_path


def _point_literals(tree) -> list[tuple[str, int, bool]]:
    """(point, line, is_inject) for every fire/inject call whose first
    arg is a string literal."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        func = node.func
        name = None
        if isinstance(func, ast.Name):
            name = func.id
        elif isinstance(func, ast.Attribute):
            name = func.attr
        if name not in _FIRE_NAMES and name not in _INJECT_NAMES:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            out.append((arg.value, node.lineno, name in _INJECT_NAMES))
    return out


class ChaosCoherenceChecker(Checker):
    code = CODE
    name = "chaos-coherence"

    def begin(self, root: str) -> None:
        self._root = root
        (self._declared, self._by_attr, self._attrs, self._in_all,
         self._registry_path) = _load_declared(root)
        self._fired: set[str] = set()
        self._whole_tree = False
        self._findings: list[Finding] = []
        try:
            docs = os.path.join(root, "docs", "fault_tolerance.md")
            with open(docs, encoding="utf-8") as fp:
                self._docs_text = fp.read()
        except OSError:
            self._docs_text = None
        # the fakes + chaos suites fire the k8s/provider verbs that
        # production only fires against a real cluster: they count
        # toward "fired somewhere" (never flagged — synthetic points
        # like "p" are a test idiom)
        tests_dir = os.path.join(root, "tests")
        if os.path.isdir(tests_dir):
            for fname in sorted(os.listdir(tests_dir)):
                if not fname.endswith(".py"):
                    continue
                try:
                    with open(os.path.join(tests_dir, fname),
                              encoding="utf-8") as fp:
                        tree = ast.parse(fp.read())
                except (OSError, SyntaxError):
                    continue
                for point, _line, _ in _point_literals(tree):
                    self._fired.add(point)
                for point in _attr_points(tree, self._by_attr):
                    self._fired.add(point)

    def visit(self, tree, source: str, path: str) -> list[Finding]:
        rel = os.path.relpath(path, self._root).replace(os.sep, "/")
        if rel == "mlrun_tpu/chaos/registry.py":
            # the registry's own FaultPoints.all() enumeration and
            # docstring examples must not count as fires — they would
            # mask the declared-but-never-fired check entirely. Seeing
            # the registry also marks this as a WHOLE-TREE scan: the
            # completeness checks in finish() only bind then (a
            # single-file scan fires almost nothing by construction)
            self._whole_tree = True
            return []
        findings: list[Finding] = []
        in_tests = rel.startswith("tests/")
        allowlisted = rel in ALLOWLIST_MODULES
        for point, line, is_inject in _point_literals(tree):
            self._fired.add(point)
            if in_tests or allowlisted:
                continue
            if point in self._declared:
                continue
            if is_inject and _wildcard_ok(point, self._declared):
                continue
            findings.append(Finding(
                CODE, path, line,
                f"chaos point '{point}' is not declared on FaultPoints",
                "declare it in mlrun_tpu/chaos/registry.py and add it "
                "to FaultPoints.all() + the docs/fault_tolerance.md "
                "point table"))
        # FaultPoints.<attr> reads must resolve
        for node in ast.walk(tree):
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "FaultPoints"
                    and isinstance(node.ctx, ast.Load)):
                if node.attr in self._by_attr:
                    self._fired.add(self._by_attr[node.attr])
                if node.attr not in self._attrs and not in_tests:
                    findings.append(Finding(
                        CODE, path, node.lineno,
                        f"FaultPoints.{node.attr} does not exist",
                        "declare the point on FaultPoints or fix the "
                        "attribute name"))
        return findings

    def finish(self) -> list[Finding]:
        if not self._whole_tree:
            return []
        findings: list[Finding] = []
        for attr, point in sorted(self._by_attr.items()):
            if self._in_all and attr not in self._in_all:
                findings.append(Finding(
                    CODE, self._registry_path, self._declared[point],
                    f"FaultPoints.{attr} ('{point}') is missing from "
                    f"FaultPoints.all()",
                    "add it to the all() list — tooling that "
                    "enumerates points can't see it otherwise"))
        for point, line in sorted(self._declared.items()):
            if point not in self._fired:
                findings.append(Finding(
                    CODE, self._registry_path, line,
                    f"declared chaos point '{point}' is never fired",
                    "thread fire(FaultPoints...) through the layer it "
                    "guards, or retire the declaration"))
            if self._docs_text is not None \
                    and f"`{point}`" not in self._docs_text:
                findings.append(Finding(
                    CODE, self._registry_path, line,
                    f"chaos point '{point}' missing from the "
                    f"docs/fault_tolerance.md point table",
                    "add a `point` row to the fault-point table"))
        return findings


def _wildcard_ok(point: str, declared: dict[str, int]) -> bool:
    if not point.endswith(".*"):
        return False
    prefix = point[:-1]  # keep the dot
    return any(p.startswith(prefix) for p in declared)


def _attr_points(tree, by_attr: dict[str, str]) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(tree):
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "FaultPoints"
                and node.attr in by_attr):
            out.add(by_attr[node.attr])
    return out
