"""MLT006 — config-key resolution against the defaults tree.

``mlconf`` is attribute-style access over the nested ``default_config``
dict in config.py. A typo'd chain (``mlconf.serving.llm.prefil_chunk``)
is not a syntax error and not an import error — it raises (or, through
``.get(...)``, silently reads the fallback default) only when that
exact code path runs, which for cold paths is production. This checker
resolves every literal ``mlconf.a.b.c`` chain and every literal
``<chain>.get("key")`` against the defaults tree parsed straight out
of config.py's AST — no import, no env resolution.

Chain walking stops at (a) a leaf value — further attributes are on
the VALUE (``mlconf.api_base_path.rstrip``), (b) a Config-object
method/property (``get``, ``update``, ``resolve_artifact_path``, …),
or (c) anything dynamic. Store context (``mlconf.x = ...``) is not
validated — tests and client_spec pushes create keys legitimately.
"""

from __future__ import annotations

import ast
import os

from .core import Checker, Finding

CODE = "MLT006"

#: (module, chain) -> rationale for a chain the defaults tree cannot
#: see (e.g. keys created at runtime by a client_spec push)
ALLOWLIST: dict[tuple[str, str], str] = {
}

_LEAF = object()


def _key_tree(config_path: str) -> tuple[dict | None, set[str]]:
    """(nested key tree from default_config, Config method/property
    names). Values are sub-dicts or _LEAF — we only need key shape,
    so non-literal values (BinOps, calls) are fine."""
    try:
        with open(config_path, encoding="utf-8") as fp:
            tree = ast.parse(fp.read())
    except (OSError, SyntaxError):
        return None, set()

    def build(node):
        if not isinstance(node, ast.Dict):
            return _LEAF
        out = {}
        for key, value in zip(node.keys, node.values):
            if isinstance(key, ast.Constant) and isinstance(key.value,
                                                            str):
                out[key.value] = build(value)
        return out

    keys = None
    methods: set[str] = set()
    for node in tree.body:
        target = None
        if isinstance(node, ast.AnnAssign):
            target = node.target
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
        if isinstance(target, ast.Name) \
                and target.id == "default_config" \
                and node.value is not None:
            keys = build(node.value)
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            for stmt in node.body:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    methods.add(stmt.name)
    return keys if isinstance(keys, dict) else None, methods


class ConfigKeyChecker(Checker):
    code = CODE
    name = "config-keys"

    def begin(self, root: str) -> None:
        self._root = root
        self._tree, self._methods = _key_tree(
            os.path.join(root, "mlrun_tpu", "config.py"))

    def visit(self, tree, source: str, path: str) -> list[Finding]:
        if self._tree is None:
            return []
        rel = os.path.relpath(path, self._root).replace(os.sep, "/")
        if rel.startswith("tests/") or rel.endswith("config.py"):
            return []
        # only modules that import mlconf from this package
        if not self._imports_mlconf(tree):
            return []
        findings: list[Finding] = []
        seen: set[int] = set()
        for node in ast.walk(tree):
            if not isinstance(node, ast.Attribute) \
                    or id(node) in seen:
                continue
            chain = self._mlconf_chain(node)
            if chain is None:
                continue
            # mark sub-attributes handled so a.b.c doesn't re-report
            # at a.b
            sub = node
            while isinstance(sub, ast.Attribute):
                seen.add(id(sub))
                sub = sub.value
            findings.extend(self._check_chain(chain, node, path, rel))
        # literal .get("key") off a chain (incl. bare mlconf.get("k"))
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            base_chain = self._base_parts(node.func.value)
            if base_chain is None:
                continue
            at = self._resolve(base_chain[1:])
            if isinstance(at, dict):
                key = node.args[0].value
                full = ".".join(base_chain[1:] + [key])
                if key not in at and (rel, full) not in ALLOWLIST:
                    findings.append(Finding(
                        CODE, path, node.lineno,
                        f"mlconf.{full} (via .get) does not resolve "
                        f"against the config.py defaults tree",
                        "fix the key or add it to default_config — "
                        "a typo'd get() silently reads the fallback"))
        return findings

    @staticmethod
    def _imports_mlconf(tree) -> bool:
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module \
                    and node.module.split(".")[-1] == "config":
                if any(alias.name == "mlconf" for alias in node.names):
                    return True
        return False

    def _mlconf_chain(self, node: ast.Attribute) -> list[str] | None:
        """Longest literal attribute chain rooted at Name('mlconf'),
        in Load context."""
        if not isinstance(node.ctx, ast.Load):
            return None
        parts = self._base_parts(node)
        if parts is None:
            return None
        return parts[1:]

    @staticmethod
    def _base_parts(node) -> list[str] | None:
        parts: list[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name) and node.id == "mlconf":
            parts.append("mlconf")
            parts.reverse()
            return parts
        return None

    def _resolve(self, chain: list[str]):
        """Walk the key tree; returns the node reached, or None when
        the walk fell off the tree (the caller decides if that is a
        finding), or _LEAF."""
        at = self._tree
        for part in chain:
            if not isinstance(at, dict):
                return at  # attribute on a leaf VALUE — out of scope
            if part in self._methods or part == "get":
                return _LEAF  # Config method/property terminates
            if part not in at:
                return None
            at = at[part]
        return at

    def _check_chain(self, chain: list[str], node, path: str,
                     rel: str) -> list[Finding]:
        at = self._tree
        for idx, part in enumerate(chain):
            if not isinstance(at, dict):
                return []  # leaf value reached — rest is on the value
            if part in self._methods:
                return []  # Config method/property
            if part not in at:
                full = ".".join(chain[:idx + 1])
                if (rel, full) in ALLOWLIST:
                    return []
                return [Finding(
                    CODE, path, node.lineno,
                    f"mlconf.{full} does not resolve against the "
                    f"config.py defaults tree",
                    "fix the key or add it to default_config so the "
                    "chain has a declared default")]
            at = at[part]
        return []
