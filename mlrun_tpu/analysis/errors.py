"""MLT005 — typed errors on the serving request path.

A ``raise Exception(...)`` / ``raise RuntimeError(...)`` on a request
path is an untyped 500: the resilience layer can't classify it
(retryable? shed? client bug?), the fleet can't decide to re-dispatch
it, and the client gets a stack trace instead of a status. Serving
code raises the typed hierarchy instead — ``ResilienceError``
subclasses (429/503/504 classes the dispatcher understands) or typed
``ValueError`` subclasses for 400-class client mistakes
(docs/serving_resilience.md).

Scope: every module under ``mlrun_tpu/serving/``. Offline/test-only
helpers that legitimately raise untyped go in the allowlist with a
rationale.
"""

from __future__ import annotations

import ast
import os

from .core import Checker, Finding, walk_functions, walk_own

CODE = "MLT005"

_BARE = {"Exception", "RuntimeError"}

#: (module, function qualname) -> rationale for an untyped raise
ALLOWLIST: dict[tuple[str, str], str] = {
    ("mlrun_tpu/serving/server.py", "GraphServer.test"):
        "offline test entry, never on a live request path — it "
        "re-raises a >=400 mock Response for interactive debugging",
}


class TypedErrorChecker(Checker):
    code = CODE
    name = "typed-errors"

    def begin(self, root: str) -> None:
        self._root = root

    def visit(self, tree, source: str, path: str) -> list[Finding]:
        rel = os.path.relpath(path, self._root).replace(os.sep, "/")
        if not rel.startswith("mlrun_tpu/serving/"):
            return []
        findings: list[Finding] = []
        for func, qual in walk_functions(tree):
            if (rel, qual) in ALLOWLIST:
                continue
            for node in walk_own(func):
                if not isinstance(node, ast.Raise) or node.exc is None:
                    continue
                name = None
                if isinstance(node.exc, ast.Call) \
                        and isinstance(node.exc.func, ast.Name):
                    name = node.exc.func.id
                elif isinstance(node.exc, ast.Name):
                    name = node.exc.id
                if name in _BARE:
                    findings.append(Finding(
                        CODE, path, node.lineno,
                        f"untyped raise {name} in {qual} on the "
                        f"serving path",
                        "raise a ResilienceError subclass (429/503/504 "
                        "classes) or a typed ValueError subclass (400) "
                        "— see docs/serving_resilience.md"))
        return findings


