"""mlt-lint: AST invariant checker for the framework's cross-cutting
contracts (docs/static_analysis.md).

Stdlib-only (ast + tokenize). Run it:

    python -m mlrun_tpu.analysis mlrun_tpu/          # human output
    make lint-invariants                             # + JSON artifact

Codes:

- MLT000 malformed/unreasoned suppression comment
- MLT001 chaos coherence (fire()/FaultPoints/docs agreement)
- MLT002 metrics discipline (one ctor site, label keys, retire, docs)
- MLT003 explicit-now in control loops (fake-clock testability)
- MLT004 blocking call under an engine lock
- MLT005 typed errors on the serving request path
- MLT006 mlconf key chains resolve against config.py defaults

Suppress one finding inline with ``# mlt: ignore[MLT0xx]: reason`` —
the reason is required. Structural exceptions go in each checker's
ALLOWLIST table with a one-line rationale.
"""

from .core import (  # noqa: F401
    Checker,
    Finding,
    SUPPRESSION_CODE,
    parse_suppressions,
)
from .engine import (  # noqa: F401
    AnalysisResult,
    default_checkers,
    iter_py_files,
    render_human,
    render_json,
    run_analysis,
)

CODES = {
    "MLT000": "malformed or unreasoned suppression comment",
    "MLT001": "chaos coherence: fire()/FaultPoints/docs agreement",
    "MLT002": "metrics discipline: ctor sites, label keys, retire, docs",
    "MLT003": "explicit-now: no wall clock in control-loop modules",
    "MLT004": "blocking call under an engine lock",
    "MLT005": "typed errors on the serving request path",
    "MLT006": "mlconf key chains resolve against config.py defaults",
}
