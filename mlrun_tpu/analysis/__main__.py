"""CLI: ``python -m mlrun_tpu.analysis [paths] [--json FILE]``.

Exit status 0 = zero unsuppressed findings (suppressed-with-reason is
fine), 1 = findings or parse errors — wired into ``make
lint-invariants`` and the obs-smoke preamble so invariant drift fails
fast, before any engine boots.
"""

from __future__ import annotations

import argparse
import sys

from . import CODES
from .engine import render_human, render_json, run_analysis


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mlrun_tpu.analysis",
        description="mlt-lint: AST invariant checker "
                    "(docs/static_analysis.md)")
    parser.add_argument("paths", nargs="*", default=["mlrun_tpu"],
                        help="files/dirs to check (default: mlrun_tpu)")
    parser.add_argument("--json", metavar="FILE", default=None,
                        help="also write the full JSON report here")
    parser.add_argument("--format", choices=("human", "json"),
                        default="human", help="stdout format")
    parser.add_argument("--list-codes", action="store_true",
                        help="print the MLT code table and exit")
    args = parser.parse_args(argv)

    if args.list_codes:
        for code, desc in sorted(CODES.items()):
            print(f"{code}  {desc}")
        return 0

    result = run_analysis(args.paths or ["mlrun_tpu"])
    if args.json:
        with open(args.json, "w", encoding="utf-8") as fp:
            fp.write(render_json(result) + "\n")
    if args.format == "json":
        print(render_json(result))
    else:
        print(render_human(result))
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
