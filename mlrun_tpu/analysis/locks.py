"""MLT004 — blocking calls under an engine lock.

The PR 4 stop()-race and the PR 9 bank-lock hardening were both the
same shape: a thread holding a hot lock reached something that can
block indefinitely (a join, a device op, an un-timed queue get), and
every other thread in the engine convoyed behind it. This checker
builds intra-module may-block summaries and flags any may-block call
lexically inside a ``with <lock>:`` body.

What counts as may-block (direct):

- ``time.sleep`` / bare ``sleep(...)``
- ``.result()`` / ``.join()`` / ``.wait()`` / ``.acquire()`` with no
  timeout bound
- ``requests.*`` / ``urlopen`` (network)
- ``.get(...)`` / ``.put(...)`` on a queue-named receiver without
  ``timeout=`` / ``block=False``
- jax device ops: ``device_put/device_get``, ``.block_until_ready()``
- file/socket I/O: ``open(...)``, ``.recv/.send/.accept/.connect``

Summaries propagate one module deep: a call to a same-module function
or ``self.`` method that may block is flagged too, with the chain in
the message. Seeded on the modules whose locks are the proven hazard
(engine scheduler, adapter bank lock, fleet ring lock) — widen
``CHECKED_MODULES`` as new lock-holding subsystems land.

Nested ``def`` bodies inside a with-block are NOT flagged (defining a
closure under a lock is free; calling it is what blocks) — the call
site is what gets charged.
"""

from __future__ import annotations

import ast
import os

from .core import Checker, Finding, qualname_parts, walk_functions, walk_own

CODE = "MLT004"

#: module (repo-relative) -> the lock this module is seeded for
CHECKED_MODULES = {
    "mlrun_tpu/serving/llm_batch.py":
        "engine scheduler lock (self._lock) — the PR 4 stop()-race lock",
    "mlrun_tpu/serving/paged.py":
        "paged engine: shares the scheduler-lock discipline",
    "mlrun_tpu/serving/adapters.py":
        "AdapterRegistry bank lock — the PR 9 hardening target",
    "mlrun_tpu/serving/fleet.py":
        "fleet ring lock — dispatch must never stall behind it",
    "mlrun_tpu/serving/prefix.py":
        "radix-index lock on the admission path",
}

#: (module, function qualname) -> rationale for a may-block call that
#: is provably bounded or intentional under its lock. Prefer
#: restructuring (move the call outside the lock);
#: this table is for sites where the blocking bound is real but
#: invisible to the AST.
ALLOWLIST: dict[tuple[str, str], str] = {
    ("mlrun_tpu/serving/llm_batch.py",
     "ContinuousBatchingEngine._enqueue"):
        "self._queue is unbounded (queue.Queue()); put() cannot block "
        "— the lock exists to order the put against the expiry "
        "sweep's atomic drain/re-put",
    ("mlrun_tpu/serving/llm_batch.py",
     "ContinuousBatchingEngine._expire_queued"):
        "re-putting drained items back onto the unbounded queue; "
        "put() cannot block and the drain/re-put must be atomic "
        "under the scheduler lock",
}

_LOCK_NAME_HINTS = ("lock",)
_LOCK_NAME_EXCLUDE = ("cond", "unlock")

_NETWORK_ROOTS = {"requests", "urllib", "httpx"}
_QUEUE_HINTS = ("queue", "_q")
_UNTIMED_METHODS = {"result", "join", "wait"}  # acquire: own branch
_JAX_BLOCKING = {("jax", "device_put"), ("jax", "device_get"),
                 ("device_put",), ("device_get",)}
_SOCKET_METHODS = {"recv", "send", "sendall", "accept", "connect"}


def _has_timeout(node: ast.Call) -> bool:
    if any(kw.arg == "timeout" and not _is_none(kw.value)
           for kw in node.keywords):
        return True
    # positional timeout on result()/join()/wait(): first arg —
    # unless it is literally None, which is the unbounded spelling
    return bool(node.args) and not _is_none(node.args[0])


def _acquire_bounded(node: ast.Call) -> bool:
    """lock.acquire(): signature is (blocking=True, timeout=-1) — the
    FIRST positional is ``blocking``, not a timeout. Bounded iff
    non-blocking or a real timeout is given."""
    args = node.args
    if args and isinstance(args[0], ast.Constant) \
            and args[0].value is False:
        return True  # acquire(False) — non-blocking try-lock
    if any(kw.arg == "blocking" and isinstance(kw.value, ast.Constant)
           and kw.value.value is False for kw in node.keywords):
        return True
    if any(kw.arg == "timeout" and not _is_none(kw.value)
           for kw in node.keywords):
        return True
    # acquire(True, 5.0): second positional is the timeout
    return len(args) >= 2 and not _is_none(args[1])


def _is_none(node) -> bool:
    return isinstance(node, ast.Constant) and node.value is None


def _blocks_directly(node: ast.Call) -> str | None:
    """Return a human description when the call itself may block."""
    func = node.func
    parts = qualname_parts(func)
    # time.sleep / sleep
    if parts in (["time", "sleep"], ["sleep"]):
        return "sleep()"
    if parts and parts[0] in _NETWORK_ROOTS:
        return f"network call {'.'.join(parts)}"
    if parts in (["urlopen"],):
        return "urlopen()"
    if parts == ["open"]:
        return "open() file I/O"
    if parts and tuple(parts) in _JAX_BLOCKING:
        return f"jax device op {'.'.join(parts)}"
    if isinstance(func, ast.Attribute):
        attr = func.attr
        if attr == "block_until_ready":
            return ".block_until_ready()"
        if attr == "acquire":
            if not _acquire_bounded(node):
                return ".acquire() with no timeout"
        elif attr in _UNTIMED_METHODS and not _has_timeout(node):
            return f".{attr}() with no timeout"
        if attr in _SOCKET_METHODS:
            return f"socket .{attr}()"
        if attr in ("get", "put"):
            recv = func.value
            recv_parts = qualname_parts(recv) or []
            recv_text = "_".join(recv_parts).lower()
            if any(h in recv_text for h in _QUEUE_HINTS):
                timed = any(kw.arg == "timeout" for kw in node.keywords)
                nonblock = any(
                    kw.arg == "block"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is False
                    for kw in node.keywords)
                if not timed and not nonblock:
                    return f"un-timed queue .{attr}()"
    return None


def _is_lock_expr(node) -> bool:
    parts = qualname_parts(node)
    if not parts:
        return False
    last = parts[-1].lower()
    if any(ex in last for ex in _LOCK_NAME_EXCLUDE):
        return False
    return any(hint in last for hint in _LOCK_NAME_HINTS)


class _ModuleIndex:
    """Intra-module call graph + may-block summaries."""

    def __init__(self, tree):
        # qualname -> FunctionDef; also method name -> [qualnames] for
        # self.X resolution across classes (approximate: any class's
        # method of that name)
        self.functions: dict[str, ast.AST] = {}
        self.by_method: dict[str, list[str]] = {}
        for func, qual in walk_functions(tree):
            self.functions[qual] = func
            self.by_method.setdefault(func.name, []).append(qual)
        self._blocks: dict[str, str | None] = {}

    def _callees(self, func) -> list[str]:
        out = []
        for node in walk_own(func):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name) and f.id in self.by_method:
                out.extend(self.by_method[f.id])
            elif (isinstance(f, ast.Attribute)
                  and isinstance(f.value, ast.Name)
                  and f.value.id == "self"
                  and f.attr in self.by_method):
                out.extend(self.by_method[f.attr])
        return out

    def may_block(self, qual: str, _seen=None) -> str | None:
        """None, or a 'via' chain description ending at a blocking
        leaf."""
        if qual in self._blocks:
            return self._blocks[qual]
        seen = _seen or set()
        if qual in seen:
            return None
        seen.add(qual)
        func = self.functions.get(qual)
        if func is None:
            return None
        self._blocks[qual] = None  # cycle guard for memo
        for node in walk_own(func):
            if isinstance(node, ast.Call):
                desc = _blocks_directly(node)
                if desc:
                    self._blocks[qual] = \
                        f"{desc} at line {node.lineno}"
                    return self._blocks[qual]
        for callee in self._callees(func):
            via = self.may_block(callee, seen)
            if via:
                self._blocks[qual] = f"{callee} -> {via}"
                return self._blocks[qual]
        return None


class BlockingUnderLockChecker(Checker):
    code = CODE
    name = "blocking-under-lock"

    def begin(self, root: str) -> None:
        self._root = root

    def visit(self, tree, source: str, path: str) -> list[Finding]:
        rel = os.path.relpath(path, self._root).replace(os.sep, "/")
        if rel not in CHECKED_MODULES:
            return []
        index = _ModuleIndex(tree)
        findings: list[Finding] = []
        for func, qual in walk_functions(tree):
            for node in walk_own(func):
                if not isinstance(node, ast.With):
                    continue
                if not any(_is_lock_expr(item.context_expr)
                           for item in node.items):
                    continue
                for call, desc in self._blocking_in(node, index):
                    key = (rel, qual)
                    if key in ALLOWLIST:
                        continue
                    findings.append(Finding(
                        CODE, path, call.lineno,
                        f"may-block under lock in {qual}: {desc} "
                        f"({CHECKED_MODULES[rel]})",
                        "move the call outside the lock, bound it "
                        "with a timeout, or add an ALLOWLIST entry "
                        "with the bound's rationale"))
        return findings

    def _blocking_in(self, with_node: ast.With, index: _ModuleIndex):
        """Yield (call, description) for may-block calls lexically
        inside the with body (nested defs excluded)."""
        for stmt in with_node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # defining a closure under the lock is free
            for node in walk_own(stmt):
                if not isinstance(node, ast.Call):
                    continue
                desc = _blocks_directly(node)
                if desc:
                    yield node, desc
                    continue
                f = node.func
                targets = []
                if isinstance(f, ast.Name) and f.id in index.by_method:
                    targets = index.by_method[f.id]
                elif (isinstance(f, ast.Attribute)
                      and isinstance(f.value, ast.Name)
                      and f.value.id == "self"
                      and f.attr in index.by_method):
                    targets = index.by_method[f.attr]
                for target in targets:
                    via = index.may_block(target)
                    if via:
                        yield node, f"call into {target} which may " \
                                    f"block ({via})"
                        break



