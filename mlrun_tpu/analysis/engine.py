"""Analysis engine: walk paths, run checker plugins, apply
suppressions, render (docs/static_analysis.md).

Deterministic by construction: files are visited in sorted order and
findings are sorted on (path, line, code, message) before rendering, so
the same tree always produces the same report — the property the tier-1
determinism test asserts.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field

from .core import (
    Checker,
    Finding,
    SUPPRESSION_CODE,
    parse_suppressions,
)

_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules",
              ".pytest_cache", ".hypothesis", "build", "dist"}


def default_checkers() -> list[Checker]:
    from .chaos import ChaosCoherenceChecker
    from .clock import ExplicitNowChecker
    from .confkeys import ConfigKeyChecker
    from .errors import TypedErrorChecker
    from .locks import BlockingUnderLockChecker
    from .metrics import MetricsDisciplineChecker

    return [
        ChaosCoherenceChecker(),
        MetricsDisciplineChecker(),
        ExplicitNowChecker(),
        BlockingUnderLockChecker(),
        TypedErrorChecker(),
        ConfigKeyChecker(),
    ]


def iter_py_files(paths) -> list[str]:
    files: list[str] = []
    for path in paths:
        path = os.path.abspath(path)
        if os.path.isfile(path):
            if path.endswith(".py"):
                files.append(path)
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS)
            for name in sorted(filenames):
                if name.endswith(".py"):
                    files.append(os.path.join(dirpath, name))
    return sorted(set(files))


def find_repo_root(paths) -> str:
    """The directory that CONTAINS the ``mlrun_tpu`` package — walk up
    from the first path until ``mlrun_tpu/__init__.py`` appears.
    Checkers use it to load cross-file contract sources (the chaos
    registry, config defaults, docs tables)."""
    start = os.path.abspath(paths[0] if paths else ".")
    node = start if os.path.isdir(start) else os.path.dirname(start)
    while True:
        if os.path.isfile(os.path.join(node, "mlrun_tpu", "__init__.py")):
            return node
        parent = os.path.dirname(node)
        if parent == node:
            # filesystem root reached: fall back to the checkout this
            # module lives in (…/<root>/mlrun_tpu/analysis/engine.py)
            return os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
        node = parent


@dataclass
class AnalysisResult:
    findings: list[Finding] = field(default_factory=list)
    suppressed: list[dict] = field(default_factory=list)
    files_checked: int = 0
    parse_errors: list[dict] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings and not self.parse_errors

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "files_checked": self.files_checked,
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": self.suppressed,
            "parse_errors": self.parse_errors,
        }


def _rel(path: str, root: str) -> str:
    try:
        rel = os.path.relpath(path, root)
    except ValueError:
        return path
    return path if rel.startswith("..") else rel


def run_analysis(paths, checkers: list[Checker] | None = None,
                 root: str | None = None) -> AnalysisResult:
    """Run every checker over every ``.py`` file under ``paths``."""
    checkers = default_checkers() if checkers is None else checkers
    files = iter_py_files(paths)
    root = root or find_repo_root(paths or ["."])
    result = AnalysisResult()

    for checker in checkers:
        checker.begin(root)

    raw: list[Finding] = []
    suppressions_by_path: dict[str, list] = {}
    for path in files:
        rel = _rel(path, root)
        try:
            with open(path, encoding="utf-8") as fp:
                source = fp.read()
            tree = ast.parse(source, filename=path)
        except (SyntaxError, UnicodeDecodeError, OSError) as exc:
            result.parse_errors.append({"path": rel, "error": str(exc)})
            continue
        result.files_checked += 1
        sups, sup_findings = parse_suppressions(source, rel)
        suppressions_by_path[rel] = sups
        raw.extend(sup_findings)
        for checker in checkers:
            raw.extend(checker.visit(tree, source, path) or [])
    for checker in checkers:
        raw.extend(checker.finish() or [])

    for finding in raw:
        finding = Finding(finding.code, _rel(finding.path, root),
                          finding.line, finding.message, finding.remedy)
        sup = next((s for s in suppressions_by_path.get(finding.path, [])
                    if s.matches(finding)), None)
        if sup is not None:
            sup.used = True
            entry = finding.to_dict()
            entry["reason"] = sup.reason
            result.suppressed.append(entry)
        else:
            result.findings.append(finding)

    # a suppression that matched nothing is rot: the site it excused
    # was fixed (delete the comment) or drifted lines (re-anchor it) —
    # exactly the unexplained-ignore decay MLT000 exists to stop
    for rel_path, sups in suppressions_by_path.items():
        for sup in sups:
            if not sup.used:
                result.findings.append(Finding(
                    SUPPRESSION_CODE, rel_path, sup.line,
                    f"suppression for {','.join(sup.codes)} matched "
                    f"no finding",
                    "delete the stale ignore comment, or re-anchor it "
                    "to the line the finding reports"))

    result.findings.sort(key=Finding.sort_key)
    result.suppressed.sort(
        key=lambda d: (d["path"], d["line"], d["code"], d["message"]))
    result.parse_errors.sort(key=lambda d: d["path"])
    return result


def render_human(result: AnalysisResult) -> str:
    lines = []
    for err in result.parse_errors:
        lines.append(f"{err['path']}: PARSE ERROR {err['error']}")
    for finding in result.findings:
        lines.append(finding.render())
    lines.append(
        f"mlt-lint: {result.files_checked} files, "
        f"{len(result.findings)} finding(s), "
        f"{len(result.suppressed)} suppressed"
        + (f", {len(result.parse_errors)} parse error(s)"
           if result.parse_errors else ""))
    return "\n".join(lines)


def render_json(result: AnalysisResult) -> str:
    return json.dumps(result.to_dict(), indent=2, sort_keys=True)
