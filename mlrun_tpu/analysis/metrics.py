"""MLT002 — metrics discipline (docs/observability.md).

Four machine-checkable halves of the telemetry contract:

1. **one constructor site per family** — ``REGISTRY.counter/gauge/
   histogram("mlt_*", ...)`` is get-or-create, so a second declaration
   silently aliases the first and the two sites drift (labels, help,
   buckets) without anything failing;
2. **label-key agreement** — every ``FAMILY.inc/set/observe/set_total/
   remove(...)`` call site must pass exactly the declared label keys
   (a missing key raises at runtime only when that code path runs; an
   extra key the same — catch both at parse time);
3. **engine stop/retire coverage** — ``replica``-labeled families an
   engine module feeds must be referenced from that module's
   stop/retire path (functions named stop/close/retire/remove*),
   because scale-down leaking series is the PR 7/PR 9 cardinality bug
   class;
4. **docs coverage** — every declared ``mlt_*`` family appears in the
   docs/observability.md series table.

Declarations and call sites live in different modules, so everything
buffers per file and is judged in ``finish``.
"""

from __future__ import annotations

import ast
import os

from .core import Checker, Finding

CODE = "MLT002"

_CTOR_METHODS = {"counter", "gauge", "histogram"}
_USE_METHODS = {"inc", "set", "observe", "set_total", "remove"}
#: kwargs on use methods that are values, not labels
_VALUE_KWARGS = {"value", "exemplar"}
#: function-name fragments that mark a stop/retire scope
_RETIRE_FRAGMENTS = ("stop", "retire", "remove", "close", "shutdown")

#: engine modules where replica-labeled families must be retired
#: (rationale per entry — the checker allowlist policy)
ENGINE_MODULES = {
    "mlrun_tpu/serving/llm_batch.py":
        "continuous-batching engine: owns the mlt_llm_* replica series",
    "mlrun_tpu/serving/paged.py":
        "paged engine subclass: inherits llm_batch's series ownership",
    "mlrun_tpu/serving/fleet.py":
        "fleet router: owns mlt_fleet_dispatches_total replica series",
    "mlrun_tpu/serving/adapters.py":
        "adapter registry: feeds mlt_adapter_* through its host engine",
}

#: (family, module) pairs exempt from the label-agreement check, with
#: rationale — prefer fixing the call site; this table is for sites
#: that are structurally correct but beyond the AST's reach
LABEL_ALLOWLIST: dict[tuple[str, str], str] = {
}


def _str_tuple(node) -> tuple[str, ...] | None:
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, str)):
                return None
            out.append(elt.value)
        return tuple(out)
    return None


class MetricsDisciplineChecker(Checker):
    code = CODE
    name = "metrics-discipline"

    def begin(self, root: str) -> None:
        self._root = root
        # family -> list of (path, line, labels-or-None)
        self._ctors: dict[str, list] = {}
        # (module rel, var name) -> family (the declaring module's
        # binding wins in that module)
        self._local_vars: dict[tuple, str] = {}
        # var name -> set of families bound to it anywhere; a use in a
        # NON-declaring module resolves only when unambiguous (imports
        # preserve names, but two modules may reuse one name for
        # different families — then the AST can't tell which was
        # imported, so the site is skipped rather than mis-checked)
        self._global_vars: dict[str, set] = {}
        # buffered use sites: (module rel, var, method, labels, path,
        # line)
        self._uses: list[tuple] = []
        # module rel -> set of var names referenced in retire scopes
        self._retire_refs: dict[str, set] = {}
        # module rel -> set of (var, line) with non-retire use
        self._module_uses: dict[str, set] = {}
        try:
            docs = os.path.join(root, "docs", "observability.md")
            with open(docs, encoding="utf-8") as fp:
                self._docs_text = fp.read()
        except OSError:
            self._docs_text = None

    def visit(self, tree, source: str, path: str) -> list[Finding]:
        rel = os.path.relpath(path, self._root).replace(os.sep, "/")
        in_tests = rel.startswith("tests/")
        # -- constructor sites (declarations bind module-level vars) --
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                value = node.value
                fam = self._ctor_family(value)
                if fam is not None and not in_tests:
                    labels = None
                    for kw in value.keywords:
                        if kw.arg == "labels":
                            labels = _str_tuple(kw.value)
                    self._ctors.setdefault(fam, []).append(
                        (path, value.lineno, labels))
                    for target in node.targets:
                        if isinstance(target, ast.Name):
                            self._local_vars[(rel, target.id)] = fam
                            self._global_vars.setdefault(
                                target.id, set()).add(fam)
            elif isinstance(node, ast.Call):
                fam = self._ctor_family(node)
                if fam is not None and not in_tests:
                    # bare (non-assigned) declaration — still a site
                    known = self._ctors.get(fam, [])
                    if not any(line == node.lineno and p == path
                               for p, line, _ in known):
                        self._ctors.setdefault(fam, []).append(
                            (path, node.lineno, None))
        if in_tests:
            return []
        # -- use sites ------------------------------------------------
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _USE_METHODS
                    and isinstance(node.func.value, ast.Name)):
                continue
            var = node.func.value.id
            if not var.isupper():
                continue  # only the module-level family bindings
            if any(kw.arg is None for kw in node.keywords):
                continue  # **labels — dynamic, out of AST reach
            labels = frozenset(kw.arg for kw in node.keywords
                               if kw.arg not in _VALUE_KWARGS)
            self._uses.append((rel, var, node.func.attr, labels, path,
                               node.lineno))
            if node.func.attr != "remove":
                self._module_uses.setdefault(rel, set()).add(var)
        # -- retire scopes --------------------------------------------
        refs = self._retire_refs.setdefault(rel, set())
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and any(frag in node.name.lower()
                            for frag in _RETIRE_FRAGMENTS):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Name):
                        refs.add(sub.id)
                    elif isinstance(sub, ast.Attribute):
                        refs.add(sub.attr)
        return []

    def _ctor_family(self, node) -> str | None:
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _CTOR_METHODS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and node.args[0].value.startswith("mlt_")):
            return node.args[0].value
        return None

    def _resolve_var(self, rel: str, var: str) -> str | None:
        """Family a variable name denotes in ``rel``: the module's own
        binding, else the globally-unambiguous one (imported names)."""
        local = self._local_vars.get((rel, var))
        if local is not None:
            return local
        fams = self._global_vars.get(var, set())
        return next(iter(fams)) if len(fams) == 1 else None

    def finish(self) -> list[Finding]:
        findings: list[Finding] = []
        # 1. exactly one constructor site per family
        for fam, sites in sorted(self._ctors.items()):
            if len(sites) > 1:
                first = sorted(sites, key=lambda s: (s[0], s[1]))[0]
                for path, line, _labels in sorted(
                        sites, key=lambda s: (s[0], s[1]))[1:]:
                    findings.append(Finding(
                        CODE, path, line,
                        f"family '{fam}' declared again (first at "
                        f"{os.path.relpath(first[0], self._root)}:"
                        f"{first[1]}) — get-or-create aliases them "
                        f"silently",
                        "import the family object from its declaring "
                        "module instead of re-declaring"))
        declared_labels = {
            fam: sites[0][2] or ()
            for fam, sites in self._ctors.items() if sites}
        # 2. label-key agreement at every use site
        for rel, var, method, labels, path, line in self._uses:
            fam = self._resolve_var(rel, var)
            if fam is None or fam not in declared_labels:
                continue
            if (fam, rel) in LABEL_ALLOWLIST:
                continue
            expected = frozenset(declared_labels[fam])
            if labels != expected:
                missing = sorted(expected - labels)
                extra = sorted(labels - expected)
                detail = []
                if missing:
                    detail.append(f"missing {missing}")
                if extra:
                    detail.append(f"unexpected {extra}")
                findings.append(Finding(
                    CODE, path, line,
                    f"{var}.{method} label keys disagree with the "
                    f"'{fam}' declaration ({', '.join(detail)})",
                    f"pass exactly {sorted(expected)} — the declared "
                    f"label-key set"))
        # 3. engine stop/retire coverage for replica-labeled families
        for rel in sorted(self._module_uses):
            if rel not in ENGINE_MODULES:
                continue
            refs = self._retire_refs.get(rel, set())
            for var in sorted(self._module_uses[rel]):
                fam = self._resolve_var(rel, var)
                if fam is None:
                    continue
                if "replica" not in declared_labels.get(fam, ()):
                    continue
                if var not in refs:
                    findings.append(Finding(
                        CODE, os.path.join(self._root, rel), 1,
                        f"replica-labeled family {var} ('{fam}') is fed "
                        f"by this engine module but never referenced "
                        f"from a stop/retire scope",
                        "remove the series in the engine's "
                        "stop()/remove_series() path — scale-down must "
                        "not leak per-replica series"))
        # 4. docs coverage
        if self._docs_text is not None:
            for fam, sites in sorted(self._ctors.items()):
                if fam not in self._docs_text:
                    path, line, _labels = sorted(
                        sites, key=lambda s: (s[0], s[1]))[0]
                    findings.append(Finding(
                        CODE, path, line,
                        f"family '{fam}' missing from the "
                        f"docs/observability.md series table",
                        "add a row to the 'Key series' table"))
        return findings
