"""Training data pipelines.

Replaces the reference's DistributedSampler+DataLoader role
(frameworks/pytorch/mlrun_interface.py:903): batches are produced on host as
full global arrays and placed with a sharded NamedSharding — each host only
materializes what it feeds its local devices in multi-host (via
jax.make_array_from_process_local_data when running SPMD).
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np


def synthetic_token_stream(batch_size: int, seq_len: int, vocab_size: int,
                           seed: int = 0) -> Iterator[tuple]:
    """Deterministic synthetic LM batches: (tokens, targets)."""
    rng = np.random.default_rng(seed)
    while True:
        tokens = rng.integers(0, vocab_size, (batch_size, seq_len + 1),
                              dtype=np.int32)
        yield tokens[:, :-1], tokens[:, 1:]


def array_token_stream(token_array: np.ndarray, batch_size: int, seq_len: int,
                       shuffle: bool = True, seed: int = 0,
                       drop_last: bool = True) -> Iterator[tuple]:
    """Chunk a flat token array into LM batches, looping forever."""
    tokens = np.asarray(token_array, dtype=np.int32).reshape(-1)
    n_chunks = (len(tokens) - 1) // seq_len
    if n_chunks < 1:
        raise ValueError("token array shorter than one sequence")
    inputs = tokens[: n_chunks * seq_len].reshape(n_chunks, seq_len)
    targets = tokens[1: n_chunks * seq_len + 1].reshape(n_chunks, seq_len)
    rng = np.random.default_rng(seed)
    while True:
        order = rng.permutation(n_chunks) if shuffle else np.arange(n_chunks)
        for start in range(0, n_chunks - batch_size + 1, batch_size):
            idx = order[start: start + batch_size]
            yield inputs[idx], targets[idx]


def text_file_stream(path: str, tokenizer, batch_size: int, seq_len: int,
                     **kwargs) -> Iterator[tuple]:
    """Tokenize a text file (HF tokenizer) into an LM stream."""
    with open(path) as fp:
        text = fp.read()
    ids = np.asarray(tokenizer(text)["input_ids"], dtype=np.int32)
    return array_token_stream(ids, batch_size, seq_len, **kwargs)


def per_process_batch(global_batch: np.ndarray, sharding):
    """Multi-host: build a global jax.Array from this process's slice."""
    import jax

    if jax.process_count() == 1:
        return jax.device_put(global_batch, sharding)
    return jax.make_array_from_process_local_data(sharding, global_batch)
