"""Training data pipelines.

Replaces the reference's DistributedSampler+DataLoader role
(frameworks/pytorch/mlrun_interface.py:903): batches are produced on host as
full global arrays and placed with a sharded NamedSharding — each host only
materializes what it feeds its local devices in multi-host (via
jax.make_array_from_process_local_data when running SPMD).
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np


def synthetic_token_stream(batch_size: int, seq_len: int, vocab_size: int,
                           seed: int = 0) -> Iterator[tuple]:
    """Deterministic synthetic LM batches: (tokens, targets)."""
    rng = np.random.default_rng(seed)
    while True:
        tokens = rng.integers(0, vocab_size, (batch_size, seq_len + 1),
                              dtype=np.int32)
        yield tokens[:, :-1], tokens[:, 1:]


def array_token_stream(token_array: np.ndarray, batch_size: int, seq_len: int,
                       shuffle: bool = True, seed: int = 0,
                       drop_last: bool = True) -> Iterator[tuple]:
    """Chunk a flat token array into LM batches, looping forever."""
    tokens = np.asarray(token_array, dtype=np.int32).reshape(-1)
    n_chunks = (len(tokens) - 1) // seq_len
    if n_chunks < 1:
        raise ValueError("token array shorter than one sequence")
    inputs = tokens[: n_chunks * seq_len].reshape(n_chunks, seq_len)
    targets = tokens[1: n_chunks * seq_len + 1].reshape(n_chunks, seq_len)
    rng = np.random.default_rng(seed)
    while True:
        order = rng.permutation(n_chunks) if shuffle else np.arange(n_chunks)
        for start in range(0, n_chunks - batch_size + 1, batch_size):
            idx = order[start: start + batch_size]
            yield inputs[idx], targets[idx]


def text_file_stream(path: str, tokenizer, batch_size: int, seq_len: int,
                     **kwargs) -> Iterator[tuple]:
    """Tokenize a text file (HF tokenizer) into an LM stream."""
    with open(path) as fp:
        text = fp.read()
    ids = np.asarray(tokenizer(text)["input_ids"], dtype=np.int32)
    return array_token_stream(ids, batch_size, seq_len, **kwargs)


def per_process_batch(global_batch: np.ndarray, sharding):
    """Multi-host: build a global jax.Array from this process's slice."""
    import jax

    if jax.process_count() == 1:
        return jax.device_put(global_batch, sharding)
    return jax.make_array_from_process_local_data(sharding, global_batch)


# -- native token-shard loader (native/data_loader.cpp) ----------------------

def _native_lib_path() -> str:
    import os

    env = os.environ.get("MLT_DATA_LOADER_LIB")
    if env:
        return env
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(here, "native", "libmlt_data.so")


class TokenShardLoader:
    """Native prefetching loader over flat token-shard files.

    Replaces the reference's DataLoader worker processes
    (mlrun/frameworks/pytorch/mlrun_interface.py:903) with
    native/data_loader.cpp: shards are mmapped read-only, worker threads
    cut seeded-shuffled (seq+1)-token windows and stage whole batches in
    a bounded ring buffer — the Python side does ONE memcpy per batch and
    the TPU step never waits on IO. Yields (tokens, targets) int32 arrays
    like synthetic_token_stream.

    Shard format: little-endian flat token files, int32 (dtype="int32")
    or uint16 (dtype="uint16") — the usual pretokenized .bin layout.
    """

    def __init__(self, paths, batch_size: int, seq_len: int,
                 dtype: str = "int32", seed: int = 0, workers: int = 2,
                 queue_depth: int = 4, lib_path: str = ""):
        import ctypes
        import os

        if isinstance(paths, (str, bytes)):
            paths = [paths]
        self.paths = [str(p) for p in paths]
        for p in self.paths:
            if not os.path.isfile(p):
                raise FileNotFoundError(p)
        self.batch_size = batch_size
        self.seq_len = seq_len
        code = {"int32": 4, "uint16": 2}.get(dtype)
        if code is None:
            raise ValueError(f"dtype must be int32|uint16, got {dtype}")

        lib_path = lib_path or _native_lib_path()
        self._lib = ctypes.CDLL(lib_path)
        self._lib.mlt_loader_open.restype = ctypes.c_uint64
        self._lib.mlt_loader_open.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_uint32,
            ctypes.c_int, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint32]
        self._lib.mlt_loader_next.restype = ctypes.c_int
        self._lib.mlt_loader_next.argtypes = [
            ctypes.c_uint64, ctypes.POINTER(ctypes.c_int32)]
        self._lib.mlt_loader_total_tokens.restype = ctypes.c_uint64
        self._lib.mlt_loader_epoch.restype = ctypes.c_uint64
        self._lib.mlt_loader_close.argtypes = [ctypes.c_uint64]

        arr = (ctypes.c_char_p * len(self.paths))(
            *[p.encode() for p in self.paths])
        self._handle = self._lib.mlt_loader_open(
            arr, len(self.paths), code, batch_size, seq_len, seed,
            workers, queue_depth)
        if not self._handle:
            raise RuntimeError(
                f"mlt_loader_open failed for {self.paths} (empty shards, "
                f"bad dtype, or shards shorter than seq_len+1)")
        self._buf = np.empty((batch_size, seq_len + 1), np.int32)

    @property
    def total_tokens(self) -> int:
        return int(self._lib.mlt_loader_total_tokens(self._handle))

    @property
    def epoch(self) -> int:
        return int(self._lib.mlt_loader_epoch(self._handle))

    def __iter__(self):
        return self

    def __next__(self) -> tuple:
        import ctypes

        ok = self._lib.mlt_loader_next(
            self._handle,
            self._buf.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if not ok:
            raise StopIteration
        tokens = self._buf[:, :-1].copy()
        targets = self._buf[:, 1:].copy()
        return tokens, targets

    def close(self):
        if getattr(self, "_handle", 0):
            self._lib.mlt_loader_close(self._handle)
            self._handle = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass


def device_prefetch(stream, sharding=None, depth: int = 2):
    """Wrap a (tokens, targets) host iterator with device-side prefetch:
    keeps ``depth`` batches already transferred (optionally with a
    NamedSharding) so the train step never waits on host->HBM copies."""
    import collections

    import jax

    queue = collections.deque()

    def put(item):
        tokens, targets = item
        if sharding is not None:
            return (jax.device_put(tokens, sharding),
                    jax.device_put(targets, sharding))
        return jax.device_put(tokens), jax.device_put(targets)

    iterator = iter(stream)
    try:
        for _ in range(depth):
            queue.append(put(next(iterator)))
    except StopIteration:
        pass
    while queue:
        out = queue.popleft()
        try:
            queue.append(put(next(iterator)))
        except StopIteration:
            pass
        yield out
