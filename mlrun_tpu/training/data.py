"""Training data pipelines.

Replaces the reference's DistributedSampler+DataLoader role
(frameworks/pytorch/mlrun_interface.py:903): batches are produced on host as
full global arrays and placed with a sharded NamedSharding — each host only
materializes what it feeds its local devices in multi-host (via
jax.make_array_from_process_local_data when running SPMD).
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from typing import Iterator, Optional

import numpy as np

from ..chaos import FaultPoints, fire


def synthetic_token_stream(batch_size: int, seq_len: int, vocab_size: int,
                           seed: int = 0) -> Iterator[tuple]:
    """Deterministic synthetic LM batches: (tokens, targets)."""
    rng = np.random.default_rng(seed)
    while True:
        tokens = rng.integers(0, vocab_size, (batch_size, seq_len + 1),
                              dtype=np.int32)
        yield tokens[:, :-1], tokens[:, 1:]


def array_token_stream(token_array: np.ndarray, batch_size: int, seq_len: int,
                       shuffle: bool = True, seed: int = 0,
                       drop_last: bool = True) -> Iterator[tuple]:
    """Chunk a flat token array into LM batches, looping forever."""
    tokens = np.asarray(token_array, dtype=np.int32).reshape(-1)
    n_chunks = (len(tokens) - 1) // seq_len
    if n_chunks < 1:
        raise ValueError("token array shorter than one sequence")
    inputs = tokens[: n_chunks * seq_len].reshape(n_chunks, seq_len)
    targets = tokens[1: n_chunks * seq_len + 1].reshape(n_chunks, seq_len)
    rng = np.random.default_rng(seed)
    while True:
        order = rng.permutation(n_chunks) if shuffle else np.arange(n_chunks)
        for start in range(0, n_chunks - batch_size + 1, batch_size):
            idx = order[start: start + batch_size]
            yield inputs[idx], targets[idx]


def text_file_stream(path: str, tokenizer, batch_size: int, seq_len: int,
                     **kwargs) -> Iterator[tuple]:
    """Tokenize a text file (HF tokenizer) into an LM stream."""
    with open(path) as fp:
        text = fp.read()
    ids = np.asarray(tokenizer(text)["input_ids"], dtype=np.int32)
    return array_token_stream(ids, batch_size, seq_len, **kwargs)


def per_process_batch(global_batch: np.ndarray, sharding):
    """Multi-host: build a global jax.Array from this process's slice."""
    import jax

    if jax.process_count() == 1:
        return jax.device_put(global_batch, sharding)
    return jax.make_array_from_process_local_data(sharding, global_batch)


# -- native token-shard loader (native/data_loader.cpp) ----------------------

def _native_lib_path() -> str:
    import os

    env = os.environ.get("MLT_DATA_LOADER_LIB")
    if env:
        return env
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(here, "native", "libmlt_data.so")


class TokenShardLoader:
    """Native prefetching loader over flat token-shard files.

    Replaces the reference's DataLoader worker processes
    (mlrun/frameworks/pytorch/mlrun_interface.py:903) with
    native/data_loader.cpp: shards are mmapped read-only, worker threads
    cut seeded-shuffled (seq+1)-token windows and stage whole batches in
    a bounded ring buffer — the Python side does ONE memcpy per batch and
    the TPU step never waits on IO. Yields (tokens, targets) int32 arrays
    like synthetic_token_stream.

    Shard format: little-endian flat token files, int32 (dtype="int32")
    or uint16 (dtype="uint16") — the usual pretokenized .bin layout.
    """

    def __init__(self, paths, batch_size: int, seq_len: int,
                 dtype: str = "int32", seed: int = 0, workers: int = 2,
                 queue_depth: int = 4, lib_path: str = ""):
        import ctypes
        import os

        if isinstance(paths, (str, bytes)):
            paths = [paths]
        self.paths = [str(p) for p in paths]
        for p in self.paths:
            if not os.path.isfile(p):
                raise FileNotFoundError(p)
        self.batch_size = batch_size
        self.seq_len = seq_len
        code = {"int32": 4, "uint16": 2}.get(dtype)
        if code is None:
            raise ValueError(f"dtype must be int32|uint16, got {dtype}")

        lib_path = lib_path or _native_lib_path()
        self._lib = ctypes.CDLL(lib_path)
        self._lib.mlt_loader_open.restype = ctypes.c_uint64
        self._lib.mlt_loader_open.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_uint32,
            ctypes.c_int, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.c_uint32, ctypes.c_uint32]
        self._lib.mlt_loader_next.restype = ctypes.c_int
        self._lib.mlt_loader_next.argtypes = [
            ctypes.c_uint64, ctypes.POINTER(ctypes.c_int32)]
        self._lib.mlt_loader_total_tokens.restype = ctypes.c_uint64
        self._lib.mlt_loader_epoch.restype = ctypes.c_uint64
        self._lib.mlt_loader_close.argtypes = [ctypes.c_uint64]
        try:
            self._lib.mlt_loader_stats.restype = ctypes.c_int
            self._lib.mlt_loader_stats.argtypes = [
                ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64)]
            self._has_stats = True
        except AttributeError:
            # an older libmlt_data.so without the stats export still loads
            self._has_stats = False

        arr = (ctypes.c_char_p * len(self.paths))(
            *[p.encode() for p in self.paths])
        self._handle = self._lib.mlt_loader_open(
            arr, len(self.paths), code, batch_size, seq_len, seed,
            workers, queue_depth)
        if not self._handle:
            raise RuntimeError(
                f"mlt_loader_open failed for {self.paths} (empty shards, "
                f"bad dtype, or shards shorter than seq_len+1)")
        self._buf = np.empty((batch_size, seq_len + 1), np.int32)
        self._obs_name = (f"{os.path.basename(self.paths[0])}"
                          f"@{self._handle}")
        self._metrics_registered = False
        self._register_metrics()

    @property
    def total_tokens(self) -> int:
        return int(self._lib.mlt_loader_total_tokens(self._handle))

    @property
    def epoch(self) -> int:
        return int(self._lib.mlt_loader_epoch(self._handle))

    def stats(self) -> dict:
        """Engine-style telemetry snapshot: ring occupancy + wait
        counters from the native side. ``consumer_waits`` climbing while
        ``ring_occupancy`` sits at 0 is the input-bound signature; the
        same keys surface on ``/metrics`` via the registry collector."""
        import ctypes

        out = {"queue_depth": 0, "ring_occupancy": 0, "batches": 0,
               "consumer_waits": 0, "producer_waits": 0}
        if self._handle and self._has_stats:
            raw = (ctypes.c_uint64 * 5)()
            if self._lib.mlt_loader_stats(self._handle, raw):
                out.update(ring_occupancy=int(raw[0]),
                           queue_depth=int(raw[1]), batches=int(raw[2]),
                           consumer_waits=int(raw[3]),
                           producer_waits=int(raw[4]))
        out["epochs"] = int(self.epoch) if self._handle else 0
        return out

    # cumulative stats() keys mirrored as counter series at scrape time
    _COUNTER_STATS = ("batches", "consumer_waits", "producer_waits",
                      "epochs")

    def _register_metrics(self):
        """Expose the ring on the process registry the way the LLM
        engines do: a weakly-bound scrape-time collector that retires
        itself (and removes its series) once the loader is closed or
        collected."""
        if self._metrics_registered:
            return
        import weakref

        try:
            from ..obs import (
                REGISTRY,
                TRAIN_LOADER_EVENTS,
                TRAIN_LOADER_OCCUPANCY,
            )
        except Exception:  # noqa: BLE001 - telemetry must never block IO
            return
        ref = weakref.ref(self)
        name = self._obs_name
        counter_stats = self._COUNTER_STATS

        def remove_series():
            TRAIN_LOADER_OCCUPANCY.remove(loader=name)
            for key in counter_stats:
                TRAIN_LOADER_EVENTS.remove(loader=name, event=key)

        def collect():
            loader = ref()
            if loader is None or not loader._handle:
                remove_series()
                return False
            stats = loader.stats()
            TRAIN_LOADER_OCCUPANCY.set(stats["ring_occupancy"], loader=name)
            for key in counter_stats:
                TRAIN_LOADER_EVENTS.set_total(stats[key], loader=name,
                                              event=key)
            return None

        REGISTRY.add_collector(collect)
        self._metrics_registered = True

    def __iter__(self):
        return self

    def __next__(self) -> tuple:
        import ctypes

        ok = self._lib.mlt_loader_next(
            self._handle,
            self._buf.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if not ok:
            raise StopIteration
        tokens = self._buf[:, :-1].copy()
        targets = self._buf[:, 1:].copy()
        return tokens, targets

    def close(self):
        if getattr(self, "_handle", 0):
            self._lib.mlt_loader_close(self._handle)
            self._handle = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass


def device_prefetch(stream, sharding=None, depth: int = 2):
    """Wrap a (tokens, targets) host iterator with device-side prefetch:
    keeps ``depth`` batches already transferred (optionally with a
    NamedSharding) so the train step never waits on host->HBM copies.

    Synchronous variant: transfers are *issued* ahead but the host batch
    for slot k+depth is still pulled on the consumer thread between
    steps. ``DevicePrefetchIterator`` moves that pull (and the transfer
    issue) onto a background thread — ``Trainer.fit`` uses it."""
    import collections

    import jax

    queue = collections.deque()

    def put(item):
        tokens, targets = item
        if sharding is not None:
            return (jax.device_put(tokens, sharding),
                    jax.device_put(targets, sharding))
        return jax.device_put(tokens), jax.device_put(targets)

    iterator = iter(stream)
    try:
        for _ in range(depth):
            queue.append(put(next(iterator)))
    except StopIteration:
        pass
    while queue:
        out = queue.popleft()
        try:
            queue.append(put(next(iterator)))
        except StopIteration:
            pass
        yield out


class _PrefetchError:
    """Queue envelope carrying a producer-side exception to the consumer
    at the exact batch position it occurred."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


_PREFETCH_END = object()  # sentinel: upstream iterator exhausted


class DevicePrefetchIterator:
    """Bounded background device-prefetch stage for the training loop.

    A producer thread pulls host batches from ``stream`` (a generator or
    :class:`TokenShardLoader`), issues the host->device transfer — via
    ``per_process_batch`` when a sharding is given, which routes through
    ``jax.make_array_from_process_local_data`` under multi-host SPMD —
    and stages the device arrays in a queue of ``depth`` entries. The
    consuming step therefore overlaps its compute with both the NEXT
    batch's host production (tokenization/IO) and its H2D copy, instead
    of paying them serially between dispatches (arXiv:2011.03641 §4:
    input staging, not FLOPs, sets the pod-scale throughput ceiling).

    Contracts:

    - **Order-preserving and deterministic** — one producer thread pulls
      sequentially; consumers see exactly the upstream batch sequence.
    - **Error-transparent** — a producer-side exception (bad shard, chaos
      injection at ``train.prefetch``) surfaces on the consumer at the
      position of the failing batch, not as a hang.
    - **Deadlock-free shutdown** — ``close()`` drains the queue while the
      producer may be blocked on a full one, so a preemption exit
      (``PreemptionGuard.agreed()`` before ``next()``) never waits on a
      prefetched batch nobody will consume. Prefetched-but-unconsumed
      batches are simply dropped.

    Telemetry: ``stats()`` reports wait seconds / staged bytes, and the
    process registry gets ``mlt_train_input_wait_seconds`` +
    ``mlt_train_h2d_bytes_total`` increments as they accrue.
    """

    def __init__(self, stream, sharding=None, depth: int = 2):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._iterator = iter(stream)
        self._sharding = sharding
        self.depth = depth
        self._queue: queue_mod.Queue = queue_mod.Queue(maxsize=depth)
        self._closed = threading.Event()
        self._exhausted = False
        # telemetry (producer-written fields only touched by the thread)
        self._wait_seconds = 0.0
        self._bytes_staged = 0
        self._batches_staged = 0
        self._batches_consumed = 0
        self._thread = threading.Thread(
            target=self._produce, daemon=True, name="mlt-device-prefetch")
        self._thread.start()

    # -- producer ------------------------------------------------------------
    def _place(self, item):
        import jax

        tokens, targets = item
        self._bytes_staged += (getattr(tokens, "nbytes", 0)
                               + getattr(targets, "nbytes", 0))
        if self._sharding is not None:
            return (per_process_batch(tokens, self._sharding),
                    per_process_batch(targets, self._sharding))
        return jax.device_put(tokens), jax.device_put(targets)

    def _put(self, item) -> bool:
        """Enqueue with close-awareness: never blocks indefinitely on a
        full queue (the consumer may have exited at a preemption point)."""
        while not self._closed.is_set():
            try:
                self._queue.put(item, timeout=0.05)
                return True
            except queue_mod.Full:
                continue
        return False

    def _produce(self):
        index = 0
        while not self._closed.is_set():
            try:
                fire(FaultPoints.train_prefetch, batch_index=index)
                batch = next(self._iterator)
            except StopIteration:
                self._put(_PREFETCH_END)
                return
            except BaseException as exc:  # noqa: BLE001 - delivered to
                # the consumer at this batch's position
                self._put(_PrefetchError(exc))
                return
            try:
                placed = self._place(batch)
            except BaseException as exc:  # noqa: BLE001
                self._put(_PrefetchError(exc))
                return
            if not self._put(placed):
                return
            self._batches_staged += 1
            index += 1

    # -- consumer ------------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._exhausted or self._closed.is_set():
            raise StopIteration
        started = time.perf_counter()
        while True:
            try:
                item = self._queue.get(timeout=0.05)
                break
            except queue_mod.Empty:
                if self._closed.is_set() or not self._thread.is_alive():
                    # a dead producer always leaves a sentinel/error
                    # behind — an empty queue here means close() raced us
                    if self._queue.empty():
                        raise StopIteration from None
        waited = time.perf_counter() - started
        self._wait_seconds += waited
        if item is _PREFETCH_END:
            self._exhausted = True
            raise StopIteration
        if isinstance(item, _PrefetchError):
            self._exhausted = True
            raise item.exc
        self._batches_consumed += 1
        return item

    # -- lifecycle -----------------------------------------------------------
    def close(self, timeout: float = 5.0):
        """Stop the producer and drop staged batches. Safe to call from
        the preemption/early-stop path with the queue full — the drain
        below is what unblocks a producer mid-``put``."""
        if self._closed.is_set():
            return
        self._closed.set()

        def _drain_queue():
            while True:
                try:
                    self._queue.get_nowait()
                except queue_mod.Empty:
                    return

        _drain_queue()
        self._thread.join(timeout)
        # a producer that was blocked in put() may have slipped one item
        # into the just-drained queue before observing the closed flag —
        # drain again after the join so no staged batch stays referenced
        _drain_queue()
        # the upstream stream is NOT closed: the caller owns its
        # lifecycle (a TokenShardLoader may feed a later fit/resume)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def stats(self) -> dict:
        return {
            "depth": self.depth,
            "queued": self._queue.qsize(),
            "batches_staged": self._batches_staged,
            "batches_consumed": self._batches_consumed,
            "input_wait_seconds": self._wait_seconds,
            "h2d_bytes": self._bytes_staged,
        }
