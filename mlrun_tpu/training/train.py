"""Distributed train step + trainer loop.

This is the TPU-native replacement for the reference's Horovod training path
(mlrun/frameworks/pytorch/mlrun_interface.py:106 train loop, :561-566 hvd
init, :849 metric allreduce, :903 DistributedSampler): no ranks, no
allreduce calls — the step function is jit-compiled with NamedShardings
derived from parallel/sharding.py rules and XLA emits all ICI/DCN
collectives. Data "sharding" replaces DistributedSampler: the global batch
array is placed with a (data×fsdp)-sharded NamedSharding.
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..models import llama as llama_mod
from ..models.llama import LlamaConfig
from ..parallel.mesh import make_mesh
from ..parallel.sharding import (
    DEFAULT_RULES,
    batch_sharding,
    tree_shardings,
)
from ..utils import logger
from .mfu import ThroughputTracker, chip_peak_flops, mfu


@dataclasses.dataclass
class TrainConfig:
    learning_rate: float = 2e-4
    warmup_steps: int = 10
    total_steps: int = 100
    weight_decay: float = 0.0
    grad_clip: float = 1.0
    grad_accum: int = 1
    b1: float = 0.9
    b2: float = 0.95
    lora_rank: int = 0          # 0 = full fine-tune; >0 = LoRA
    lora_alpha: float = 32.0
    mesh_shape: dict | None = None
    seq_axis: str | None = None  # set to e.g. "seq" for context parallelism
    # chunked cross-entropy: avoids the [B,S,vocab] logits allocation
    # (0 = full logits). 512 is a good default for 128k vocab.
    loss_chunk: int = 512
    # long-context: "ring" | "ulysses" shards the SEQUENCE over seq_axis
    # inside the step (models/llama_cp). Composes with LoRA and grad_accum;
    # mesh may be seq-only or data x seq (fsdp/tensor can't combine with
    # CP under jax 0.9 — see make_train_step).
    context_parallel: str | None = None
    # pipeline parallelism (parallel/pipeline.py): >1 splits the layer
    # stack into that many GPipe stages over a 'pipe' mesh axis; composes
    # with a 'data' axis (D independent pipelines) and grad_accum.
    pipeline_stages: int = 0
    # microbatches per pipeline step (0 = pipeline_stages; more shrinks
    # the fill/drain bubble at the cost of smaller per-stage matmuls)
    pipeline_microbatches: int = 0
    # expert parallelism (models/moe.py): >0 swaps the dense MLP for that
    # many routed experts (MoEConfig) sharded over an 'expert' mesh axis
    # when present; composes with data/fsdp/tensor axes.
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    # attention kernel for the train step (None = keep the model config's
    # own setting): "mlt_flash" runs our pallas flash kernel (custom-vjp
    # blockwise backward; interpret mode off-TPU so CPU runs exercise the
    # real kernel path), "flash" the tuned library kernel, "reference"
    # plain XLA — see ops/attention.attention and
    # docs/training_performance.md "Flash attention in the step"
    attention_impl: str | None = None


class TrainState:
    """Minimal train state pytree (params/lora/opt_state/step)."""

    def __init__(self, params, opt_state, step, lora=None):
        self.params = params
        self.opt_state = opt_state
        self.step = step
        self.lora = lora

    def tree_flatten(self):
        return (self.params, self.opt_state, self.step, self.lora), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], children[2], children[3])


jax.tree_util.register_pytree_node(
    TrainState, TrainState.tree_flatten, TrainState.tree_unflatten)


def accumulate_grads(compute_grads: Callable, target_tree, tokens, targets,
                     accum: int):
    """Gradient accumulation shared by the plain and context-parallel
    steps: split the batch into ``accum`` micro-batches, scan
    ``compute_grads(tokens, targets) -> (grads, metrics)``, average the
    gradients, and report the last micro-batch's metrics."""
    b = tokens.shape[0]
    if b < accum or b % accum:
        raise ValueError(
            f"grad_accum={accum} needs a batch divisible by it "
            f"(got batch={b}); a non-multiple would silently drop samples "
            "and an empty micro-batch yields NaN loss")
    micro = b // accum
    tok = tokens.reshape(accum, micro, -1)
    tgt = targets.reshape(accum, micro, -1)

    def body(grads_sum, xs):
        t, g = xs
        grads, metrics = compute_grads(t, g)
        return jax.tree_util.tree_map(
            lambda a, b_: a + b_, grads_sum, grads), metrics

    zero = jax.tree_util.tree_map(jnp.zeros_like, target_tree)
    grads, metrics_stack = jax.lax.scan(body, zero, (tok, tgt))
    grads = jax.tree_util.tree_map(lambda g: g / accum, grads)
    metrics = jax.tree_util.tree_map(lambda m: m[-1], metrics_stack)
    return grads, metrics


def resolve_model_config(model_config, train_config: TrainConfig):
    """Apply TrainConfig model-shaping options: ``moe_experts`` converts a
    dense LlamaConfig into an MoEConfig with the same backbone dims, so a
    user reaches expert parallelism through TrainConfig exactly like
    ``context_parallel``/``pipeline_stages`` (SURVEY §2.4);
    ``attention_impl`` overrides the model's attention dispatch for the
    whole step (flash kernels in the training hot path)."""
    from ..models.moe import MoEConfig

    if train_config.moe_experts and not isinstance(model_config, MoEConfig):
        model_config = MoEConfig(
            **dataclasses.asdict(model_config),
            n_experts=train_config.moe_experts,
            top_k=train_config.moe_top_k,
            capacity_factor=train_config.moe_capacity_factor)
    if train_config.attention_impl is not None and \
            hasattr(model_config, "attention_impl"):
        model_config = dataclasses.replace(
            model_config, attention_impl=train_config.attention_impl)
    return model_config


def _model_api(model_config):
    """(loss_fn, param_shapes, init_params, default_rules) for the
    config's model family — the dense llama path and the MoE path share
    the whole trainer below this indirection. Every loss adapter takes
    the SAME signature (config, params, tokens, targets, lora=,
    act_spec=, loss_chunk=) so the step builder has exactly one call
    site per family decision."""
    from ..models import moe as moe_mod

    if isinstance(model_config, moe_mod.MoEConfig):
        def moe_loss(config, params, tokens, targets, lora=None,
                     act_spec=None, loss_chunk=0):
            # lora is rejected up-front for MoE; act_spec only applies to
            # Explicit-mode meshes of the dense path
            return moe_mod.loss_fn(config, params, tokens, targets,
                                   loss_chunk=loss_chunk)

        return (moe_loss, moe_mod.param_shapes, moe_mod.init_params,
                moe_mod.make_moe_rules())
    return (llama_mod.loss_fn, llama_mod.param_shapes,
            llama_mod.init_params, None)


def make_optimizer(config: TrainConfig) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, config.learning_rate, config.warmup_steps,
        max(config.total_steps, config.warmup_steps + 1))
    chain = []
    if config.grad_clip:
        chain.append(optax.clip_by_global_norm(config.grad_clip))
    chain.append(optax.adamw(schedule, b1=config.b1, b2=config.b2,
                             weight_decay=config.weight_decay))
    return optax.chain(*chain)


def make_train_step(model_config: LlamaConfig, train_config: TrainConfig,
                    optimizer: optax.GradientTransformation,
                    mesh: Mesh, rules=None) -> Callable:
    """Build the jitted sharded train step: (state, tokens, targets) ->
    (state, metrics). Works for full fine-tune and LoRA (frozen base),
    dense and MoE (``moe_experts``), plain and pipelined
    (``pipeline_stages``)."""
    model_config = resolve_model_config(model_config, train_config)
    from ..models.moe import MoEConfig

    is_moe = isinstance(model_config, MoEConfig)
    is_lora = train_config.lora_rank > 0
    accum = max(1, train_config.grad_accum)

    if is_moe and is_lora:
        raise ValueError("moe_experts does not compose with lora_rank yet")
    if is_moe and train_config.context_parallel:
        raise ValueError(
            "moe_experts does not compose with context_parallel yet")

    if train_config.pipeline_stages > 1:
        return _make_pp_step(model_config, train_config, optimizer, mesh,
                             rules=rules)

    if train_config.context_parallel:
        seq_axis = train_config.seq_axis or "seq"
        if seq_axis not in mesh.axis_names:
            raise ValueError(
                f"context_parallel needs a '{seq_axis}' axis in the mesh")
        offending = [a for a in mesh.axis_names
                     if a not in (seq_axis, "data") and mesh.shape[a] > 1]
        if offending:
            # jax 0.9 XLA CHECK-crashes on backward through partial-manual
            # shard_map when an auto axis is active. The 'data' axis is
            # supported via the full-manual data x seq mode (params
            # replicated over data); fsdp/tensor cannot combine with CP
            # until the compiler bug is fixed — scale batch with
            # grad_accum instead.
            raise ValueError(
                f"context_parallel training supports seq-only or "
                f"data x seq meshes in this jax version (active axes "
                f"{offending} cannot combine with '{seq_axis}')")
        return _make_cp_step(model_config, train_config, optimizer, mesh,
                             seq_axis, rules)

    # under Auto axis types GSPMD resolves the embedding gather itself;
    # act_spec stays available for Explicit-mode meshes
    act_spec = None
    try:
        from jax.sharding import AxisType
    except ImportError:  # pre-AxisType jax: every mesh is Auto-typed
        AxisType = None

    if AxisType is not None and any(
            t == AxisType.Explicit
            for t in getattr(mesh, "axis_types", ())):
        batch_axes = tuple(a for a in ("data", "fsdp") if a in mesh.axis_names
                           and mesh.shape[a] > 1) or None
        tensor_axis = "tensor" if ("tensor" in mesh.axis_names
                                   and mesh.shape["tensor"] > 1) else None
        act_spec = NamedSharding(
            mesh,
            PartitionSpec(batch_axes, train_config.seq_axis, tensor_axis))

    family_loss, shapes_fn, _, family_rules = _model_api(model_config)

    def loss_for(params, lora, tokens, targets):
        return family_loss(model_config, params, tokens, targets,
                           lora=lora, act_spec=act_spec,
                           loss_chunk=train_config.loss_chunk)

    def compute_grads(params, lora, tokens, targets):
        if is_lora:
            def lora_loss(lora_):
                return loss_for(params, lora_, tokens, targets)

            (loss, metrics), grads = jax.value_and_grad(
                lora_loss, has_aux=True)(lora)
        else:
            def full_loss(params_):
                return loss_for(params_, lora, tokens, targets)

            (loss, metrics), grads = jax.value_and_grad(
                full_loss, has_aux=True)(params)
        return grads, metrics

    def step_fn(state: TrainState, tokens, targets):
        if accum > 1:
            grads, metrics = accumulate_grads(
                lambda t, g: compute_grads(state.params, state.lora, t, g),
                state.lora if is_lora else state.params,
                tokens, targets, accum)
        else:
            grads, metrics = compute_grads(state.params, state.lora, tokens,
                                           targets)

        target_tree = state.lora if is_lora else state.params
        updates, new_opt_state = optimizer.update(
            grads, state.opt_state, target_tree)
        new_target = optax.apply_updates(target_tree, updates)
        new_state = TrainState(
            params=state.params if is_lora else new_target,
            opt_state=new_opt_state,
            step=state.step + 1,
            lora=new_target if is_lora else state.lora,
        )
        metrics = dict(metrics)
        metrics["grad_norm"] = optax.global_norm(grads)
        return new_state, metrics

    # shardings
    rules = rules if rules is not None else (
        family_rules if family_rules is not None else DEFAULT_RULES)
    params_shapes = shapes_fn(model_config)
    param_shardings = tree_shardings(params_shapes, mesh, rules)
    data_sh = batch_sharding(mesh, train_config.seq_axis)
    replicated = NamedSharding(mesh, PartitionSpec())

    if is_lora:
        from ..models.lora import init_lora

        lora_shapes = jax.eval_shape(
            lambda: init_lora(model_config, jax.random.PRNGKey(0),
                              train_config.lora_rank,
                              train_config.lora_alpha))
        lora_shardings = tree_shardings(lora_shapes, mesh, rules)
        opt_state_shapes = jax.eval_shape(optimizer.init, lora_shapes)
        opt_state_shardings = tree_shardings(opt_state_shapes, mesh, rules)
        state_shardings = TrainState(param_shardings, opt_state_shardings,
                                     replicated, lora_shardings)
    else:
        opt_state_shapes = jax.eval_shape(optimizer.init, params_shapes)
        opt_state_shardings = tree_shardings(opt_state_shapes, mesh, rules)
        state_shardings = TrainState(param_shardings, opt_state_shardings,
                                     replicated, None)

    jitted = jax.jit(
        step_fn,
        in_shardings=(state_shardings, data_sh, data_sh),
        out_shardings=(state_shardings, replicated),
        donate_argnums=(0,),
    )
    jitted._state_shardings = state_shardings
    jitted._data_sharding = data_sh
    return jitted


def _make_cp_step(model_config, train_config, optimizer, mesh, seq_axis,
                  rules):
    """Context-parallel step adapter: wraps models/llama_cp's train step in
    the (state, tokens, targets) -> (state, metrics) contract. Supports
    full fine-tune and LoRA, with gradient accumulation."""
    from ..models.llama_cp import make_cp_train_step

    raw_step = make_cp_train_step(
        model_config, mesh, optimizer, seq_axis=seq_axis,
        attn_impl=train_config.context_parallel,
        lora_rank=train_config.lora_rank,
        lora_alpha=train_config.lora_alpha,
        grad_accum=train_config.grad_accum)

    def step_fn(state: TrainState, tokens, targets):
        params, lora, opt_state, metrics = raw_step(
            state.params, state.lora, state.opt_state, tokens, targets)
        new_state = TrainState(params, opt_state, state.step + 1, lora)
        return new_state, metrics

    batch_axes = tuple(a for a in ("data",) if a in mesh.axis_names
                       and mesh.shape[a] > 1) or None
    step_fn._data_sharding = NamedSharding(
        mesh, PartitionSpec(batch_axes, seq_axis))
    step_fn._state_shardings = None
    return step_fn


# pipelined params: the stacked-stage layer tree [P, L/P, ...] shards its
# stage dim over 'pipe'; everything else (embedding, head, opt scalars)
# replicates — the pipelined region's shard_map expects exactly this
PP_RULES: list[tuple[str, tuple]] = [
    (r".*layers.*", ("pipe",)),
    (r".*", ()),
]


def _pp_setup(model_config, train_config: TrainConfig, mesh: Mesh,
              rules=None):
    """Validate the mesh and build (batch_axis, split_fn, split param
    shapes, param shardings) for pipeline-parallel training."""
    from ..parallel.pipeline import split_layers_for_stages

    if rules is not None:
        # loud, like the lora/context_parallel compositions: the pipelined
        # region's shard_map fixes the stage sharding, so user rules would
        # be silently dropped if accepted
        raise ValueError(
            "pipeline_stages uses its own stage sharding (PP_RULES); "
            "custom sharding rules are not supported with the pipeline "
            "trainer")
    stages = train_config.pipeline_stages
    if "pipe" not in mesh.axis_names or mesh.shape["pipe"] != stages:
        raise ValueError(
            f"pipeline_stages={stages} needs a 'pipe' mesh axis of that "
            f"size (mesh: {dict(mesh.shape)})")
    if train_config.lora_rank:
        raise ValueError(
            "pipeline_stages does not compose with lora_rank yet")
    if train_config.context_parallel or train_config.moe_experts:
        raise ValueError(
            "pipeline_stages composes with data parallelism only (not "
            "context_parallel/moe_experts)")
    offending = [a for a in mesh.axis_names
                 if a not in ("pipe", "data") and mesh.shape[a] > 1]
    if offending:
        raise ValueError(
            f"pipeline training runs on pipe (+ optional data) mesh axes; "
            f"active axes {offending} are not supported inside the "
            "pipelined region")
    batch_axis = "data" if ("data" in mesh.axis_names
                            and mesh.shape["data"] > 1) else None

    def split(params):
        out = dict(params)
        out["layers"] = split_layers_for_stages(params["layers"], stages)
        return out

    shapes = jax.eval_shape(split, llama_mod.param_shapes(model_config))
    shardings = tree_shardings(shapes, mesh, PP_RULES)
    return batch_axis, split, shapes, shardings


def _make_pp_step(model_config, train_config: TrainConfig, optimizer,
                  mesh: Mesh, rules=None):
    """GPipe train step: layers pipelined over the 'pipe' axis via
    parallel/pipeline.py, composing with a 'data' axis (independent
    pipelines per data shard) and with grad_accum."""
    from ..parallel.pipeline import pipeline_loss_fn

    batch_axis, _, shapes, param_shardings = _pp_setup(
        model_config, train_config, mesh, rules=rules)
    microbatches = (train_config.pipeline_microbatches
                    or train_config.pipeline_stages)
    loss = pipeline_loss_fn(model_config, mesh, microbatches, "pipe",
                            batch_axis=batch_axis)
    accum = max(1, train_config.grad_accum)

    def compute_grads(params, tokens, targets):
        (_, metrics), grads = jax.value_and_grad(
            loss, has_aux=True)(params, tokens, targets)
        return grads, metrics

    def step_fn(state: TrainState, tokens, targets):
        if accum > 1:
            grads, metrics = accumulate_grads(
                lambda t, g: compute_grads(state.params, t, g),
                state.params, tokens, targets, accum)
        else:
            grads, metrics = compute_grads(state.params, tokens, targets)
        updates, new_opt_state = optimizer.update(
            grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = dict(metrics)
        metrics["grad_norm"] = optax.global_norm(grads)
        return TrainState(new_params, new_opt_state, state.step + 1,
                          None), metrics

    replicated = NamedSharding(mesh, PartitionSpec())
    opt_shardings = tree_shardings(
        jax.eval_shape(optimizer.init, shapes), mesh, PP_RULES)
    state_shardings = TrainState(param_shardings, opt_shardings,
                                 replicated, None)
    data_sh = NamedSharding(mesh, PartitionSpec(batch_axis))
    jitted = jax.jit(
        step_fn,
        in_shardings=(state_shardings, data_sh, data_sh),
        out_shardings=(state_shardings, replicated),
        donate_argnums=(0,),
    )
    jitted._state_shardings = state_shardings
    jitted._data_sharding = data_sh
    return jitted


def init_train_state(model_config: LlamaConfig, train_config: TrainConfig,
                     optimizer, mesh: Mesh, key: jax.Array,
                     rules=None) -> TrainState:
    """Initialize params directly sharded on the mesh (jit with
    out_shardings so no host-memory staging of the full model)."""
    model_config = resolve_model_config(model_config, train_config)
    if train_config.pipeline_stages > 1:
        _, split, shapes, param_shardings = _pp_setup(
            model_config, train_config, mesh, rules=rules)
        params = jax.jit(
            lambda k: split(llama_mod.init_params(model_config, k)),
            out_shardings=param_shardings)(key)
        opt_state = jax.jit(
            optimizer.init,
            out_shardings=tree_shardings(
                jax.eval_shape(optimizer.init, shapes), mesh, PP_RULES),
        )(params)
        step = jax.device_put(jnp.zeros((), jnp.int32),
                              NamedSharding(mesh, PartitionSpec()))
        return TrainState(params, opt_state, step, None)

    _, shapes_fn, init_fn, family_rules = _model_api(model_config)
    rules = rules if rules is not None else (
        family_rules if family_rules is not None else DEFAULT_RULES)
    is_lora = train_config.lora_rank > 0
    params_shapes = shapes_fn(model_config)
    param_shardings = tree_shardings(params_shapes, mesh, rules)

    init_params_sharded = jax.jit(
        functools.partial(init_fn, model_config),
        out_shardings=param_shardings)
    params = init_params_sharded(key)

    if is_lora:
        from ..models.lora import init_lora

        lora_shapes = jax.eval_shape(
            lambda: init_lora(model_config, key, train_config.lora_rank,
                              train_config.lora_alpha))
        lora_shardings = tree_shardings(lora_shapes, mesh, rules)
        lora = jax.jit(
            functools.partial(init_lora, model_config,
                              rank=train_config.lora_rank,
                              alpha=train_config.lora_alpha),
            out_shardings=lora_shardings)(key)
        opt_state = jax.jit(
            optimizer.init,
            out_shardings=tree_shardings(
                jax.eval_shape(optimizer.init, lora_shapes), mesh, rules),
        )(lora)
    else:
        lora = None
        opt_state = jax.jit(
            optimizer.init,
            out_shardings=tree_shardings(
                jax.eval_shape(optimizer.init, params_shapes), mesh, rules),
        )(params)
    step = jax.device_put(jnp.zeros((), jnp.int32),
                          NamedSharding(mesh, PartitionSpec()))
    return TrainState(params, opt_state, step, lora)


def _all_hosts_agree(flag: bool) -> bool:
    """Max-reduce a local boolean across hosts (PreemptionGuard.agreed's
    construction): under multi-host JAX every host must take the same
    stop decision in the same step, or the hosts still stepping deadlock
    in the slice collectives. Single-process: the flag itself."""
    if jax.process_count() <= 1:
        return flag
    import numpy as np
    from jax.experimental import multihost_utils

    flags = multihost_utils.process_allgather(
        np.asarray(flag, np.int32))
    return bool(np.max(flags))


# distinct on-demand-profiler tick source per trainer instance: two
# concurrent fit loops must not jointly drain one steps-bound capture
# (utils/profiler.tick counts down only the claiming source's ticks)
_TRAINER_SEQUENCE = iter(range(1, 1 << 30))


class Trainer:
    """High-level trainer used by the jax framework adapter and bench."""

    def __init__(self, model_config: LlamaConfig,
                 train_config: TrainConfig | None = None,
                 mesh: Mesh | None = None, rules=None):
        # wire the persistent XLA compilation cache BEFORE anything can
        # trigger a jit compile: a resubmitted JobSet carrying
        # COMPILE_CACHE_ENV then loads step-fn executables from disk
        # instead of recompiling (utils/compile_cache.py); no-op when
        # mlconf.training.compile_cache_dir is unset
        from ..utils import compile_cache

        compile_cache.configure_from_mlconf()
        self.train_config = train_config or TrainConfig()
        self.model_config = resolve_model_config(model_config,
                                                 self.train_config)
        self.mesh = mesh or make_mesh(self.train_config.mesh_shape)
        self.rules = rules
        self.optimizer = make_optimizer(self.train_config)
        self.step_fn = make_train_step(
            self.model_config, self.train_config, self.optimizer,
            self.mesh, rules)
        self.state: Optional[TrainState] = None
        self._metrics_history: list[dict] = []
        # warmup() products: wall seconds of the last step-fn compile and
        # the AOT executable train_step dispatches through when shapes
        # match (no in-process recompile even without a persistent cache)
        self.compile_seconds: Optional[float] = None
        self._compiled = None
        self._warmed_shape: Optional[tuple] = None
        # goodput accounting (docs/observability.md "Goodput & badput"):
        # fit() builds a fresh per-run ledger here; after fit it holds
        # the final attribution (bench/debug read .summary())
        self.goodput = None
        self._compile_attributed = False
        self._profiler_source = f"trainer-{next(_TRAINER_SEQUENCE)}"
        # device HBM + host RSS exposition while this trainer lives
        # (mlt_device_mem_bytes / mlt_host_rss_bytes, scrape-time)
        from ..obs import register_memory_collector

        register_memory_collector(self)

    def init(self, seed: int = 0) -> TrainState:
        self.state = init_train_state(
            self.model_config, self.train_config, self.optimizer, self.mesh,
            jax.random.PRNGKey(seed), self.rules)
        return self.state

    def warmup(self, batch_size: int, seq_len: int) -> dict:
        """AOT-lower/compile the step function for ``(batch_size,
        seq_len)`` int32 batches before the loop starts.

        Records the compile wall time (``compile_seconds``, also the
        ``mlt_train_compile_seconds`` gauge) and keeps the compiled
        executable so matching-shape ``train_step`` calls dispatch
        through it directly. With ``mlconf.training.compile_cache_dir``
        set, the compile also lands in the persistent cache, so the NEXT
        process — a preemption-resume resubmit, a second A-B bench run —
        warms up in loader-time instead of compile-time. Step functions
        without an AOT path (context-parallel wrapper) skip gracefully.
        """
        assert self.state is not None, "call init() first"
        from ..obs import TRAIN_COMPILE_SECONDS
        from ..utils import compile_cache

        cache_dir = compile_cache.configure_from_mlconf()
        if not hasattr(self.step_fn, "lower"):
            logger.warning("warmup skipped: step function has no AOT "
                           "lowering path", step_fn=type(self.step_fn))
            return {"skipped": True}
        spec = jax.ShapeDtypeStruct((batch_size, seq_len), jnp.int32)
        started = time.perf_counter()
        self._compiled = self.step_fn.lower(self.state, spec, spec).compile()
        elapsed = time.perf_counter() - started
        self._warmed_shape = (batch_size, seq_len)
        self.compile_seconds = elapsed
        TRAIN_COMPILE_SECONDS.set(elapsed)
        logger.info("train step compiled", batch=batch_size, seq=seq_len,
                    compile_s=round(elapsed, 3),
                    cache_dir=cache_dir or "(off)")
        return {"compile_seconds": elapsed, "cache_dir": cache_dir,
                "batch_size": batch_size, "seq_len": seq_len}

    def shard_batch(self, tokens, targets):
        sharding = self.step_fn._data_sharding
        return (jax.device_put(tokens, sharding),
                jax.device_put(targets, sharding))

    def train_step(self, tokens, targets) -> dict:
        tokens, targets = self.shard_batch(tokens, targets)
        return self._dispatch(tokens, targets)

    def _dispatch(self, tokens, targets) -> dict:
        """Dispatch one step on already-sharded batches (fit() times the
        h2d placement and the dispatch as separate goodput phases)."""
        fn = self.step_fn
        if (self._compiled is not None
                and tokens.shape == self._warmed_shape
                and tokens.dtype == jnp.int32):
            fn = self._compiled
        self.state, metrics = fn(self.state, tokens, targets)
        return metrics

    def _maybe_resume(self, checkpoint_manager, context) -> bool:
        """Honor the service's checkpoint-resume directive
        (MLT_RESUME_FROM_CHECKPOINT / MLT_RESUME_STEP, written into a
        resubmitted JobSet by runtime_handlers.TpuJobHandler): restore the
        train state before the first step so the rescheduled slice resumes
        rather than restarting. No directive, no manager, or an
        already-advanced state (explicit restore) → no-op. Returns
        whether a directive was honored — a resumed run's first-dispatch
        warmup is ``re_warm`` badput (elasticity tax), not a cold
        ``compile`` (obs/goodput.py)."""
        from ..obs import flight_record
        from .checkpoint import resume_directive

        directive = resume_directive()
        if directive is None or checkpoint_manager is None:
            # the common no-directive entry must not force a device sync:
            # int(state.step) blocks the host on everything in flight,
            # and fit() may be entered with steps still dispatching
            return False
        if int(self.state.step) != 0:
            # a directive exists — only now is the sync warranted, to let
            # an explicit prior restore win over the env contract
            return True
        path, step = directive
        try:
            self.state = checkpoint_manager.restore(self.state, step=step)
        except Exception as exc:  # noqa: BLE001 - a missing/corrupt
            # checkpoint must not turn a resumable run into a crash loop;
            # training from step 0 is the correct degraded behavior
            logger.warning("checkpoint resume failed — starting fresh",
                           path=path, step=step, error=str(exc))
            return True
        logger.info("resumed from checkpoint", path=path,
                    step=int(self.state.step))
        flight_record("train.resume", path=str(path),
                      step=int(self.state.step))
        if context is not None and hasattr(context, "log_result"):
            context.log_result("resumed_from_step", int(self.state.step))
        return True

    def reshard(self, devices, checkpoint_manager=None,
                num_slices: int | None = None) -> dict:
        """Rebuild the mesh + step function over ``devices`` and move the
        train state onto it — the elastic slice-loss/grow-back core
        (docs/fault_tolerance.md "Elastic training"). The logical mesh
        shape is refit by rescaling one axis (``parallel.mesh.refit_shape``
        — conventionally the DCN/data axis that spanned the lost slice).

        State transfer has two modes: with a checkpoint available the
        state is RESTORED from it under the new shardings — the only
        honest source after a slice death, since on real hardware the
        dead slice's shards are gone (``CheckpointManager.restore`` is
        sharding-agnostic, so the cross-world-size restore is exact).
        Without one (grow-back, where the survivors hold everything; or
        a simulated shrink that never checkpointed) the LIVE state is
        resharded in place via ``device_put`` — no step rewind. Returns
        the decision record the flight-recorder chain carries."""
        from ..parallel.mesh import _detect_num_slices, make_mesh, refit_shape

        assert self.state is not None, "call init() first"
        devices = list(devices)
        old_world = int(self.mesh.devices.size)
        new_shape = refit_shape(dict(self.mesh.shape), len(devices))
        # slice count for the NEW mesh: the caller (ElasticGuard via fit)
        # knows how many slices survive; detection — and especially the
        # global MLT_NUM_SLICES override — describes the FULL device set
        # and must not be trusted for a survivor subset (it would fail
        # the refit shape's DCN divisibility check mid-recovery)
        num_slices = int(num_slices or _detect_num_slices(devices))
        if next(iter(new_shape.values())) % max(1, num_slices):
            num_slices = 1
        started = time.perf_counter()
        mesh = make_mesh(new_shape, devices=devices, num_slices=num_slices)
        step_fn = make_train_step(self.model_config, self.train_config,
                                  self.optimizer, mesh, self.rules)
        shardings = getattr(step_fn, "_state_shardings", None)
        if shardings is None:
            raise ValueError(
                "elastic resharding needs a step function that exposes "
                "its state shardings (the context-parallel wrapper does "
                "not)")
        latest = checkpoint_manager.latest_step() \
            if checkpoint_manager is not None else None
        if latest is not None:
            abstract = jax.tree_util.tree_map(
                lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                                  sharding=s),
                self.state, shardings)
            state = checkpoint_manager.restore(abstract, step=latest)
            decision = "restore_checkpoint"
        else:
            state = jax.device_put(self.state, shardings)
            decision = "carry_live_state"
        # swap atomically only once the transfer succeeded — a failed
        # restore leaves the trainer on its old (still valid) world
        self.mesh = mesh
        self.step_fn = step_fn
        self.state = state
        self._compiled = None        # the AOT executable binds the OLD mesh
        self._warmed_shape = None
        elapsed = time.perf_counter() - started
        info = {"world_from": old_world,
                "world_to": int(mesh.devices.size),
                "decision": decision,
                "restored_step": int(self.state.step),
                "reshard_s": elapsed}
        logger.info("resharded train state", **{
            k: round(v, 3) if isinstance(v, float) else v
            for k, v in info.items()})
        return info

    def fit(self, data_iter, steps: int, context=None,
            log_every: int = 10, callbacks: list | None = None,
            checkpoint_manager=None, preemption_guard=None,
            elastic_guard=None,
            epoch_steps: int = 0, prefetch: int | None = None,
            defer_metrics: bool | None = None) -> dict:
        """Run the training loop; logs metrics to the run context
        rank-0-only. With ``preemption_guard`` + ``checkpoint_manager``, a
        SIGTERM (TPU slice eviction) triggers one final synchronous
        checkpoint and a clean early return with ``preempted: True`` — the
        JobSet restart then resumes from that step (training/preemption.py).

        With ``elastic_guard`` (:class:`~.elastic.ElasticGuard`), a
        multi-slice run survives losing a slice mid-fit: the guard is
        polled once per step, a ``fail`` event reshards the run onto the
        survivors (:meth:`reshard` — mesh refit, sharding-agnostic
        checkpoint restore, step-fn rebuild) and training continues at
        reduced world size, taxed as ``degraded`` badput until a
        ``join`` event grows it back. The full
        detect→reshard→continue→grow chain lands in the flight recorder
        (docs/fault_tolerance.md "Elastic training").

        The hot loop is pipelined (docs/training_performance.md):
        ``prefetch`` (default ``mlconf.training.prefetch``) wraps
        ``data_iter`` in a :class:`~.data.DevicePrefetchIterator` so host
        batch production and the H2D transfer overlap the previous step's
        compute; ``defer_metrics`` (default
        ``mlconf.training.defer_metrics``) stages log-point metric reads
        as async device->host copies drained one log interval later —
        the host never stalls dispatch on ``float(loss)``. Callbacks are
        handed same-step host values at log points, so their presence
        forces the synchronous read path. ``tokens_per_sec``/``mfu`` are
        steady-state (post compile/ramp window); the first-step compile
        is reported separately as ``compile_seconds``.

        ``callbacks`` take structured ``frameworks._common.Callback``
        objects (on_train_begin / on_step_end / on_epoch_end /
        on_train_end; returning False from a step/epoch hook stops
        training gracefully with ``stopped_early: True``) as well as the
        legacy bare ``callback(step, metrics, trainer)`` callables.
        ``epoch_steps`` groups steps into epochs for the epoch hooks
        (0 = no epoch structure)."""
        from ..config import mlconf
        from ..frameworks._common.callbacks import CallbackList
        from ..obs import (
            TRAIN_COMPILE_SECONDS,
            TRAIN_H2D_BYTES,
            TRAIN_INPUT_WAIT,
            TRAIN_STEP_TIME,
            GoodputLedger,
            flight_record,
            get_flight_recorder,
        )
        from ..utils import profiler as profiler_mod
        from .data import DevicePrefetchIterator

        assert self.state is not None, "call init() first"
        # goodput ledger: every wall-second of this fit lands in the
        # 'step' goodput phase or a typed badput bucket, and the phase
        # transitions below make the attribution sum to wall time by
        # construction (docs/observability.md "Goodput & badput")
        run_uid = str(getattr(context, "uid", "") or "") \
            if context is not None else ""
        ledger = self.goodput = GoodputLedger(run=run_uid)
        with ledger.phase("checkpoint"):
            resumed = self._maybe_resume(checkpoint_manager, context)
        if self.compile_seconds is not None and not self._compile_attributed:
            # warmup() compiled before this fit's wall window opened —
            # attribute it out-of-band, once per trainer
            self._compile_attributed = True
            ledger.attribute("re_warm" if resumed else "compile",
                             self.compile_seconds)
        flight_record("train.fit_begin", run=run_uid, steps=steps,
                      resumed=resumed)
        hooks = CallbackList(callbacks, context=context, trainer=self)

        train_cfg = mlconf.training
        depth = (int(train_cfg.get("prefetch", 0) or 0)
                 if prefetch is None else int(prefetch))
        prefetcher = (data_iter
                      if isinstance(data_iter, DevicePrefetchIterator)
                      else None)
        owned = None
        if depth > 0 and prefetcher is None:
            data_iter = owned = prefetcher = DevicePrefetchIterator(
                data_iter,
                sharding=getattr(self.step_fn, "_data_sharding", None),
                depth=depth)
        defer = (bool(train_cfg.get("defer_metrics", True))
                 if defer_metrics is None else bool(defer_metrics))
        defer = defer and not hooks.callbacks

        tracker = ThroughputTracker(
            int(train_cfg.get("warmup_steps_excluded", 1) or 0))
        input_wait = 0.0     # host seconds blocked in next(data_iter)
        wait_flushed = 0.0   # portion already on the registry counter
        h2d_inline = 0       # bytes counted on the no-prefetch path
        # a caller-owned prefetcher may carry bytes from a PREVIOUS fit —
        # baseline the flush so the counter only gets this fit's delta.
        # (an owned one starts at 0: its pre-baseline staging is ours)
        h2d_flushed = (prefetcher.stats()["h2d_bytes"]
                       if prefetcher is not None and owned is None else 0)
        pending = None       # staged log point awaiting its drain

        def _flush_obs():
            nonlocal wait_flushed, h2d_flushed
            if input_wait > wait_flushed:
                TRAIN_INPUT_WAIT.inc(input_wait - wait_flushed)
                wait_flushed = input_wait
            total = (prefetcher.stats()["h2d_bytes"]
                     if prefetcher is not None else h2d_inline)
            if total > h2d_flushed:
                TRAIN_H2D_BYTES.inc(total - h2d_flushed)
                h2d_flushed = total

        def _log_view(view: dict) -> dict:
            self._metrics_history.append(view)
            if context is not None:
                context.log_metrics(view, step=view["step"])
            else:
                logger.info("train step", **{
                    k: round(v, 4) if isinstance(v, float) else v
                    for k, v in view.items()})
            return view

        def _stage(metrics: dict, extras: dict):
            """Issue async device->host copies for the log point; the
            values are read (cheaply, already resident) at the NEXT log
            point or the loop-exit flush — dispatch never stalls here."""
            staged = {}
            for key, value in metrics.items():
                try:
                    value.copy_to_host_async()
                except AttributeError:
                    pass
                staged[key] = value
            # state.step itself is donated into the NEXT dispatch
            # (donate_argnums=0) — stage a fresh derived array instead
            step_arr = self.state.step + 0
            try:
                step_arr.copy_to_host_async()
            except AttributeError:
                pass
            return (step_arr, staged, extras)

        def _drain(entry) -> dict:
            step_arr, staged, extras = entry
            view = {k: float(v) for k, v in staged.items()}
            view.update(extras)
            view["step"] = int(step_arr)
            return _log_view(view)

        # elastic degraded-capacity accounting: while the run is at W' of
        # W devices, the (1 - W'/W) share of every step-second is moved
        # from goodput into the 'degraded' bucket — attribution still
        # sums to wall because transfer() only reclassifies, and the tax
        # lands BEFORE each export so the counters stay monotone
        degraded_lost = 0.0   # capacity fraction currently lost
        degraded_mark = 0.0   # goodput seconds already taxed
        reshard_pending = False  # next dispatch recompiles → 'reshard'

        def _degraded_tax():
            nonlocal degraded_mark
            good = ledger.goodput_seconds()
            if degraded_lost <= 0.0:
                degraded_mark = good
                return
            delta = good - degraded_mark
            if delta > 0:
                moved = delta * degraded_lost
                ledger.transfer("step", "degraded", moved)
                degraded_mark = good - moved

        hooks.on_train_begin()
        seq_len = None
        last = {}
        epoch = 0
        stopped = False
        local_stop = False  # pending stop vote, acted on at uniform points
        if epoch_steps:
            hooks.on_epoch_begin(0)
        try:
            for step in range(steps):
                # agreed() (not .requested): all hosts must latch in the SAME
                # step or the ones still stepping deadlock the slice collectives
                if preemption_guard is not None and preemption_guard.agreed():
                    logger.warning("preempted — checkpointing before exit",
                                   step=int(self.state.step))
                    flight_record("train.preempt", run=run_uid,
                                  step=int(self.state.step))
                    # a staged log point must land before the early return —
                    # its metrics are what the post-mortem sees
                    if pending is not None:
                        with ledger.phase("metric_flush"):
                            last = _drain(pending)
                        pending = None
                    if checkpoint_manager is not None:
                        with ledger.phase("checkpoint"):
                            checkpoint_manager.save(int(self.state.step),
                                                    self.state, force=True)
                            checkpoint_manager.wait()
                        if context is not None and \
                                hasattr(context, "log_checkpoint"):
                            # the service reads status.checkpoint when it
                            # resubmits the evicted slice — this write is what
                            # makes the restart a *resume*
                            context.log_checkpoint(
                                checkpoint_manager.directory,
                                step=int(self.state.step), commit=False)
                    last = dict(last)
                    last["preempted"] = True
                    last["step"] = int(self.state.step)
                    if context is not None:
                        context.log_result("preempted", True)
                    # the black-box artifact is what the post-eviction
                    # debugging session reads — dump BEFORE the process
                    # can be SIGKILLed at grace-period end
                    flight_record("train.preempt_exit", run=run_uid,
                                  step=int(self.state.step))
                    get_flight_recorder().dump(
                        "preemption", extra={"run": run_uid,
                                             "step": int(self.state.step)})
                    # preempted runs still finalize callbacks (close writers,
                    # log the tensorboard dir) — they matter MOST here, since
                    # the artifacts are what survives the eviction
                    hooks.on_train_end(last)
                    return last
                if elastic_guard is not None:
                    event = elastic_guard.poll()
                    if event is not None:
                        # a staged log point must land before the world
                        # changes — its device arrays live on the OLD mesh
                        if pending is not None:
                            with ledger.phase("metric_flush"):
                                last = _drain(pending)
                            pending = None
                        _degraded_tax()  # settle the tax at the OLD rate
                        if event.kind == "fail":
                            flight_record(
                                "train.slice_fail", run=run_uid,
                                step=int(self.state.step),
                                slice=event.slice_index,
                                survivors=len(event.devices),
                                survivor_devices=[str(d)
                                                  for d in event.devices])
                        else:
                            flight_record(
                                "train.slice_join", run=run_uid,
                                step=int(self.state.step),
                                slice=event.slice_index,
                                world=len(event.devices))
                        with ledger.phase("reshard"):
                            # shrink restores from the last checkpoint
                            # (the dead slice's shards are gone on real
                            # hardware); grow carries the live state —
                            # the survivors hold everything
                            info = self.reshard(
                                event.devices,
                                checkpoint_manager
                                if event.kind == "fail" else None,
                                num_slices=elastic_guard.num_slices
                                - len(elastic_guard.failed_slices))
                        reshard_pending = True
                        degraded_lost = elastic_guard.lost_fraction()
                        degraded_mark = ledger.goodput_seconds()
                        info_flat = {
                            k: (round(v, 3) if isinstance(v, float) else v)
                            for k, v in info.items()}
                        if event.kind == "fail":
                            # black-box artifact: survivor set + reshard
                            # decision, dumped BEFORE training resumes
                            # (the PR 10 post-mortem path)
                            get_flight_recorder().dump(
                                "slice-preemption",
                                extra={"run": run_uid,
                                       "slice": event.slice_index,
                                       "survivors": [str(d) for d
                                                     in event.devices],
                                       **info_flat})
                            flight_record("train.reshard", run=run_uid,
                                          **info_flat)
                        else:
                            flight_record("train.grow", run=run_uid,
                                          **info_flat)
                        if context is not None and \
                                hasattr(context, "log_result"):
                            context.log_result("world_size",
                                               info["world_to"])
                        if prefetcher is not None:
                            # already-staged batches re-place through
                            # shard_batch; future ones stage straight
                            # onto the new mesh
                            prefetcher._sharding = getattr(
                                self.step_fn, "_data_sharding", None)
                ledger.enter("data_wait")
                t_input = time.perf_counter()
                tokens, targets = next(data_iter)
                input_wait += time.perf_counter() - t_input
                seq_len = tokens.shape[1]
                if prefetcher is None:
                    h2d_inline += (getattr(tokens, "nbytes", 0)
                                   + getattr(targets, "nbytes", 0))
                ledger.enter("h2d")
                tokens, targets = self.shard_batch(tokens, targets)
                ledger.enter("step")
                t_dispatch = time.perf_counter()
                metrics = self._dispatch(tokens, targets)
                if step == 0 and self.compile_seconds is None:
                    # tracing + XLA compile block the host inside the first
                    # dispatch (execution does not) — compile-class time,
                    # kept OUT of the steady-state throughput window
                    self.compile_seconds = time.perf_counter() - t_dispatch
                    TRAIN_COMPILE_SECONDS.set(self.compile_seconds)
                    # ...and out of goodput: land the dispatch interval,
                    # then reclassify the compile-class share (a RESUMED
                    # run's warm re-compile is the elasticity tax bucket)
                    self._compile_attributed = True
                    ledger.enter("step")
                    ledger.transfer(
                        "step", "re_warm" if resumed else "compile",
                        self.compile_seconds)
                elif reshard_pending:
                    # the first dispatch after a reshard re-traces +
                    # compiles for the new mesh (warm when the persistent
                    # compile cache holds the program) — reshard-class
                    # time, not goodput
                    reshard_pending = False
                    recompile = time.perf_counter() - t_dispatch
                    ledger.enter("step")
                    ledger.transfer("step", "reshard", recompile)
                    degraded_mark = ledger.goodput_seconds()
                    flight_record("train.reshard_warm", run=run_uid,
                                  loop_step=step,
                                  compile_s=round(recompile, 3))
                # on-demand profiling: claims/advances an armed
                # POST /debug/profile capture; one global check when dark
                profiler_mod.tick(self._profiler_source, context)
                tracker.note_step(tokens.shape[0] * tokens.shape[1])
                log_point = (step + 1) % log_every == 0 or step == steps - 1
                # non-log steps hand callbacks the RAW device metrics — no
                # float() there, so the host keeps dispatching ahead of the
                # device; a callback that reads a value pays its own sync
                step_metrics: dict = dict(metrics)
                if log_point:
                    _degraded_tax()
                    tps = tracker.tokens_per_sec()
                    extras = {
                        "tokens_per_sec": tps,
                        "tokens_per_sec_per_chip": tps / jax.device_count(),
                        "mfu": mfu(tps,
                                   self.model_config.flops_per_token(seq_len)),
                        "input_wait_seconds": input_wait,
                    }
                    if self.compile_seconds is not None:
                        extras["compile_seconds"] = self.compile_seconds
                    if elastic_guard is not None:
                        extras["world_size"] = int(self.mesh.devices.size)
                    extras["goodput_fraction"] = ledger.goodput_fraction()
                    if tps > 0:
                        TRAIN_STEP_TIME.set(
                            tokens.shape[0] * seq_len / tps, timer="fit")
                    _flush_obs()
                    flight_record("train.step", run=run_uid,
                                  step=step + 1,
                                  goodput_fraction=round(
                                      extras["goodput_fraction"], 4))
                    if defer:
                        if pending is not None:
                            with ledger.phase("metric_flush"):
                                last = _drain(pending)
                        pending = _stage(metrics, extras)
                    else:
                        with ledger.phase("metric_flush"):
                            step_metrics = {k: float(v)
                                            for k, v in metrics.items()}
                            step_metrics.update(extras)
                            step_metrics["step"] = int(self.state.step)
                            last = _log_view(step_metrics)
                    # flush attribution deltas onto the mlt_goodput_*
                    # counters at every log point (the federation loop
                    # sees a live fraction, not an end-of-run dump)
                    ledger.export()
                if hooks.callbacks:
                    multihost = jax.process_count() > 1
                    if not hooks.on_step_end(step, step_metrics,
                                             log_point=log_point):
                        local_stop = True
                    if not multihost:
                        stopped = stopped or local_stop
                    elif log_point:
                        # multi-host: a stop vote driven by host-local state
                        # must flip every host in the SAME step or the
                        # still-stepping hosts deadlock in the slice
                        # collectives (PreemptionGuard.agreed construction).
                        # Agreement runs only at log points — deterministic
                        # step indices every host reaches — so pure-observer
                        # callbacks don't cost an allgather per step; a vote
                        # takes effect within log_every steps.
                        stopped = _all_hosts_agree(local_stop)
                    epoch_boundary = epoch_steps and \
                        ((step + 1) % epoch_steps == 0 or step == steps - 1
                         or stopped)
                    if epoch_boundary:
                        # epoch hooks always see host-readable floats — a
                        # boundary off the log cadence would otherwise hand
                        # TensorBoard/metrics logging raw device arrays
                        epoch_view = step_metrics if log_point else \
                            {k: float(v) for k, v in metrics.items()}
                        epoch_vote = not hooks.on_epoch_end(epoch, epoch_view)
                        local_stop = local_stop or epoch_vote
                        if not multihost:
                            stopped = stopped or epoch_vote
                        elif not stopped:
                            # uniform: every host reaches this iff `stopped`
                            # (agreed) is False everywhere, and the boundary
                            # condition itself is step-index-deterministic
                            stopped = _all_hosts_agree(local_stop)
                        epoch += 1
                        if not stopped and step < steps - 1:
                            hooks.on_epoch_begin(epoch)
                    if stopped:
                        if isinstance(last, dict) and last:
                            last = dict(last)
                        else:
                            last = {k: float(v) for k, v in metrics.items()}
                        last["stopped_early"] = True
                        last.setdefault("step", int(self.state.step))
                        break
            if pending is not None:
                with ledger.phase("metric_flush"):
                    last = _drain(pending)
                pending = None
            hooks.on_train_end(last)
            return last
        except BaseException as unwinding:
            # crash post-mortem: the event sequence into the failure is
            # the artifact (docs/observability.md "Flight recorder &
            # debug endpoints"). An explicit except — NOT
            # sys.exc_info() in the finally, which also sees an
            # exception a CALLER frame is busy handling and would dump
            # a spurious crash artifact for a successful fit. Guarded:
            # the original exception must win the unwind.
            try:
                flight_record("train.exception", run=run_uid,
                              error=str(unwinding),
                              error_type=type(unwinding).__name__)
                get_flight_recorder().dump(
                    "train-crash", extra={"run": run_uid,
                                          "error": str(unwinding)})
            except Exception:  # noqa: BLE001
                pass
            raise
        finally:
            if pending is not None:
                # exception exit with a staged log point: land it in the
                # history/context before unwinding (the preemption branch
                # does the same — these are the post-mortem metrics)
                try:
                    _drain(pending)
                except Exception:  # noqa: BLE001 - the original
                    pass           # exception must win the unwind
            _flush_obs()
            try:
                # settle any trailing degraded-capacity tax, then close:
                # trailing open interval -> its current phase; final
                # counter flush + fraction gauge. summary() stays
                # readable on self.goodput
                _degraded_tax()
                ledger.close()
            except Exception:  # noqa: BLE001 - accounting must not
                pass           # replace the loop's own outcome
            if owned is not None:
                # created here -> closed here; drains staged batches so a
                # producer blocked on a full queue can never outlive fit
                owned.close()

    @property
    def metrics_history(self) -> list[dict]:
        return list(self._metrics_history)
