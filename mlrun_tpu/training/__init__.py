from .checkpoint import CheckpointManager, save_checkpoint_artifact  # noqa: F401
from .data import (  # noqa: F401
    DevicePrefetchIterator,
    TokenShardLoader,
    array_token_stream,
    device_prefetch,
    per_process_batch,
    synthetic_token_stream,
    text_file_stream,
)
from .elastic import ElasticGuard, SliceEvent  # noqa: F401
from .mfu import ThroughputTracker, chip_peak_flops, mfu  # noqa: F401
from .preemption import PreemptionGuard  # noqa: F401
from .train import (  # noqa: F401
    TrainConfig,
    Trainer,
    TrainState,
    init_train_state,
    make_optimizer,
    make_train_step,
)
