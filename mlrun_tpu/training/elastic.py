"""Elastic multi-slice training: the slice-aware fault model.

A multi-slice TPU job's failure domain is the pod-slice ("Exploring the
limits of Concurrency in ML Training on Google TPUs", PAPERS.md): one
slice preempted used to mean the whole JobSet restarts. The elastic path
instead reshards the run onto the survivors and keeps training at
reduced world size until the replacement slice joins
(docs/fault_tolerance.md "Elastic training").

This module is the detection half: :class:`ElasticGuard` partitions the
device set into slices and tracks which are alive. ``Trainer.fit`` polls
it once per step (the ``preemption_guard`` pattern) and reacts to the
events it emits:

- ``fail``: a slice died — reshard onto the survivors
  (``Trainer.reshard``, restoring from the last checkpoint: on real
  hardware the dead slice's shards are gone).
- ``join``: the replacement slice is back — grow back to full world
  size (the survivors hold the full state, so this is an in-memory
  reshard, no step rewind).

Detection sources, in order:

- programmatic ``fail_slice``/``join_slice`` (tests, an external watcher
  wired to the JobSet controller's child-job events);
- the ``train.slice_fail`` chaos point, fired with a mutable ``box`` on
  every poll — an armed injection setting ``box["fail"]``/``box["join"]``
  kills/revives a slice mid-fit deterministically. The injection IS the
  failure: no devices actually die, so the same reshard machinery that
  would run on hardware is exercised end-to-end on the CPU backend.

On real multi-slice TPU, slice membership comes from the devices'
``slice_index``; on CPU/virtual backends devices are split into
``num_slices`` contiguous blocks (``MLT_NUM_SLICES`` /
``parallel.mesh._detect_num_slices``).
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Optional

from ..chaos import chaos
from ..chaos import fire as chaos_fire
from ..utils import logger


@dataclasses.dataclass(frozen=True)
class SliceEvent:
    """One slice-membership change observed by a poll."""

    kind: str           # "fail" | "join"
    slice_index: int
    devices: tuple      # the ACTIVE device set after this event


class ElasticGuard:
    """Tracks slice liveness over a device set (single consumer: the
    training loop; programmatic mutations may come from other threads —
    the event queue is the only shared state and ``deque`` append/pop
    are atomic)."""

    def __init__(self, devices=None, num_slices: int | None = None):
        import jax

        from ..parallel.mesh import _detect_num_slices

        devices = list(devices if devices is not None else jax.devices())
        if not devices:
            raise ValueError("elastic guard needs at least one device")
        num_slices = int(num_slices or _detect_num_slices(devices))
        if num_slices < 1 or len(devices) % num_slices:
            raise ValueError(
                f"{len(devices)} devices do not split into "
                f"{num_slices} equal slices")
        # group by the hardware slice_index when the backend has one;
        # contiguous equal blocks otherwise (virtual slices on CPU)
        by_slice: dict[int, list] = {}
        ids = {getattr(d, "slice_index", None) for d in devices}
        if None not in ids and len(ids) == num_slices:
            for d in devices:
                by_slice.setdefault(int(d.slice_index), []).append(d)
            self._slices = [by_slice[k] for k in sorted(by_slice)]
        else:
            per = len(devices) // num_slices
            self._slices = [devices[i * per:(i + 1) * per]
                            for i in range(num_slices)]
        self._failed: set[int] = set()
        self._events: deque = deque()

    # -- state ---------------------------------------------------------------
    @property
    def num_slices(self) -> int:
        return len(self._slices)

    @property
    def failed_slices(self) -> list[int]:
        return sorted(self._failed)

    @property
    def degraded(self) -> bool:
        return bool(self._failed)

    @property
    def devices(self) -> list:
        """The ACTIVE device set (every device of every live slice)."""
        return [d for i, group in enumerate(self._slices)
                if i not in self._failed for d in group]

    def lost_fraction(self) -> float:
        """Capacity fraction currently lost to failed slices — the
        ``degraded`` goodput-bucket tax rate."""
        return len(self._failed) / len(self._slices)

    # -- mutations -----------------------------------------------------------
    def fail_slice(self, slice_index: int):
        """Mark a slice preempted. Failing the LAST live slice is a job
        failure, not elasticity — rejected loudly so a bad injection
        can't make the trainer 'reshard' onto nothing."""
        slice_index = self._validate(slice_index)
        if slice_index in self._failed:
            return
        if len(self._failed) + 1 >= len(self._slices):
            raise ValueError(
                f"slice {slice_index} is the last survivor — no elastic "
                "recovery exists for losing every slice")
        self._failed.add(slice_index)
        logger.warning("slice preempted", slice=slice_index,
                       survivors=len(self.devices))
        self._events.append(SliceEvent("fail", slice_index,
                                       tuple(self.devices)))

    def join_slice(self, slice_index: int):
        """A replacement for a failed slice joined (grow-back)."""
        slice_index = self._validate(slice_index)
        if slice_index not in self._failed:
            return
        self._failed.discard(slice_index)
        logger.info("slice rejoined", slice=slice_index,
                    world=len(self.devices))
        self._events.append(SliceEvent("join", slice_index,
                                       tuple(self.devices)))

    def _validate(self, slice_index: int) -> int:
        slice_index = int(slice_index)
        if not 0 <= slice_index < len(self._slices):
            raise ValueError(
                f"slice {slice_index} out of range "
                f"(num_slices={len(self._slices)})")
        return slice_index

    # -- polling -------------------------------------------------------------
    def poll(self) -> Optional[SliceEvent]:
        """One health check, called once per train step. Fires the
        ``train.slice_fail`` chaos point (dark: one attribute read) and
        returns the oldest pending membership change, or None."""
        if chaos.enabled:
            box: dict = {"fail": None, "join": None}
            chaos_fire("train.slice_fail", box=box,
                       failed=self.failed_slices,
                       num_slices=len(self._slices))
            if box["fail"] is not None:
                self.fail_slice(box["fail"])
            if box["join"] is not None:
                self.join_slice(box["join"])
        return self._events.popleft() if self._events else None
