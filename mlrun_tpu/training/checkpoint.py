"""Orbax-based checkpoint/resume.

The reference has no step-level checkpointing (SURVEY.md §5.4) — this is the
TPU-native addition demanded by preemptible slices: async orbax saves of the
sharded train state into the artifact store layer, registered as model
artifacts so resume rides the same registry.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax

from ..utils import logger


class CheckpointManager:
    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_interval_steps: int = 0):
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(os.path.expanduser(directory))
        os.makedirs(self.directory, exist_ok=True)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps or 1,
            enable_async_checkpointing=True)
        self._manager = ocp.CheckpointManager(self.directory, options=options)

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        import orbax.checkpoint as ocp
        from orbax.checkpoint.checkpoint_manager import (
            StepAlreadyExistsError,
        )

        try:
            saved = self._manager.save(
                step, args=ocp.args.StandardSave(_to_pytree(state)),
                force=force)
        except StepAlreadyExistsError:
            # a forced save (e.g. the preemption path) can race a periodic
            # save of the same step — the step being on disk IS success
            return True
        return bool(saved)

    def restore(self, state_like: Any, step: int | None = None) -> Any:
        """Restore ``step`` (default: latest) into the shape of
        ``state_like``.

        The restore is **sharding-agnostic**: every device-array leaf of
        the target is reduced to its abstract (shape, dtype, sharding)
        before orbax sees it, so a checkpoint written at N slices
        restores at N−1 (or N+1) — the arrays materialize directly under
        the TARGET's shardings, whatever layout the writer had. This is
        the load-bearing invariant of elastic training: the survivors'
        trainer hands in a state skeleton sharded over the SHRUNK mesh
        and gets the old checkpoint's values back resharded onto it.
        ``state_like`` leaves may already be abstract
        (``jax.ShapeDtypeStruct`` carrying a sharding) — the elastic
        reshard path builds exactly that, with no concrete donor state.
        """
        import orbax.checkpoint as ocp

        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        target = _abstract_leaves(_to_pytree(state_like))
        restored = self._manager.restore(
            step, args=ocp.args.StandardRestore(target))
        # Donation safety: orbax hands back arrays whose buffers the
        # restore machinery may still co-own. The trainer donates the
        # state into the step executable (donate_argnums=0), and
        # donating a co-owned buffer corrupts it — observed as garbage
        # step values / segfaults once the executable came
        # deserialized from the persistent compile cache. One XLA copy
        # per restore makes every leaf exclusively ours.
        import jax.numpy as jnp

        restored = jax.tree_util.tree_map(
            lambda a: jnp.copy(a) if isinstance(a, jax.Array) else a,
            restored)
        return _from_pytree(state_like, restored)

    def latest_step(self) -> Optional[int]:
        return self._manager.latest_step()

    def wait(self):
        self._manager.wait_until_finished()

    def close(self):
        self._manager.close()


def _to_pytree(state):
    from .train import TrainState

    if isinstance(state, TrainState):
        tree = {"params": state.params, "opt_state": state.opt_state,
                "step": state.step}
        if state.lora is not None:
            tree["lora"] = state.lora
        return tree
    return state


def _abstract_leaves(tree):
    """Replace device-array leaves with (shape, dtype, sharding)
    abstractions. Host leaves (numpy scalars etc.) pass through concrete;
    already-abstract leaves pass through unchanged."""

    def leaf(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        sharding = getattr(x, "sharding", None)
        if isinstance(x, jax.Array) and sharding is not None:
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)
        return x

    return jax.tree_util.tree_map(leaf, tree)


def _from_pytree(state_like, restored):
    from .train import TrainState

    if isinstance(state_like, TrainState):
        return TrainState(
            restored["params"], restored["opt_state"], restored["step"],
            restored.get("lora"))
    return restored


def save_checkpoint_artifact(context, key: str, manager: CheckpointManager,
                             framework: str = "jax", **kwargs):
    """Register the checkpoint dir as a model artifact on the run."""
    manager.wait()
    record = getattr(context, "log_checkpoint", None)
    if record is not None:
        # status.checkpoint is what the service monitor wires into a
        # resubmitted JobSet's resume env (runtime_handlers.TpuJobHandler)
        record(manager.directory, step=manager.latest_step(), commit=False)
    return context.log_model(
        key, model_dir=manager.directory, framework=framework,
        upload=False, target_path=manager.directory, **kwargs)


def resume_directive() -> tuple[str, Optional[int]] | None:
    """The checkpoint-resume env contract written by the service when it
    resubmits a preempted run: (path, step) or None. Step may be None when
    only the path was recorded."""
    from ..common.runtimes_constants import (
        RESUME_CHECKPOINT_ENV,
        RESUME_STEP_ENV,
    )

    path = os.environ.get(RESUME_CHECKPOINT_ENV, "")
    if not path:
        return None
    step_raw = os.environ.get(RESUME_STEP_ENV, "")
    try:
        step = int(step_raw) if step_raw else None
    except ValueError:
        step = None
    return path, step
