"""MFU accounting — model FLOPs utilization vs chip peak, and the
steady-state throughput window behind ``tokens_per_sec``/``mfu`` in
``Trainer.fit`` (docs/training_performance.md)."""

from __future__ import annotations

import time

import jax

# peak dense bf16 TFLOP/s per chip
PEAK_FLOPS = {
    "v4": 275e12,
    "v5 lite": 197e12,  # v5e
    "v5e": 197e12,
    "v5": 459e12,       # v5p
    "v5p": 459e12,
    "v6 lite": 918e12,  # trillium / v6e
    "v6e": 918e12,
    "cpu": 1e12,        # nominal, keeps the math defined on CPU meshes
}


def chip_peak_flops(device=None) -> float:
    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for name, peak in PEAK_FLOPS.items():
        if name in kind:
            return peak
    if device.platform in ("tpu", "axon"):
        return 197e12  # conservative default: v5e
    return PEAK_FLOPS["cpu"]


def mfu(tokens_per_sec: float, flops_per_token: float,
        n_chips: int | None = None, device=None) -> float:
    n_chips = n_chips or jax.device_count()
    peak = chip_peak_flops(device) * n_chips
    value = (tokens_per_sec * flops_per_token) / peak
    # surface the last computed utilization on /metrics so a scrape
    # answers "is this slice earning its keep" without a log dive
    from ..obs import TRAIN_MFU

    TRAIN_MFU.set(value)
    return value


class ThroughputTracker:
    """Steady-state tokens/sec window for the training loop.

    Dividing total tokens by total elapsed time folds the first step's
    XLA compile into the rate, understating throughput (and MFU) for any
    run short enough to care about — a 60 s compile over a 100-step smoke
    run halves the reported number. The tracker excludes the first
    ``warmup_excluded`` steps from the window: ``note_step`` is called
    after each step's *dispatch* returns (jit tracing+compile block the
    host there, execution does not), so the steady window starts once
    compile-class host stalls are behind us. Compile time itself is
    reported separately (``compile_seconds``).
    """

    def __init__(self, warmup_excluded: int = 1):
        self.warmup_excluded = max(0, int(warmup_excluded))
        self.steps = 0
        self.tokens_total = 0
        self._t_start = time.perf_counter()
        self._t_steady: float | None = (
            self._t_start if self.warmup_excluded == 0 else None)
        self._tokens_at_steady = 0

    def note_step(self, tokens: int):
        self.steps += 1
        self.tokens_total += int(tokens)
        if self._t_steady is None and self.steps >= self.warmup_excluded:
            self._t_steady = time.perf_counter()
            self._tokens_at_steady = self.tokens_total

    @property
    def in_steady_state(self) -> bool:
        return (self._t_steady is not None
                and self.tokens_total > self._tokens_at_steady)

    def tokens_per_sec(self) -> float:
        """Steady-state rate; falls back to the whole-run rate while the
        warmup window hasn't produced a measurable steady interval."""
        now = time.perf_counter()
        if self.in_steady_state:
            elapsed = now - self._t_steady
            tokens = self.tokens_total - self._tokens_at_steady
        else:
            elapsed = now - self._t_start
            tokens = self.tokens_total
        return tokens / elapsed if elapsed > 0 else 0.0
