"""MFU accounting — model FLOPs utilization vs chip peak."""

from __future__ import annotations

import jax

# peak dense bf16 TFLOP/s per chip
PEAK_FLOPS = {
    "v4": 275e12,
    "v5 lite": 197e12,  # v5e
    "v5e": 197e12,
    "v5": 459e12,       # v5p
    "v5p": 459e12,
    "v6 lite": 918e12,  # trillium / v6e
    "v6e": 918e12,
    "cpu": 1e12,        # nominal, keeps the math defined on CPU meshes
}


def chip_peak_flops(device=None) -> float:
    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for name, peak in PEAK_FLOPS.items():
        if name in kind:
            return peak
    if device.platform in ("tpu", "axon"):
        return 197e12  # conservative default: v5e
    return PEAK_FLOPS["cpu"]


def mfu(tokens_per_sec: float, flops_per_token: float,
        n_chips: int | None = None, device=None) -> float:
    n_chips = n_chips or jax.device_count()
    peak = chip_peak_flops(device) * n_chips
    value = (tokens_per_sec * flops_per_token) / peak
    # surface the last computed utilization on /metrics so a scrape
    # answers "is this slice earning its keep" without a log dive
    from ..obs import TRAIN_MFU

    TRAIN_MFU.set(value)
    return value
