"""Graceful preemption handling for TPU slices.

Preemptible/spot TPU pod-slices get SIGTERM with a grace period before
eviction (GKE node drain). The reference has no in-run elasticity at all
(SURVEY §5.3: an MPIJob worker failure fails the run); the TPU-native
design instead checkpoints at the preemption signal so the rescheduled
JobSet restart resumes from the last step rather than from scratch.

Usage (wired through Trainer.fit): install() the guard once per process;
the training loop polls ``requested`` each step and performs a final
synchronous checkpoint before exiting with a resumable state.
"""

from __future__ import annotations

import os
import signal
import threading

from ..utils import logger


class PreemptionGuard:
    """Latches SIGTERM (and optionally extra signals) into a flag the
    training loop can poll. Chain-calls any previous handler so process
    managers above us still observe the signal."""

    def __init__(self, signals=(signal.SIGTERM,)):
        self._signals = tuple(signals)
        self._event = threading.Event()
        self._previous: dict = {}
        self._installed = False

    # -- lifecycle ---------------------------------------------------------
    def install(self) -> "PreemptionGuard":
        if self._installed:
            return self
        if threading.current_thread() is not threading.main_thread():
            # signal handlers can only be set from the main thread (e.g.
            # service-threaded local runs); fall back to manual request()
            logger.warning("preemption guard not installed "
                           "(not on main thread)")
            return self
        for sig in self._signals:
            self._previous[sig] = signal.signal(sig, self._handle)
        self._installed = True
        return self

    def restore(self):
        if not self._installed:
            return
        for sig, previous in self._previous.items():
            signal.signal(sig, previous)
        self._previous.clear()
        self._installed = False

    def __enter__(self) -> "PreemptionGuard":
        return self.install()

    def __exit__(self, *exc_info):
        self.restore()

    # -- signal path -------------------------------------------------------
    def _handle(self, signum, frame):
        # nothing but the event set may happen here: logging can hit
        # CPython's buffered-IO reentrancy guard if the signal lands
        # mid-write, and chaining an exiting previous handler would kill
        # the process before the graceful checkpoint runs. The FIRST
        # signal only latches; a SECOND signal escalates to the previous
        # handler (supervisor semantics preserved for hard kills).
        if self._event.is_set():
            previous = self._previous.get(signum)
            if callable(previous):
                previous(signum, frame)
            elif previous == signal.SIG_DFL:
                # the saved handler is usually SIG_DFL (an int, not
                # callable) — restore it and re-raise so the default
                # terminate semantics actually apply on escalation
                # instead of silently swallowing every later signal
                signal.signal(signum, signal.SIG_DFL)
                os.kill(os.getpid(), signum)
            return
        self._event.set()

    def request(self):
        """Programmatic preemption (tests / external watchers)."""
        self._event.set()

    def on_preempted(self, callback, name: str = "preemption-watcher",
                     timeout: float | None = None) -> threading.Thread:
        """Run ``callback`` once when the preemption latch sets.

        The watcher thread blocks on the latch event (no polling), so the
        callback fires on the FIRST signal — before the second-signal
        escalation in ``_handle`` can ever run. Serving replicas use this
        to drain in-flight requests inside the eviction grace period
        (``GraphServer.drain_on_preemption``). Returns the (daemon)
        watcher thread."""

        def _wait():
            if not self._event.wait(timeout):
                return
            # the latch set is the black-box event of record for an
            # eviction; the dump AFTER the callback captures the drain
            # decisions too (obs/flight.py — guarded: the grace-period
            # drain must never be blocked by telemetry)
            try:
                from ..obs import flight_record

                flight_record("preempt.signal", watcher=name)
            except Exception:  # noqa: BLE001
                pass
            try:
                callback()
            except Exception as exc:  # noqa: BLE001 - a crashing handler
                # must not take the watcher (and the process teardown) down
                logger.error("preemption callback failed", error=str(exc))
            try:
                from ..obs import get_flight_recorder

                get_flight_recorder().dump("preemption",
                                           extra={"watcher": name})
            except Exception:  # noqa: BLE001
                pass

        thread = threading.Thread(target=_wait, daemon=True, name=name)
        thread.start()
        return thread

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    def agreed(self) -> bool:
        """Cross-host agreement on the preemption latch.

        SIGTERM lands on pod-slice hosts at slightly different times; if
        one host stops stepping while another still runs the train-step
        collectives, the slice deadlocks until SIGKILL. Under multi-host
        JAX this reduces the local flag across processes (max), so every
        host flips in the same step. Single-process: just the local flag.
        """
        import jax

        if jax.process_count() <= 1:
            return self._event.is_set()
        import numpy as np
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray(self._event.is_set(), np.int32))
        return bool(np.max(flags))
