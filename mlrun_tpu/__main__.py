"""CLI (reference analog: mlrun/__main__.py:79 `main` click group —
run/build/deploy/project/get/logs/version commands; `run --from-env` is the
in-pod entrypoint contract, reference :241-244).
"""

from __future__ import annotations

import base64
import json
import os
import pathlib
import sys
import tempfile

import click

from .config import mlconf
from .utils import logger


@click.group()
def main():
    """mlrun-tpu — TPU-native MLOps framework CLI."""


@main.command(context_settings={"ignore_unknown_options": True})
@click.argument("url", required=False)
@click.option("--name", default="", help="run name")
@click.option("--project", "-p", default="", help="project name")
@click.option("--handler", default="", help="handler function name")
@click.option("--param", multiple=True, help="key=value parameter")
@click.option("--str-param", multiple=True,
              help="key=value parameter taken verbatim as a string (no "
                   "JSON coercion; the KFP compiler routes STRING-typed "
                   "step outputs here so '7' stays '7')")
@click.option("--inputs", "-i", multiple=True, help="key=url input")
@click.option("--artifact-path", default="", help="artifact output path")
@click.option("--kind", default="", help="runtime kind")
@click.option("--image", default="", help="container image")
@click.option("--from-env", is_flag=True,
              help="read run spec from MLT_EXEC_CONFIG (in-pod entrypoint)")
@click.option("--kfp-output", multiple=True,
              help="key=path: write run result <key> to <path> after the "
                   "run (KFP v2 output-parameter contract; paths come "
                   "from placeholder-substituted args)")
@click.option("--local", is_flag=True, help="force local in-process run")
@click.option("--watch", "-w", is_flag=True, default=False)
@click.argument("run_args", nargs=-1, type=click.UNPROCESSED)
def run(url, name, project, handler, param, str_param, inputs,
        artifact_path, kind, image, from_env, kfp_output, local, watch,
        run_args):
    """Execute a function/task (the in-pod contract: `run --from-env`)."""
    from .model import RunObject
    from .run import new_function

    struct = {}
    if from_env:
        config = os.environ.get(mlconf.exec_config_env)
        if not config:
            raise click.ClickException(
                f"--from-env set but {mlconf.exec_config_env} is empty")
        struct = json.loads(config)
        # embedded code (reference MLRUN_EXEC_CODE contract, __main__.py:313)
        code = os.environ.get(mlconf.exec_code_env)
        if code and not url:
            # a private temp dir, NOT the cwd — with the local-process
            # provider the subprocess inherits the service's cwd and a
            # bare "main.py" would clobber whatever file lives there
            code_dir = tempfile.mkdtemp(prefix="mlt-exec-")
            url = os.path.join(code_dir, "main.py")
            pathlib.Path(url).write_text(
                base64.b64decode(code).decode())

    # a RunObject, not a RunTemplate: the exec config of a RESUBMITTED
    # resource carries status (retry_count, checkpoint) that the in-run
    # ctx must round-trip instead of erasing on its first store_run
    template = RunObject.from_dict(struct) if struct else RunObject()
    if name:
        template.metadata.name = name
    if project:
        template.metadata.project = project
    for pair in param:
        key, _, value = pair.partition("=")
        try:
            value = json.loads(value)
        except (ValueError, TypeError):
            pass
        template.spec.parameters[key] = value
    for pair in str_param:
        key, _, value = pair.partition("=")
        template.spec.parameters[key] = value
    for pair in inputs:
        key, _, value = pair.partition("=")
        template.spec.inputs[key] = value
    if artifact_path:
        template.spec.output_path = artifact_path

    fn = new_function(
        name=name or template.metadata.name or "run",
        project=project or template.metadata.project,
        kind=kind or ("local" if (from_env or local or not mlconf.is_remote)
                      else "job"),
        command=url or "", image=image)
    run_result = fn.run(
        template, handler=handler or template.spec.handler_name or None,
        local=from_env or local, watch=watch)
    state = run_result.state()
    # KFP v2 output parameters: the pipeline compiler passes each produced
    # key as `--kfp-output key={{$.outputs.parameters[...].output_file}}`
    # (args, because the KFP launcher substitutes runtime placeholders in
    # command/args only — env values arrive verbatim); write the run
    # results there so downstream taskOutputParameter inputs resolve.
    # MLT_KFP_OUTPUTS stays as a JSON-env fallback for non-KFP callers.
    output_map = {}
    env_outputs = os.environ.get("MLT_KFP_OUTPUTS")
    if env_outputs:
        output_map.update(json.loads(env_outputs))
    for item in kfp_output:
        key, _, path = item.partition("=")
        if path:
            output_map[key] = path
    if output_map and state != "error":
        results = run_result.status.results or {}
        missing = []
        for key, path in output_map.items():
            if key not in results:
                missing.append(key)
                continue
            value = results[key]
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            pathlib.Path(path).write_text(
                value if isinstance(value, str) else json.dumps(value))
        if missing:
            # fail HERE with the unproduced keys named — otherwise the KFP
            # launcher fails the task later with an opaque "missing output
            # file" that doesn't point at the handler's actual omission
            raise click.ClickException(
                "run finished but did not produce declared output "
                f"parameter(s) {sorted(missing)}; available results: "
                f"{sorted(results)}")
    click.echo(f"run {run_result.metadata.uid} finished: {state}")
    if state == "error":
        click.echo(run_result.status.error or "", err=True)
        sys.exit(1)


@main.command()
@click.argument("kind", type=click.Choice(
    ["runs", "functions", "artifacts", "projects", "schedules"]))
@click.option("--project", "-p", default="")
@click.option("--name", default="")
@click.option("--state", default="")
def get(kind, project, name, state):
    """List objects from the run DB."""
    from .db import get_run_db

    db = get_run_db()
    if kind == "runs":
        rows = db.list_runs(name=name, project=project, state=state)
        for r in rows:
            meta, status = r.get("metadata", {}), r.get("status", {})
            click.echo(f"{meta.get('uid', '')[:12]}  "
                       f"{meta.get('name', ''):24} {status.get('state', '')}"
                       f"  {status.get('results', {})}")
    elif kind == "functions":
        for f in db.list_functions(name=name, project=project):
            meta = f.get("metadata", {})
            click.echo(f"{meta.get('name', ''):24} {f.get('kind', '')}")
    elif kind == "artifacts":
        for a in db.list_artifacts(name=name, project=project):
            meta = a.get("metadata", {})
            click.echo(f"{meta.get('key', ''):24} {a.get('kind', '')}  "
                       f"{a.get('spec', {}).get('target_path', '')}")
    elif kind == "projects":
        for p in db.list_projects():
            click.echo(p.get("metadata", {}).get("name", ""))
    elif kind == "schedules":
        for s in db.list_schedules(project or "*"):
            click.echo(f"{s.get('name', ''):24} {s.get('cron_trigger', '')}")


@main.command()
@click.argument("uid")
@click.option("--project", "-p", default="")
@click.option("--watch", "-w", is_flag=True)
def logs(uid, project, watch):
    """Fetch (or tail) run logs."""
    from .db import get_run_db

    state, _ = get_run_db().watch_log(uid, project, watch=watch)
    click.echo(f"\nfinal state: {state}")


@main.command()
@click.argument("context", default="./")
@click.option("--name", "-n", default="")
@click.option("--url", "-u", default="")
@click.option("--run", "-r", "workflow", default="",
              help="run this workflow after load")
@click.option("--arguments", "-x", multiple=True, help="workflow key=value")
def project(context, name, url, workflow, arguments):
    """Load (and optionally run a workflow of) a project."""
    from .projects import load_project

    proj = load_project(context=context, url=url or None, name=name or None)
    click.echo(f"project loaded: {proj.name}")
    if workflow:
        args = {}
        for pair in arguments:
            key, _, value = pair.partition("=")
            args[key] = value
        status = proj.run(workflow, arguments=args, engine="local")
        click.echo(f"workflow {workflow}: {status.state}")


@main.command()
@click.argument("func_url")
@click.option("--tag", default="latest")
@click.option("--with-tpu", is_flag=True)
def build(func_url, tag, with_tpu):
    """Build/deploy a function image via the service."""
    import inspect

    from .run import import_function

    fn = import_function(func_url)
    deploy_kwargs = {}
    if "with_tpu" in inspect.signature(fn.deploy).parameters:
        deploy_kwargs["with_tpu"] = with_tpu
    ok = fn.deploy(**deploy_kwargs)
    click.echo(f"build {'succeeded' if ok else 'failed'}: {fn.spec.image}")
    if not ok:
        sys.exit(1)


@main.command()
@click.option("--port", default=0, type=int)
@click.option("--host", default="")
def db(port, host):
    """Start the metadata/orchestration service (aiohttp)."""
    from .service.app import run_app

    run_app(host=host, port=port)


@main.command()
@click.option("--port", default=8080, type=int)
@click.option("--host", default="0.0.0.0")
@click.option("--function", "func_url", default="",
              help="db:// or yaml url of a serving function")
def serve(port, host, func_url):
    """Start a serving-graph gateway (SERVING_SPEC_ENV or --function)."""
    from .serving.asgi import serve as serve_graph

    function = None
    if func_url:
        from .run import import_function

        function = import_function(func_url)
    serve_graph(function=function, host=host, port=port)


@main.command(context_settings={"ignore_unknown_options": True})
@click.option("--requirement", "-r", multiple=True,
              help="pip requirement (repeatable)")
@click.option("--overlay-root", default="", help="overlay cache directory")
@click.argument("cmd", nargs=-1, type=click.UNPROCESSED)
def bootstrap(requirement, overlay_root, cmd):
    """Ensure a cached requirements overlay, then exec CMD with it on
    PYTHONPATH — the in-pod half of the build path (runtime handlers wrap
    run commands with this when the function declares
    build.requirements)."""
    from .utils.bootstrap import exec_with_requirements

    exec_with_requirements(list(requirement), list(cmd),
                           overlay_root=overlay_root or None)


@main.command()
def version():
    from . import __version__

    click.echo(f"mlrun-tpu version {__version__}")


@main.command()
@click.option("--api", default="", help="service url")
@click.option("--artifact-path", default="")
@click.option("--env-file", default="~/.mlrun-tpu.env")
def config_cmd(api, artifact_path, env_file):
    """Write a client env file."""
    path = os.path.expanduser(env_file)
    lines = []
    if api:
        lines.append(f"MLT_DBPATH={api}")
    if artifact_path:
        lines.append(f"MLT_ARTIFACT_PATH={artifact_path}")
    with open(path, "w") as fp:
        fp.write("\n".join(lines) + "\n")
    click.echo(f"wrote {path}")


main.add_command(config_cmd, name="config")


if __name__ == "__main__":
    main()
