"""Core object model (reference analog: mlrun/model.py — fresh implementation).

``ModelObj`` is the serialization base (reference mlrun/model.py:46): declarative
``_dict_fields`` plus nested-object fields, round-tripping to/from plain dicts.
``RunSpec``/``RunStatus``/``RunObject`` mirror the run contract
(reference model.py:904,1262,1454); ``RunTemplate`` is the submittable task;
``HyperParamOptions`` (:856) drives the grid/list/random generators;
``Notification`` (:681) is the notification spec.
"""

from __future__ import annotations

import base64
import json
import time
import typing
import warnings
from copy import deepcopy
from typing import Any, Optional

from .common.runtimes_constants import RunStates
from .config import mlconf
from .utils import generate_uid, get_in, now_iso, update_in


class ModelObj:
    """Dict-serializable base object.

    Subclasses list plain fields in ``_dict_fields`` and nested model fields in
    ``_fields_to_serialize`` mapping name -> class (or None for raw dict).
    """

    _dict_fields: list[str] = []
    _nested_fields: dict[str, type | None] = {}

    @staticmethod
    def _verify_list(param, name):
        if param is not None and not isinstance(param, list):
            raise ValueError(f"parameter {name} must be a list")

    @staticmethod
    def _verify_dict(param, name):
        if param is not None and not isinstance(param, dict):
            raise ValueError(f"parameter {name} must be a dict")

    def to_dict(self, exclude: list | None = None) -> dict:
        exclude = exclude or []
        out: dict[str, Any] = {}
        fields = self._dict_fields or [
            k for k in self.__dict__ if not k.startswith("_")
        ]
        for field in fields:
            if field in exclude:
                continue
            value = getattr(self, field, None)
            if value is None:
                continue
            if isinstance(value, ModelObj):
                value = value.to_dict()
            elif isinstance(value, list) and value and isinstance(value[0], ModelObj):
                value = [v.to_dict() for v in value]
            out[field] = value
        return out

    @classmethod
    def from_dict(cls, struct: dict | None = None, deprecated_fields: dict | None = None):
        struct = struct or {}
        deprecated_fields = deprecated_fields or {}
        obj = cls()
        fields = cls._dict_fields or list(struct.keys())
        for field in fields:
            if field not in struct:
                continue
            value = struct[field]
            nested_cls = cls._nested_fields.get(field)
            if nested_cls is not None and isinstance(value, dict):
                value = nested_cls.from_dict(value)
            setattr(obj, field, value)
        for old, new in deprecated_fields.items():
            if old in struct and new:
                setattr(obj, new, struct[old])
        return obj

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), default=str)

    def to_yaml(self) -> str:
        import yaml

        return yaml.safe_dump(self.to_dict(), default_flow_style=False)

    def copy(self):
        return deepcopy(self)

    def __repr__(self):
        return f"{self.__class__.__name__}({self.to_dict()})"


class Credentials(ModelObj):
    _dict_fields = ["access_key"]

    def __init__(self, access_key: str | None = None):
        self.access_key = access_key


class ImageBuilder(ModelObj):
    """Image build spec (reference model.py:485)."""

    _dict_fields = [
        "functionSourceCode", "source", "image", "base_image", "commands",
        "extra", "secret", "code_origin", "origin_filename", "requirements",
    ]

    def __init__(self, functionSourceCode=None, source=None, image=None,
                 base_image=None, commands=None, extra=None, secret=None,
                 code_origin=None, origin_filename=None, requirements=None):
        self.functionSourceCode = functionSourceCode
        self.source = source
        self.image = image
        self.base_image = base_image
        self.commands = commands or []
        self.extra = extra
        self.secret = secret
        self.code_origin = code_origin
        self.origin_filename = origin_filename
        self.requirements = requirements or []

    def with_source(self, source_code: str):
        self.functionSourceCode = base64.b64encode(source_code.encode()).decode()
        return self


class Notification(ModelObj):
    """Notification spec (reference model.py:681)."""

    _dict_fields = [
        "kind", "name", "message", "severity", "when", "condition",
        "params", "status", "sent_time",
    ]

    def __init__(self, kind="console", name="", message="", severity="info",
                 when=None, condition="", params=None, status=None, sent_time=None):
        self.kind = kind
        self.name = name
        self.message = message
        self.severity = severity
        self.when = when or ["completed", "error"]
        self.condition = condition
        self.params = params or {}
        self.status = status
        self.sent_time = sent_time


class HyperParamStrategies:
    grid = "grid"
    list = "list"
    random = "random"
    custom = "custom"
    all = [grid, list, random, custom]


class HyperParamOptions(ModelObj):
    """Hyper-parameter sweep options (reference model.py:856)."""

    _dict_fields = [
        "param_file", "strategy", "selector", "max_iterations", "max_errors",
        "parallel_runs", "stop_condition", "teardown_dask",
    ]

    def __init__(self, param_file=None, strategy=None, selector=None,
                 max_iterations=None, max_errors=None, parallel_runs=None,
                 stop_condition=None, teardown_dask=None):
        self.param_file = param_file
        self.strategy = strategy
        self.selector = selector  # e.g. "max.accuracy" / "min.loss"
        self.max_iterations = max_iterations
        self.max_errors = max_errors
        self.parallel_runs = parallel_runs
        self.stop_condition = stop_condition
        self.teardown_dask = teardown_dask


class RunMetadata(ModelObj):
    _dict_fields = ["uid", "name", "project", "labels", "annotations", "iteration"]

    def __init__(self, uid=None, name=None, project=None, labels=None,
                 annotations=None, iteration=None):
        self.uid = uid
        self.name = name
        self.project = project
        self.labels = labels or {}
        self.annotations = annotations or {}
        self.iteration = iteration or 0


class RunSpec(ModelObj):
    """Run spec (reference model.py:904)."""

    _dict_fields = [
        "parameters", "hyperparams", "hyper_param_options", "inputs", "outputs",
        "input_path", "output_path", "function", "secret_sources", "data_stores",
        "handler", "scrape_metrics", "verbose", "notifications", "state_thresholds",
        "returns", "allow_empty_resources", "retry_policy",
    ]
    _nested_fields = {"hyper_param_options": HyperParamOptions}

    def __init__(self, parameters=None, hyperparams=None, hyper_param_options=None,
                 inputs=None, outputs=None, input_path=None, output_path=None,
                 function=None, secret_sources=None, data_stores=None, handler=None,
                 scrape_metrics=None, verbose=None, notifications=None,
                 state_thresholds=None, returns=None, allow_empty_resources=None,
                 retry_policy=None):
        self.parameters = parameters or {}
        self.hyperparams = hyperparams or {}
        self.hyper_param_options = hyper_param_options or HyperParamOptions()
        self.inputs = inputs or {}
        self.outputs = outputs or []
        self.input_path = input_path
        self.output_path = output_path
        self.function = function
        self.secret_sources = secret_sources or []
        self.data_stores = data_stores or []
        self.handler = handler
        self.scrape_metrics = scrape_metrics
        self.verbose = verbose
        self.notifications = notifications or []
        self.state_thresholds = state_thresholds or {}
        self.returns = returns or []
        self.allow_empty_resources = allow_empty_resources
        # run-level fault tolerance (common/schemas/run.py RetryPolicy;
        # enforced by service/runtime_handlers.py monitor_runs)
        self.retry_policy = retry_policy or {}

    @property
    def handler_name(self) -> str:
        if callable(self.handler):
            return self.handler.__name__
        return str(self.handler or "")

    def is_hyper_job(self) -> bool:
        return bool(self.hyperparams) or bool(
            self.hyper_param_options and self.hyper_param_options.param_file
        )


class RunStatus(ModelObj):
    """Run status (reference model.py:1262)."""

    _dict_fields = [
        "state", "error", "host", "commit", "status_text", "results", "artifacts",
        "artifact_uris", "start_time", "last_update", "end_time", "iterations",
        "ui_url", "reason", "notifications", "retry_count", "failure_class",
        "checkpoint", "last_heartbeat",
    ]

    def __init__(self, state=None, error=None, host=None, commit=None,
                 status_text=None, results=None, artifacts=None, artifact_uris=None,
                 start_time=None, last_update=None, end_time=None, iterations=None,
                 ui_url=None, reason=None, notifications=None, retry_count=None,
                 failure_class=None, checkpoint=None, last_heartbeat=None):
        self.state = state or RunStates.created
        self.error = error
        self.host = host
        self.commit = commit
        self.status_text = status_text
        self.results = results
        self.artifacts = artifacts
        self.artifact_uris = artifact_uris or {}
        self.start_time = start_time
        self.last_update = last_update
        self.end_time = end_time
        self.iterations = iterations
        self.ui_url = ui_url
        self.reason = reason
        self.notifications = notifications or {}
        # fault-tolerance bookkeeping (service monitor + in-run ctx)
        self.retry_count = retry_count
        self.failure_class = failure_class
        self.checkpoint = checkpoint
        self.last_heartbeat = last_heartbeat

    def is_failed(self) -> Optional[bool]:
        if self.state in RunStates.error_states():
            return True
        if self.state in RunStates.terminal_states():
            return False
        return None


class RunTemplate(ModelObj):
    """A submittable task: metadata + spec (reference model.py:1358)."""

    _dict_fields = ["kind", "metadata", "spec"]
    _nested_fields = {"metadata": RunMetadata, "spec": RunSpec}
    kind = "run"

    def __init__(self, spec: RunSpec | None = None, metadata: RunMetadata | None = None):
        self.spec = spec or RunSpec()
        self.metadata = metadata or RunMetadata()

    # fluent task-building api (reference model.py NewTask helpers)
    def with_params(self, **params):
        self.spec.parameters = params
        return self

    def with_input(self, key, path):
        self.spec.inputs[key] = path
        return self

    def with_hyper_params(self, hyperparams: dict, selector=None, strategy=None,
                          **options):
        self.spec.hyperparams = hyperparams
        opts = self.spec.hyper_param_options or HyperParamOptions()
        opts.selector = selector or opts.selector
        opts.strategy = strategy or opts.strategy
        for key, value in options.items():
            setattr(opts, key, value)
        self.spec.hyper_param_options = opts
        return self

    def with_secrets(self, kind, source):
        self.spec.secret_sources.append({"kind": kind, "source": source})
        return self

    def with_retry(self, max_retries: int = 3, backoff: float = 5.0,
                   backoff_factor: float = 2.0, backoff_max: float = 300.0,
                   jitter: float = 0.1, retry_on: list | None = None,
                   stall_timeout: float = -1.0, on_stall: str = "abort"):
        """Opt this run into service-side resubmission on infra failures
        (preemption, image-pull backoff, node drain, 5xx) — user-code
        errors are never retried. ``stall_timeout``/``on_stall`` arm the
        heartbeat watchdog. See docs/fault_tolerance.md."""
        from .common.schemas.run import RetryPolicy

        policy = RetryPolicy(
            max_retries=max_retries, backoff=backoff,
            backoff_factor=backoff_factor, backoff_max=backoff_max,
            jitter=jitter, retry_on=retry_on, stall_timeout=stall_timeout,
            on_stall=on_stall)
        self.spec.retry_policy = policy.model_dump(exclude_none=True)
        return self

    def set_label(self, key, value):
        self.metadata.labels[key] = str(value)
        return self


class RunObject(RunTemplate):
    """A submitted/executing run — template + live status (reference model.py:1454)."""

    _dict_fields = ["kind", "metadata", "spec", "status"]
    _nested_fields = {"metadata": RunMetadata, "spec": RunSpec, "status": RunStatus}

    def __init__(self, spec=None, metadata=None, status=None):
        super().__init__(spec, metadata)
        self.status = status or RunStatus()
        self._db = None

    @classmethod
    def from_template(cls, template: RunTemplate) -> "RunObject":
        return cls(spec=template.spec.copy(), metadata=template.metadata.copy())

    @property
    def uid(self) -> str:
        return self.metadata.uid

    def state(self) -> str:
        """Current run state — a METHOD, matching the reference contract
        (reference model.py:1720): terminal states return directly, a
        non-terminal state refreshes from the DB first so pollers see
        live progress."""
        current = (self.status.state if self.status else None)
        if current in RunStates.terminal_states():
            return current
        try:
            self.refresh()
        except Exception:  # noqa: BLE001 - detached object (no DB): the
            # locally-known state is still the best answer
            pass
        return (self.status.state if self.status else None) \
            or RunStates.created

    @property
    def error(self) -> str:
        """Error string when the run failed/aborted, else ''
        (reference model.py:1504)."""
        if self.status and self.status.state in (
                RunStates.error, RunStates.aborted, RunStates.aborting):
            return (self.status.error or self.status.reason
                    or self.status.status_text
                    or ("run was aborted"
                        if self.status.state != RunStates.error
                        else "unknown error"))
        return ""

    @property
    def ui_url(self) -> str:
        """UI URL when a frontend is attached (reference model.py:1566)."""
        return (self.status.ui_url if self.status else "") or ""

    def abort(self):
        """Abort the run server-side (reference model.py:1831)."""
        self._run_db().abort_run(self.metadata.uid, self.metadata.project)

    @staticmethod
    def create_uri(project: str, uid: str, iteration, tag: str = "") -> str:
        """<project>@<uid>#<iteration>[:tag] (reference model.py:1837)."""
        suffix = f":{tag}" if tag else ""
        return f"{project}@{uid}#{iteration}{suffix}"

    @staticmethod
    def parse_uri(uri: str) -> tuple:
        """Parse <project>@<uid>#<iteration>[:tag] back to its parts
        (reference model.py:1844)."""
        import re

        match = re.match(
            r"^(?P<project>[^@]+)@(?P<uid>[^#]+)#(?P<iteration>[^:]+)"
            r"(:(?P<tag>.+))?$", uri)
        if not match:
            raise ValueError(
                "uri not in supported format "
                "<project>@<uid>#<iteration>[:tag]")
        groups = match.groupdict()
        return (groups["project"], groups["uid"], groups["iteration"],
                groups["tag"] or "")

    def output(self, key: str):
        """Return a result value or artifact uri by key."""
        if self.status.results and key in self.status.results:
            return self.status.results[key]
        return (self.status.artifact_uris or {}).get(key)

    @property
    def outputs(self) -> dict:
        out = dict(self.status.results or {})
        out.update(self.status.artifact_uris or {})
        return out

    def artifact(self, key: str):
        """Return a DataItem for a named output artifact."""
        uri = (self.status.artifact_uris or {}).get(key)
        if not uri:
            return None
        from .datastore import store_manager

        return store_manager.object(url=uri)

    def _run_db(self):
        if self._db is None:
            from .db import get_run_db

            self._db = get_run_db()
        return self._db

    def refresh(self) -> "RunObject":
        db = self._run_db()
        updated = db.read_run(
            uid=self.metadata.uid, project=self.metadata.project,
            iter=self.metadata.iteration,
        )
        if updated:
            self.status = RunStatus.from_dict(updated.get("status", {}))
        return self

    def logs(self, watch: bool = True, db=None, offset: int = 0) -> str:
        """Fetch (and optionally tail) run logs (reference model.py:1750)."""
        db = db or self._run_db()
        state, text = db.watch_log(
            self.metadata.uid, self.metadata.project, watch=watch, offset=offset
        )
        if state:
            self.status.state = state
        return state

    def wait_for_completion(self, sleep: float = 1.0, timeout: float = 600,
                            raise_on_failure: bool = True) -> str:
        """Poll the DB until the run reaches a terminal state (model.py:1767)."""
        start = time.monotonic()
        while True:
            self.refresh()
            if self.status.state in RunStates.terminal_states():
                break
            if time.monotonic() - start > timeout:
                raise TimeoutError(
                    f"run {self.metadata.uid} did not complete within {timeout}s"
                )
            time.sleep(sleep)
        if raise_on_failure and self.status.state != RunStates.completed:
            raise RuntimeError(
                f"task {self.metadata.name} did not complete "
                f"(state={self.status.state})"
            )
        return self.status.state

    def show(self):
        """Notebook-rich run view (HTML detail card via render.py);
        plain-log summary outside IPython (reference model.py show)."""
        from .render import run_to_html
        from .utils import logger

        html = run_to_html(self.to_dict(), display=True)
        if not html:
            logger.info(
                "run summary", name=self.metadata.name,
                uid=self.metadata.uid, state=self.status.state,
                results=self.status.results,
                artifacts=list((self.status.artifact_uris or {}).keys()),
            )

    def _repr_html_(self) -> str:
        from .render import run_to_html

        return run_to_html(self.to_dict(), display=False)

    def to_dict(self, exclude=None):
        out = super().to_dict(exclude)
        out["kind"] = self.kind
        return out


def new_task(name: str = "", project: str = "", handler=None, params: dict | None = None,
             hyper_params: dict | None = None, param_file: str = "", selector: str = "",
             hyper_param_options: HyperParamOptions | dict | None = None,
             inputs: dict | None = None, outputs: list | None = None,
             in_path: str = "", out_path: str = "", artifact_path: str = "",
             secrets: list | None = None, base: RunTemplate | None = None,
             returns: list | None = None) -> RunTemplate:
    """Create a RunTemplate (reference model.py new_task)."""
    if base:
        run = deepcopy(base)
    else:
        run = RunTemplate()
    run.metadata.name = name or run.metadata.name
    run.metadata.project = project or run.metadata.project
    spec = run.spec
    spec.handler = handler or spec.handler
    spec.parameters = params or spec.parameters
    spec.hyperparams = hyper_params or spec.hyperparams
    if isinstance(hyper_param_options, dict):
        hyper_param_options = HyperParamOptions.from_dict(hyper_param_options)
    spec.hyper_param_options = hyper_param_options or spec.hyper_param_options
    if param_file:
        spec.hyper_param_options.param_file = param_file
    if selector:
        spec.hyper_param_options.selector = selector
    spec.inputs = inputs or spec.inputs
    spec.outputs = outputs or spec.outputs
    spec.returns = returns or spec.returns
    spec.input_path = in_path or spec.input_path
    spec.output_path = artifact_path or out_path or spec.output_path
    spec.secret_sources = secrets or spec.secret_sources
    return run


NewTask = new_task


class RunOutputs:
    """Convenience dict-like view on run outputs used by pipelines."""

    def __init__(self, run: RunObject):
        self._run = run

    def __getitem__(self, key):
        value = self._run.output(key)
        if value is None:
            raise KeyError(key)
        return value

    def keys(self):
        return self._run.outputs.keys()
