from .pipelines import (  # noqa: F401
    PipelineContext,
    PipelineStep,
    load_and_run,
    pipeline_context,
)
from .project import (  # noqa: F401
    MlrunProject,
    ProjectMetadata,
    ProjectSpec,
    get_current_project,
    get_or_create_project,
    load_project,
    new_project,
)


def run_function(function, **kwargs):
    """Module-level run_function delegating to the active project
    (reference mlrun/projects/__init__.py)."""
    from .project import get_current_project

    return get_current_project().run_function(function, **kwargs)


def build_function(function, **kwargs):
    return get_current_project().build_function(function, **kwargs)


def deploy_function(function, **kwargs):
    return get_current_project().deploy_function(function, **kwargs)
