"""Projects (reference analog: mlrun/projects/project.py — new_project :122,
load_project :290, get_or_create_project :435, MlrunProject :1136 with
run() :3055, run_function() :3386, build_function :3499, deploy_function :3738,
log_artifact/dataset/model :1559-1735)."""

from __future__ import annotations

import glob
import os
import shutil
import subprocess
import typing
import warnings

import yaml

from ..artifacts import ArtifactManager, ArtifactProducer
from ..config import mlconf
from ..model import ModelObj
from ..utils import generate_uid, logger, normalize_name, now_iso
from .pipelines import (
    PipelineContext,
    _PipelineRunStatus,
    get_workflow_engine,
    pipeline_context,
)

_current_project = None


class ProjectMetadata(ModelObj):
    _dict_fields = ["name", "created", "labels", "annotations"]

    def __init__(self, name=None, created=None, labels=None, annotations=None):
        self.name = name
        self.created = created
        self.labels = labels or {}
        self.annotations = annotations or {}


class ProjectSpec(ModelObj):
    _dict_fields = [
        "description", "params", "functions", "workflows", "artifacts",
        "source", "context", "subpath", "origin_url", "goals", "owner",
        "artifact_path", "conda", "default_image", "build",
        "default_requirements",
    ]

    def __init__(self, description=None, params=None, functions=None,
                 workflows=None, artifacts=None, source=None, context=None,
                 subpath=None, origin_url=None, goals=None, owner=None,
                 artifact_path=None, conda=None, default_image=None,
                 build=None, default_requirements=None):
        self.description = description
        self.params = params or {}
        self.functions = functions or []   # [{name, spec|url, kind, image...}]
        self.workflows = workflows or []   # [{name, path, handler, engine}]
        self.artifacts = artifacts or []
        self.source = source
        self.context = context or "./"
        self.subpath = subpath
        self.origin_url = origin_url
        self.goals = goals
        self.owner = owner
        self.artifact_path = artifact_path
        self.conda = conda
        self.default_image = default_image
        self.build = build
        self.default_requirements = default_requirements or []

    def get_workflow(self, name: str) -> dict | None:
        for workflow in self.workflows:
            if workflow.get("name") == name:
                return workflow
        return None

    def set_workflow(self, name: str, workflow: dict):
        self.workflows = [w for w in self.workflows
                          if w.get("name") != name] + [workflow]


class ProjectStatus(ModelObj):
    _dict_fields = ["state"]

    def __init__(self, state=None):
        self.state = state


class MlrunProject(ModelObj):
    kind = "project"
    _dict_fields = ["kind", "metadata", "spec", "status"]
    _nested_fields = {"metadata": ProjectMetadata, "spec": ProjectSpec,
                      "status": ProjectStatus}

    def __init__(self, metadata=None, spec=None, status=None):
        self.metadata = metadata or ProjectMetadata()
        self.spec = spec or ProjectSpec()
        self.status = status or ProjectStatus()
        self._function_objects: dict[str, typing.Any] = {}
        self._db = None
        self._artifact_manager = None

    # -- identity ----------------------------------------------------------
    @property
    def name(self) -> str:
        return self.metadata.name

    @property
    def artifact_path(self) -> str:
        return self.spec.artifact_path or mlconf.resolve_artifact_path(
            self.name)

    def _get_db(self):
        if self._db is None:
            from ..db import get_run_db

            self._db = get_run_db()
        return self._db

    def get_param(self, key: str, default=None):
        return self.spec.params.get(key, default)

    # -- functions ---------------------------------------------------------
    def set_function(self, func=None, name: str = "", kind: str = "",
                     image: str = "", handler: str = "", with_repo=None,
                     tag: str = "", requirements: list | None = None):
        """Register a function in the project (reference project.py
        set_function). ``func`` may be a runtime object, a file path, or a
        db:// / hub:// url."""
        from ..run import code_to_function, import_function, new_function
        from ..runtimes.base import BaseRuntime

        if isinstance(func, BaseRuntime):
            function = func
            name = name or function.metadata.name
        elif isinstance(func, str) and (
                func.startswith("db://") or func.startswith("hub://")
                or func.endswith(".yaml")):
            function = import_function(func, project=self.name)
            name = name or function.metadata.name
        elif isinstance(func, str) and func.endswith(".py"):
            path = func if os.path.isabs(func) else os.path.join(
                self.spec.context or "./", func)
            function = code_to_function(
                name=name or os.path.splitext(os.path.basename(func))[0],
                project=self.name, filename=path, handler=handler,
                kind=kind or "job", image=image,
                requirements=requirements)
        elif func is None and handler:
            function = new_function(name=name or handler, kind=kind or "local",
                                    project=self.name)
            function.spec.default_handler = handler
        else:
            raise ValueError(f"unsupported function source {func!r}")
        function.metadata.project = self.name
        function.metadata.name = normalize_name(name or
                                                function.metadata.name)
        if image:
            function.spec.image = image
        if kind and function.kind != kind:
            pass  # kind conversion is explicit via to_job etc.
        if tag:
            function.metadata.tag = tag
        self._function_objects[function.metadata.name] = function
        entry = {"name": function.metadata.name, "kind": function.kind}
        self.spec.functions = [
            f for f in self.spec.functions
            if f.get("name") != function.metadata.name
        ] + [entry]
        return function

    def get_function(self, key: str, sync: bool = False, enrich: bool = False,
                     ignore_cache: bool = False):
        if key in self._function_objects and not ignore_cache:
            return self._function_objects[key]
        from ..run import import_function

        function = import_function(f"db://{self.name}/{key}")
        self._function_objects[key] = function
        return function

    def get_function_names(self) -> list[str]:
        return [f.get("name") for f in self.spec.functions]

    def remove_function(self, name: str):
        self._function_objects.pop(name, None)
        self.spec.functions = [f for f in self.spec.functions
                               if f.get("name") != name]

    def sync_functions(self, names: list | None = None, always: bool = True,
                       save: bool = False):
        for entry in self.spec.functions:
            name = entry.get("name")
            if names and name not in names:
                continue
            if name not in self._function_objects or always:
                try:
                    self.get_function(name, ignore_cache=True)
                except Exception as exc:  # noqa: BLE001
                    logger.warning("could not sync function", name=name,
                                   error=str(exc))
        if save:
            self.save()
        return self._function_objects

    # -- run / build / deploy ---------------------------------------------
    def run_function(self, function, handler: str = "", name: str = "",
                     params: dict | None = None, hyperparams: dict | None = None,
                     hyper_param_options=None, inputs: dict | None = None,
                     outputs: list | None = None, workdir: str = "",
                     labels: dict | None = None, base_task=None, watch=True,
                     local: bool | None = None, schedule=None,
                     artifact_path: str = "", notifications=None,
                     returns: list | None = None, builder_env=None):
        """Run a registered or given function (reference project.py:3386,
        module-level run_function)."""
        function = self._resolve_function(function)
        context = pipeline_context()
        if context is not None:
            # inside a workflow file: create a step and run via the engine
            step = function.as_step(
                runspec=base_task, handler=handler, name=name,
                project=self.name, params=params, inputs=inputs,
                outputs=outputs, artifact_path=artifact_path,
                hyperparams=hyperparams,
                hyper_param_options=hyper_param_options, returns=returns)
            engine = getattr(context, "engine", "local")
            if engine == "kfp":
                # kfp tracing: emit a container op, do NOT execute
                from .pipelines import _KFPRunner

                return _KFPRunner._step_to_container_op(
                    step, context.artifact_path)
            if engine == "kfp-compile":
                # kfp-free IR compilation: record, do NOT execute
                context.steps.append(step)
                return step
            run = step.run(context)
            context.runs.append(run)
            return step
        run = function.run(
            base_task, handler=handler, name=name, params=params,
            hyperparams=hyperparams, hyper_param_options=hyper_param_options,
            inputs=inputs, artifact_path=artifact_path or self.artifact_path,
            watch=watch, schedule=schedule, notifications=notifications,
            returns=returns,
            local=local if local is not None else not mlconf.is_remote)
        return run

    def build_function(self, function, with_tpu: bool = False,
                       skip_deployed: bool = False, **kwargs):
        function = self._resolve_function(function)
        if hasattr(function, "deploy"):
            function.deploy(skip_deployed=skip_deployed, with_tpu=with_tpu)
        return function

    def deploy_function(self, function, models: list | None = None,
                        env: dict | None = None, tag: str = "", **kwargs):
        function = self._resolve_function(function)
        if env:
            function.set_envs(env)
        if models:
            for model in models:
                function.add_model(**model)
        address = function.deploy(project=self.name, tag=tag)
        return function, address

    def _resolve_function(self, function):
        from ..runtimes.base import BaseRuntime

        if isinstance(function, BaseRuntime):
            return function
        if isinstance(function, str):
            return self.get_function(function)
        raise ValueError(f"unsupported function arg {function!r}")

    # -- artifacts ---------------------------------------------------------
    def _producer(self) -> ArtifactProducer:
        return ArtifactProducer("project", self.name, self.name,
                                uid=generate_uid())

    def _get_artifact_manager(self) -> ArtifactManager:
        if self._artifact_manager is None:
            self._artifact_manager = ArtifactManager(db=self._get_db())
        return self._artifact_manager

    def log_artifact(self, item, body=None, tag: str = "", local_path: str = "",
                     artifact_path: str = "", format: str | None = None,
                     upload: bool | None = None, labels: dict | None = None,
                     target_path: str = "", **kwargs):
        manager = self._get_artifact_manager()
        artifact = manager.log_artifact(
            self._producer(), item, body=body, tag=tag, local_path=local_path,
            artifact_path=artifact_path or self.artifact_path, format=format,
            upload=upload, labels=labels, target_path=target_path, **kwargs)
        return artifact

    def log_dataset(self, key, df, tag="", local_path="", artifact_path="",
                    upload=None, labels=None, format="parquet", preview=None,
                    stats=None, target_path="", **kwargs):
        from ..artifacts import DatasetArtifact

        ds = DatasetArtifact(key, df=df, preview=preview, format=format,
                             stats=stats, target_path=target_path)
        return self.log_artifact(
            ds, tag=tag, local_path=local_path,
            artifact_path=artifact_path or self.artifact_path,
            upload=upload, labels=labels, **kwargs)

    def log_model(self, key, body=None, framework="", tag="", model_dir="",
                  model_file="", metrics=None, parameters=None,
                  artifact_path="", upload=None, labels=None, inputs=None,
                  outputs=None, extra_data=None, algorithm="", **kwargs):
        from ..artifacts import ModelArtifact

        model = ModelArtifact(
            key, body=body, model_file=model_file, model_dir=model_dir,
            metrics=metrics, parameters=parameters, inputs=inputs,
            outputs=outputs, framework=framework, algorithm=algorithm,
            extra_data=extra_data)
        return self.log_artifact(
            model, tag=tag, artifact_path=artifact_path or self.artifact_path,
            upload=upload, labels=labels, **kwargs)

    def get_artifact(self, key: str, tag: str = "", iter: int | None = None):
        db = self._get_db()
        struct = db.read_artifact(key, tag=tag or "latest", iter=iter,
                                  project=self.name)
        from ..artifacts import dict_to_artifact

        return dict_to_artifact(struct)

    def list_artifacts(self, name="", tag=None, labels=None, kind=None):
        return self._get_db().list_artifacts(
            name=name, project=self.name, tag=tag, labels=labels, kind=kind)

    def list_runs(self, name="", uid=None, labels=None, state="", last=0):
        return self._get_db().list_runs(
            name=name, uid=uid, project=self.name, labels=labels,
            state=state, last=last)

    def list_functions(self, name="", tag="", labels=None):
        return self._get_db().list_functions(
            name=name, project=self.name, tag=tag, labels=labels)

    def list_models(self, name="", tag=None, labels=None):
        return self._get_db().list_artifacts(
            name=name, project=self.name, tag=tag, labels=labels,
            kind="model")

    # -- source ------------------------------------------------------------
    def set_source(self, source: str = "", pull_at_runtime: bool = False,
                   workdir: str = ""):
        self.spec.source = source
        if workdir:
            self.spec.subpath = workdir
        return self

    def set_secrets(self, secrets: dict | None = None, file_path: str = ""):
        """Store project secrets (local mode: env process-level)."""
        import os as _os

        secrets = dict(secrets or {})
        if file_path:
            with open(file_path) as fp:
                for line in fp:
                    line = line.strip()
                    if line and not line.startswith("#") and "=" in line:
                        key, value = line.split("=", 1)
                        secrets[key.strip()] = value.strip()
        for key, value in secrets.items():
            _os.environ[f"MLT_SECRET_{key}"] = str(value)
        return self

    def get_secret(self, key: str, default=None):
        import os as _os

        return _os.environ.get(f"MLT_SECRET_{key}",
                               _os.environ.get(key, default))

    # -- workflows ---------------------------------------------------------
    def set_workflow(self, name: str, workflow_path: str, handler: str = "",
                     engine: str = "", **kwargs):
        self.spec.set_workflow(name, {
            "name": name, "path": workflow_path, "handler": handler,
            "engine": engine, **kwargs})
        return self

    def run(self, name: str = "", workflow_path: str = "",
            arguments: dict | None = None, artifact_path: str = "",
            workflow_handler=None, namespace=None, sync: bool = False,
            watch: bool = False, dirty: bool = False, engine: str = "",
            local: bool | None = None, schedule=None,
            timeout: float | None = None) -> _PipelineRunStatus:
        """Run a named or ad-hoc workflow (reference project.py:3055)."""
        workflow = None
        if name:
            workflow = self.spec.get_workflow(name)
            if workflow is None and not workflow_path and not workflow_handler:
                raise ValueError(f"workflow '{name}' is not defined")
        workflow = dict(workflow or {})
        if workflow_path:
            workflow["path"] = workflow_path
        engine = engine or workflow.get("engine") or "local"
        if local is None:
            local = engine == "local" and not mlconf.is_remote
        if sync:
            self.sync_functions()
        runner = get_workflow_engine(engine, local=local)
        status = runner.run(
            self, workflow, name=name, workflow_handler=workflow_handler,
            artifact_path=artifact_path or self.artifact_path,
            args=arguments, local=local, watch=watch)
        if watch and engine != "local":
            status.wait_for_completion(timeout=timeout or 3600)
        return status

    # -- persistence -------------------------------------------------------
    # -- reference-contract parity (mlrun/projects/project.py) -------------
    # spec/metadata bridges: ported user code reads these directly off the
    # project object
    @property
    def description(self) -> str:
        return self.spec.description or ""

    @description.setter
    def description(self, value: str):
        self.spec.description = value

    @property
    def params(self) -> dict:
        return self.spec.params

    @params.setter
    def params(self, value: dict):
        self.spec.params = value or {}

    @property
    def source(self) -> str:
        return self.spec.source or ""

    @source.setter
    def source(self, value: str):
        self.spec.source = value

    @property
    def context(self) -> str:
        return self.spec.context or "./"

    @property
    def mountdir(self) -> str:
        return getattr(self.spec, "mountdir", "") or ""

    @property
    def workflows(self) -> list:
        return self.spec.workflows

    @property
    def artifacts(self) -> list:
        return self.spec.artifacts

    @property
    def default_image(self) -> str:
        return self.spec.default_image or ""

    def set_default_image(self, image: str):
        self.spec.default_image = image

    @property
    def notifiers(self):
        from ..utils.notifications import NotificationPusher

        return NotificationPusher([])

    def with_secrets(self, kind: str = "env", source=None) -> "MlrunProject":
        """Reference with_secrets: env-file path or dict of values."""
        if isinstance(source, dict):
            self.set_secrets(source)
        elif isinstance(source, str):
            self.set_secrets(file_path=source)
        return self

    # build
    def build_config(self, image: str = "", set_as_default: bool = False,
                     base_image: str = "", commands: list | None = None,
                     requirements: list | None = None, **kwargs):
        """Record the project-level build spec (reference build_config)."""
        from ..model import ImageBuilder

        build = self.spec.build or ImageBuilder()
        if isinstance(build, dict):
            build = ImageBuilder.from_dict(build)
        build.image = image or build.image
        build.base_image = base_image or build.base_image
        if commands:
            build.commands = list(build.commands or []) + [
                c for c in commands if c not in (build.commands or [])]
        if requirements:
            build.requirements = list(build.requirements or []) + [
                q for q in requirements
                if q not in (build.requirements or [])]
        self.spec.build = build
        if set_as_default and image:
            self.set_default_image(image)
        return build

    def build_image(self, image: str = "", base_image: str = "",
                    commands: list | None = None,
                    requirements: list | None = None,
                    set_as_default: bool = True, with_tpu: bool = False):
        """Build the project image from the recorded/passed build config
        (reference build_image — backed by the build service)."""
        from ..run import new_function

        build = self.build_config(image=image, base_image=base_image,
                                  commands=commands,
                                  requirements=requirements)
        fn = new_function(f"{self.name}-image", project=self.name,
                          kind="job", image=build.image or "")
        fn.spec.build = build
        deployed = fn.deploy(watch=True, with_tpu=with_tpu)
        if deployed and set_as_default and fn.spec.image:
            self.set_default_image(fn.spec.image)
        return deployed

    # artifacts / store
    def get_artifact_uri(self, key: str, category: str = "artifact",
                         tag: str = "", iter: int | None = None) -> str:
        """store://<category>s/<project>/<key>[:tag] (reference
        get_artifact_uri)."""
        uri = f"store://{category}s/{self.name}/{key}"
        if iter is not None:
            uri = f"{uri}#{iter}"
        if tag:
            uri = f"{uri}:{tag}"
        return uri

    def get_store_resource(self, uri: str):
        from ..datastore import store_manager

        return store_manager.object(url=uri, project=self.name)

    def get_item_absolute_path(self, url: str) -> str:
        """Resolve a context-relative path against the project context
        (reference get_item_absolute_path)."""
        if "://" in url or os.path.isabs(url):
            return url
        return os.path.join(self.spec.context or "./",
                            self.spec.subpath or "", url)

    def set_artifact(self, key: str, artifact=None, target_path: str = "",
                     tag: str = ""):
        """Register an artifact in the project spec (imported on load;
        reference set_artifact)."""
        entry = {"key": key, "target_path": target_path, "tag": tag}
        if isinstance(artifact, dict):
            entry.update(artifact)
        elif artifact is not None:
            entry.update(getattr(artifact, "to_dict", lambda: {})())
        self.spec.artifacts = [a for a in self.spec.artifacts
                               if a.get("key") != key] + [entry]

    def import_artifact(self, item_path: str, new_key: str = ""):
        """Load an exported artifact spec (yaml/json) and log it under
        this project (reference import_artifact)."""
        import yaml

        from ..artifacts.manager import dict_to_artifact

        with open(item_path) as f:
            struct = yaml.safe_load(f)
        artifact = dict_to_artifact(struct)
        if new_key:
            artifact.metadata.key = new_key
        return self.log_artifact(artifact)

    def delete_artifact(self, key: str, tag: str = ""):
        self._get_db().del_artifact(key, tag=tag, project=self.name)

    # datastore profiles
    def register_datastore_profile(self, profile,
                                   private: dict | None = None):
        struct = profile if isinstance(profile, dict) else profile.to_dict()
        self._get_db().store_datastore_profile(struct, project=self.name,
                                               private=private)

    def get_datastore_profile(self, name: str):
        return self._get_db().get_datastore_profile(name, project=self.name)

    def list_datastore_profiles(self) -> list:
        return self._get_db().list_datastore_profiles(project=self.name)

    def delete_datastore_profile(self, name: str):
        self._get_db().delete_datastore_profile(name, project=self.name)

    # alerts
    def store_alert_config(self, name: str, config: dict):
        return self._get_db().store_alert_config(name, config,
                                                 project=self.name)

    def get_alert_config(self, name: str) -> dict:
        return self._get_db().get_alert_config(name, project=self.name)

    def list_alerts_configs(self) -> list:
        return self._get_db().list_alert_configs(project=self.name)

    def delete_alert_config(self, name: str):
        self._get_db().delete_alert_config(name, project=self.name)

    def get_alert_template(self, name: str) -> dict:
        """A builtin alert template (reference get_alert_template)."""
        from ..service.alerts import get_alert_template

        return get_alert_template(name)

    def list_alert_templates(self) -> list:
        from ..service.alerts import list_alert_templates

        return list_alert_templates()

    def create_alert_from_template(self, name: str, template: str,
                                   entity_id: str = "*",
                                   notifications: list | None = None):
        """Instantiate a builtin template as this project's alert config
        (the reference's template->config flow)."""
        config = self.get_alert_template(template)
        config["name"] = name
        config["entity_id"] = entity_id
        if notifications:
            config["notifications"] = notifications
        self.store_alert_config(name, config)
        return config

    def reset_alert_config(self, name: str):
        """Clear an alert's silencing window + fired state (reference
        reset_alert_config)."""
        alert = self.get_alert_config(name)
        alert["silence_until"] = ""
        alert.pop("last_fired", None)
        self.store_alert_config(name, alert)

    # model monitoring (reference enable/disable_model_monitoring +
    # set_model_monitoring_function; apps are MonitoringApplicationBase
    # subclasses driven by the windowed controller)
    def enable_model_monitoring(self, default_apps: bool = True,
                                **kwargs) -> "MlrunProject":
        self.spec.params["model_monitoring_enabled"] = True
        if default_apps:
            apps = self.spec.params.setdefault(
                "model_monitoring_apps", [])
            for default in ("HistogramDataDriftApplication",
                            "LatencyApplication"):
                if default not in apps:
                    apps.append(default)
        return self

    def disable_model_monitoring(self) -> "MlrunProject":
        self.spec.params["model_monitoring_enabled"] = False
        return self

    def set_model_monitoring_function(self, name: str,
                                      application_class: str = "",
                                      **kwargs):
        apps = self.spec.params.setdefault("model_monitoring_apps", [])
        entry = application_class or name
        if entry not in apps:
            apps.append(entry)
        return entry

    def list_model_monitoring_functions(self) -> list:
        return list(self.spec.params.get("model_monitoring_apps", []))

    def remove_model_monitoring_function(self, name: str):
        apps = self.spec.params.get("model_monitoring_apps", [])
        if name in apps:
            apps.remove(name)

    # api gateways
    def list_api_gateways(self) -> list:
        db = self._get_db()
        lister = getattr(db, "list_api_gateways", None)
        if lister:
            return lister(self.name)
        return [f for f in db.list_functions(project=self.name)
                if f.get("kind") == "api-gateway"]

    # git remotes (reference create_remote/set_remote/remove_remote/
    # pull/push over the project context's git repo)
    def _git(self, *args, check: bool = True):
        import subprocess

        return subprocess.run(["git", "-C", self.spec.context or "./",
                               *args], check=check, capture_output=True,
                              text=True)

    def create_remote(self, url: str, name: str = "origin",
                      branch: str = ""):
        self._git("remote", "add", name, url)
        self.spec.origin_url = url

    def set_remote(self, url: str, name: str = "origin", overwrite=True):
        existing = self._git("remote", check=False).stdout.split()
        if name in existing:
            if not overwrite:
                raise ValueError(f"remote {name} exists")
            self._git("remote", "set-url", name, url)
        else:
            self._git("remote", "add", name, url)
        self.spec.origin_url = url

    def remove_remote(self, name: str):
        self._git("remote", "remove", name)

    def pull(self, remote: str = "origin", branch: str = ""):
        self._git("pull", remote, *( [branch] if branch else [] ))

    def push(self, branch: str, message: str = "", update: bool = True,
             remote: str = "origin", add: list | None = None):
        if add:
            self._git("add", *add)
        if update:
            self.save()
            self._git("add", "project.yaml", check=False)
        if message:
            self._git("commit", "-m", message, check=False)
        self._git("push", remote, branch)

    # lifecycle
    def save_to_db(self, store: bool = True):
        return self.save(store=store)

    def save_workflow(self, name: str, target: str, artifact_path: str = "",
                      ttl=None):
        """Export a named workflow spec to a file (reference
        save_workflow)."""
        import yaml

        workflow = self.spec.get_workflow(name)
        if workflow is None:
            raise ValueError(f"workflow {name} not found in project spec")
        with open(target, "w") as f:
            yaml.safe_dump(dict(workflow), f)

    def reload(self, sync: bool = False, context: str = "",
               ) -> "MlrunProject":
        """Re-load the project from its context dir (reference reload)."""
        project = load_project(context=context or self.spec.context or "./",
                               name=self.name, save=False,
                               sync_functions=sync)
        self.spec = project.spec
        self.status = project.status
        return self

    def setup(self, save: bool = True) -> "MlrunProject":
        """Run the project_setup.py hook from the context dir (reference
        setup): a `setup(project) -> project` function customizing the
        loaded project."""
        setup_file = os.path.join(self.spec.context or "./",
                                  self.spec.subpath or "",
                                  "project_setup.py")
        if not os.path.isfile(setup_file):
            return self
        import importlib.util

        module_spec = importlib.util.spec_from_file_location(
            "project_setup", setup_file)
        module = importlib.util.module_from_spec(module_spec)
        module_spec.loader.exec_module(module)
        if hasattr(module, "setup"):
            project = module.setup(self)
            if project is not None and save:
                project.save()
            return project or self
        return self

    def get_function_objects(self) -> dict:
        """Initialized function objects by name (reference
        get_function_objects)."""
        self.sync_functions()
        return dict(self._function_objects)

    def get_run_status(self, run, timeout: float = 600,
                       expected_statuses=None):
        """Wait for a workflow/pipeline run and return it (reference
        get_run_status)."""
        wait = getattr(run, "wait_for_completion", None)
        if wait:
            wait(timeout=timeout)
        return run

    def save(self, filepath: str = "", store: bool = True):
        self.metadata.created = self.metadata.created or now_iso()
        filepath = filepath or os.path.join(
            self.spec.context or "./", "project.yaml")
        os.makedirs(os.path.dirname(os.path.abspath(filepath)), exist_ok=True)
        with open(filepath, "w") as fp:
            fp.write(self.to_yaml())
        if store:
            self._get_db().store_project(self.name, self.to_dict())
        return self

    def export(self, filepath: str = ""):
        return self.save(filepath, store=False)

    def register_artifacts(self):
        for entry in self.spec.artifacts:
            try:
                self.log_artifact(
                    entry.get("key"),
                    target_path=entry.get("target_path") or entry.get("url"),
                    kind=entry.get("kind", "artifact"), upload=False)
            except Exception as exc:  # noqa: BLE001
                logger.warning("failed to register artifact",
                               key=entry.get("key"), error=str(exc))


def new_project(name: str, context: str = "./", init_git: bool = False,
                user_project: bool = False, remote: str | None = None,
                from_template: str | None = None, secrets: dict | None = None,
                description: str = "", subpath: str = "",
                save: bool = True, overwrite: bool = False,
                parameters: dict | None = None,
                default_image: str | None = None) -> MlrunProject:
    """Create a new project (reference project.py:122)."""
    global _current_project

    name = normalize_name(name)
    if user_project:
        user = os.environ.get("USER", os.environ.get("USERNAME", "user"))
        name = f"{name}-{user.lower()}"
    project = MlrunProject(
        metadata=ProjectMetadata(name=name),
        spec=ProjectSpec(description=description, context=context,
                         subpath=subpath, params=parameters or {},
                         default_image=default_image))
    if from_template:
        loaded = _load_project_file(from_template)
        project.spec.functions = loaded.spec.functions
        project.spec.workflows = loaded.spec.workflows
        project.spec.artifacts = loaded.spec.artifacts
    if init_git:
        try:
            subprocess.run(["git", "init", context], capture_output=True,
                           check=False)
        except OSError:
            pass
    if secrets:
        project.set_secrets(secrets)
    if save:
        project.save()
    _current_project = project
    return project


def load_project(context: str = "./", url: str | None = None,
                 name: str | None = None, secrets: dict | None = None,
                 init_git: bool = False, subpath: str = "",
                 clone: bool = False, user_project: bool = False,
                 save: bool = True, sync_functions: bool = False) -> MlrunProject:
    """Load a project from context dir / git url / yaml (reference :290)."""
    global _current_project

    if url and (url.endswith(".git") or url.startswith("git://")):
        if clone and os.path.isdir(context) and os.listdir(context):
            shutil.rmtree(context)
        subprocess.run(["git", "clone", url.replace("git://", "https://"),
                        context], check=True, capture_output=True)
    project_file = url if url and url.endswith((".yaml", ".yml")) else \
        os.path.join(context, subpath or "", "project.yaml")
    if os.path.isfile(project_file):
        project = _load_project_file(project_file)
    else:
        if not name:
            raise ValueError(
                f"project file not found at {project_file} and no name given")
        project = MlrunProject(metadata=ProjectMetadata(name=name))
    if name:
        project.metadata.name = normalize_name(name)
    project.spec.context = context
    project.spec.subpath = subpath or project.spec.subpath
    if secrets:
        project.set_secrets(secrets)
    if save:
        project.save()
    if sync_functions:
        project.sync_functions()
    _current_project = project
    return project


def get_or_create_project(name: str, context: str = "./",
                          url: str | None = None, secrets: dict | None = None,
                          init_git: bool = False, subpath: str = "",
                          clone: bool = False, user_project: bool = False,
                          from_template: str | None = None,
                          save: bool = True,
                          parameters: dict | None = None) -> MlrunProject:
    """Load from the DB if it exists, else create (reference :435)."""
    global _current_project

    from ..db import get_run_db

    name_n = normalize_name(name)
    try:
        struct = get_run_db().get_project(name_n)
    except Exception:  # noqa: BLE001
        struct = None
    if struct:
        project = MlrunProject.from_dict(struct)
        project.spec.context = context
        _current_project = project
        return project
    try:
        return load_project(context=context, url=url, name=name_n,
                            secrets=secrets, init_git=init_git,
                            subpath=subpath, clone=clone,
                            user_project=user_project, save=save)
    except (ValueError, FileNotFoundError, subprocess.CalledProcessError):
        return new_project(name_n, context=context, init_git=init_git,
                           user_project=user_project, secrets=secrets,
                           from_template=from_template, save=save,
                           parameters=parameters)


def get_current_project(silent: bool = False) -> MlrunProject | None:
    if _current_project is None and not silent:
        raise ValueError("no active project (use new/load/get_or_create)")
    return _current_project


def _load_project_file(path: str) -> MlrunProject:
    with open(path) as fp:
        struct = yaml.safe_load(fp)
    return MlrunProject.from_dict(struct or {})
