"""Workflow engines (reference analog: mlrun/projects/pipelines.py —
_KFPRunner :542, _LocalRunner :673, _RemoteRunner :756, load_and_run :987).

The local engine executes the step DAG in-process in topological order; the
remote engine submits the workflow to the service, which runs it in a runner
job (reference server/api/crud/workflows.py:31). A KFP adapter can compile the
same DAG when kfp is importable.
"""

from __future__ import annotations

import importlib.util
import os
import threading
import time
import uuid
from typing import Callable, Optional

from ..common.runtimes_constants import RunStates
from ..config import mlconf
from ..model import RunObject
from ..utils import generate_uid, logger, now_iso


class PipelineStep:
    """A deferred function invocation inside a workflow (fn.as_step)."""

    def __init__(self, function=None, runspec=None, handler=None, name="",
                 project="", params=None, inputs=None, outputs=None,
                 artifact_path="", image="", returns=None, **kwargs):
        self.function = function
        self.runspec = runspec
        self.handler = handler
        self.name = name or (function.metadata.name if function else "step")
        self.project = project
        self.params = params or {}
        self.inputs = inputs or {}
        self.outputs = outputs or []
        self.returns = returns
        self.artifact_path = artifact_path
        self.image = image
        self.kwargs = kwargs
        self.after_steps: list["PipelineStep"] = []
        self._run: Optional[RunObject] = None

    def after(self, *steps: "PipelineStep") -> "PipelineStep":
        self.after_steps.extend(steps)
        return self

    @property
    def outputs_resolved(self) -> dict:
        if self._run is None:
            return {}
        return self._run.outputs

    def output(self, key: str):
        """Reference a named output of this step (resolved lazily when the
        local engine executes)."""
        return _StepOutput(self, key)

    def run(self, context: "PipelineContext") -> RunObject:
        params = {
            key: (value.resolve() if isinstance(value, _StepOutput) else value)
            for key, value in self.params.items()
        }
        inputs = {
            key: (value.resolve() if isinstance(value, _StepOutput) else value)
            for key, value in self.inputs.items()
        }
        function = self.function
        if self.image:
            function.spec.image = self.image
        run = function.run(
            self.runspec, handler=self.handler, name=self.name,
            project=self.project or context.project_name, params=params,
            inputs=inputs, artifact_path=self.artifact_path
            or context.artifact_path, local=context.local,
            watch=context.watch, returns=self.returns, **self.kwargs)
        self._run = run
        return run


class _StepOutput:
    def __init__(self, step: PipelineStep, key: str):
        self.step = step
        self.key = key

    def resolve(self):
        if self.step._run is None:
            raise RuntimeError(
                f"step '{self.step.name}' has not executed yet")
        value = self.step._run.output(self.key)
        if value is None:
            raise KeyError(
                f"step '{self.step.name}' has no output '{self.key}'")
        return value


class PipelineContext:
    """State for one workflow execution.

    ``engine="kfp-compile"`` traces the workflow without executing it:
    ``run_function`` records each step in :attr:`steps` and returns the
    step object so workflow files can keep chaining ``.after()`` /
    ``.output()`` exactly as they do under the local engine.
    """

    def __init__(self, project=None, workflow_name: str = "", local=True,
                 watch=False, artifact_path: str = "", args: dict | None = None,
                 engine: str = "local"):
        self.steps: list[PipelineStep] = []
        self.project = project
        self.project_name = project.name if project is not None else ""
        self.workflow_name = workflow_name
        self.local = local
        self.watch = watch
        self.artifact_path = artifact_path
        self.args = args or {}
        self.engine = engine
        self.workflow_id = uuid.uuid4().hex
        self.runs: list[RunObject] = []
        self.state = RunStates.running
        self.error: Optional[str] = None


# module-level pipeline context used by workflow python files
_current_context: Optional[PipelineContext] = None
_context_lock = threading.Lock()


def pipeline_context() -> Optional[PipelineContext]:
    return _current_context


class _PipelineRunStatus:
    """Returned by project.run() (reference pipelines.py _PipelineRunStatus)."""

    def __init__(self, run_id: str, engine: "_PipelineRunner", project,
                 workflow=None, state: str = ""):
        self.run_id = run_id
        self._engine = engine
        self.project = project
        self.workflow = workflow
        self._state = state
        self.runs: list[RunObject] = []
        self.error: Optional[str] = None

    @property
    def state(self) -> str:
        return self._state

    def wait_for_completion(self, timeout=3600, expected_statuses=None):
        return self._engine.wait_for_completion(
            self, timeout=timeout, expected_statuses=expected_statuses)

    def __str__(self):
        return self.run_id


class _PipelineRunner:
    engine = "base"

    @classmethod
    def run(cls, project, workflow_spec, name="", workflow_handler=None,
            secrets=None, artifact_path=None, namespace=None, source=None,
            args=None, local=True, watch=False) -> _PipelineRunStatus:
        raise NotImplementedError

    @classmethod
    def wait_for_completion(cls, run_status, timeout=3600,
                            expected_statuses=None):
        return run_status.state


class _LocalRunner(_PipelineRunner):
    """Execute workflow steps inline (reference pipelines.py:673)."""

    engine = "local"

    @classmethod
    def run(cls, project, workflow_spec, name="", workflow_handler=None,
            secrets=None, artifact_path=None, namespace=None, source=None,
            args=None, local=True, watch=False) -> _PipelineRunStatus:
        global _current_context

        handler = workflow_handler or _load_workflow_handler(
            workflow_spec, project)
        context = PipelineContext(
            project=project, workflow_name=name, local=local, watch=watch,
            artifact_path=artifact_path or project.spec.artifact_path,
            args=args)
        with _context_lock:
            _current_context = context
        status = _PipelineRunStatus(context.workflow_id, cls, project,
                                    workflow=workflow_spec)
        try:
            handler(**(args or {}))
            context.state = RunStates.completed
        except Exception as exc:  # noqa: BLE001 - workflow error → status
            context.state = RunStates.error
            context.error = str(exc)
            logger.error("workflow failed", name=name, error=str(exc))
        finally:
            with _context_lock:
                _current_context = None
        status._state = context.state
        status.runs = context.runs
        status.error = context.error
        if context.state == RunStates.error:
            raise RuntimeError(f"workflow {name} failed: {context.error}")
        return status


class _RemoteRunner(_PipelineRunner):
    """Submit the workflow to the service (reference pipelines.py:756)."""

    engine = "remote"

    @classmethod
    def run(cls, project, workflow_spec, name="", workflow_handler=None,
            secrets=None, artifact_path=None, namespace=None, source=None,
            args=None, local=False, watch=False) -> _PipelineRunStatus:
        from ..db import get_run_db

        db = get_run_db()
        run_id = db.submit_pipeline(
            project.name, workflow_spec if isinstance(workflow_spec, dict)
            else workflow_spec.to_dict(),
            arguments=args, artifact_path=artifact_path)
        return _PipelineRunStatus(run_id, cls, project, workflow=workflow_spec,
                                  state=RunStates.running)

    @classmethod
    def wait_for_completion(cls, run_status, timeout=3600,
                            expected_statuses=None):
        return wait_for_run_completion(
            run_status.run_id, timeout=timeout,
            project=run_status.project.name,
            expected_statuses=expected_statuses)


def _step_exec_env(step: "PipelineStep", artifact_path: str,
                   params: dict | None = None,
                   inputs: dict | None = None) -> list[dict]:
    """The in-pod contract env for one step (`mlrun-tpu run --from-env`
    with MLT_EXEC_CONFIG/MLT_EXEC_CODE — the mlrun_op analog from
    pipeline-adapters ops.py:66). Shared by the kfp-free IR compiler and
    the kfp-SDK container-op builder so the contract can't drift."""
    import json as jsonlib

    function = step.function
    run = {
        "metadata": {"name": step.name,
                     "project": function.metadata.project},
        "spec": {"parameters": step.params if params is None else params,
                 "inputs": step.inputs if inputs is None else inputs,
                 "handler": step.handler or function.spec.default_handler,
                 "output_path": step.artifact_path or artifact_path,
                 "function": function.uri},
    }
    env = [{"name": "MLT_EXEC_CONFIG",
            "value": jsonlib.dumps(run, default=str)}]
    build = function.spec.build
    if build and getattr(build, "functionSourceCode", ""):
        env.append({"name": "MLT_EXEC_CODE",
                    "value": build.functionSourceCode})
    return env


def compile_kfp_pipeline(project, workflow_spec=None, name: str = "",
                         workflow_handler=None, artifact_path: str = "",
                         args: dict | None = None) -> dict:
    """Compile a workflow to a KFP v2 ``PipelineSpec`` IR dict WITHOUT the
    kfp package (reference pipelines.py:542 compiles via the kfp SDK; the
    IR schema itself is plain JSON, so emitting it directly keeps the
    compile path executable in kfp-less environments — submission to a KFP
    endpoint still requires the kfp client, see _KFPRunner.run).

    Each step becomes an executor running the in-pod contract;
    ``.after()`` chains and ``step.output()`` references become dag
    dependencies. Step-output params are declared as component
    input/output parameter definitions and injected into the exec config
    via KFP runtime placeholders (``{{$.inputs.parameters['k']}}``) so
    the backend substitutes the produced value at run time.
    """
    global _current_context

    handler = workflow_handler or _load_workflow_handler(
        workflow_spec, project)
    context = PipelineContext(
        project=project, workflow_name=name, local=False,
        artifact_path=artifact_path or project.spec.artifact_path,
        args=args, engine="kfp-compile")
    with _context_lock:
        _current_context = context
    try:
        handler(**(args or {}))
    finally:
        with _context_lock:
            _current_context = None

    # unique task names: duplicate step names get -2/-3… suffixes (like the
    # kfp SDK) so later steps can't silently overwrite earlier ones
    task_names: dict[int, str] = {}
    used: dict[str, int] = {}
    for step in context.steps:
        count = used.get(step.name, 0) + 1
        used[step.name] = count
        task_names[id(step)] = (step.name if count == 1
                                else f"{step.name}-{count}")

    # producer steps must declare every output key a consumer references
    produced: dict[int, set] = {}
    for step in context.steps:
        for value in {**step.params, **step.inputs}.values():
            if isinstance(value, _StepOutput):
                produced.setdefault(id(value.step), set()).add(value.key)

    executors: dict = {}
    components: dict = {}
    tasks: dict = {}
    for step in context.steps:
        task_name = task_names[id(step)]
        deps = {task_names[id(dep)] for dep in step.after_steps
                if id(dep) in task_names}
        task_inputs: dict = {}
        static_params: dict = {}
        static_inputs: dict = {}
        dyn_args: list = []
        for key, value, bucket, flag in (
                [(k, v, static_params, "--str-param")
                 for k, v in step.params.items()]
                + [(k, v, static_inputs, "--inputs")
                   for k, v in step.inputs.items()]):
            if isinstance(value, _StepOutput):
                producer = task_names[id(value.step)]
                deps.add(producer)
                task_inputs[key] = {"taskOutputParameter": {
                    "producerTask": producer,
                    "outputParameterKey": value.key}}
                # dynamic values ride in ARGS: the KFP launcher substitutes
                # {{$...}} runtime placeholders in command/args only, so an
                # env-embedded placeholder would arrive verbatim; the
                # --from-env entrypoint merges --param/--inputs over
                # MLT_EXEC_CONFIG (__main__.py run)
                dyn_args += [flag,
                             f"{key}={{{{$.inputs.parameters['{key}']}}}}"]
            else:
                bucket[key] = value

        env = _step_exec_env(step, context.artifact_path,
                             params=static_params, inputs=static_inputs)
        # output-parameter paths ride in ARGS for the same reason
        # (__main__.py --kfp-output writes run results to those paths)
        out_args = list(dyn_args)
        for key in sorted(produced.get(id(step), ())):
            out_args += ["--kfp-output",
                         f"{key}={{{{$.outputs.parameters['{key}']"
                         f".output_file}}}}"]
        container = {
            "image": step.function.full_image_path(),
            "command": ["mlrun-tpu", "run", "--from-env"],
            "env": env,
        }
        if out_args:
            container["args"] = out_args
        executors[f"exec-{task_name}"] = {"container": container}
        component: dict = {"executorLabel": f"exec-{task_name}"}
        if task_inputs:
            component["inputDefinitions"] = {"parameters": {
                key: {"parameterType": "STRING"} for key in task_inputs}}
        if produced.get(id(step)):
            component["outputDefinitions"] = {"parameters": {
                key: {"parameterType": "STRING"}
                for key in sorted(produced[id(step)])}}
        components[f"comp-{task_name}"] = component

        task = {"componentRef": {"name": f"comp-{task_name}"},
                "taskInfo": {"name": task_name}}
        if deps:
            task["dependentTasks"] = sorted(deps)
        if task_inputs:
            task["inputs"] = {"parameters": task_inputs}
        tasks[task_name] = task

    return {
        "pipelineInfo": {"name": name or context.workflow_id},
        "schemaVersion": "2.1.0",
        "sdkVersion": "mlrun-tpu",
        "deploymentSpec": {"executors": executors},
        "components": components,
        "root": {"dag": {"tasks": tasks}},
    }


class _KFPRunner(_PipelineRunner):
    """Compile the workflow to Kubeflow Pipelines when kfp is available
    (reference pipelines.py:542 + pipeline-adapters mlrun_op, ops.py:66).
    The kfp-free compile path is :func:`compile_kfp_pipeline`."""

    engine = "kfp"
    compile = staticmethod(compile_kfp_pipeline)

    @staticmethod
    def _step_to_container_op(step: "PipelineStep", artifact_path: str):
        """One workflow step → a KFP container op running the in-pod
        contract (`mlrun-tpu run --from-env`), the mlrun_op analog."""
        import kfp.dsl as dsl

        op = dsl.ContainerOp(
            name=step.name,
            image=step.function.full_image_path(),
            command=["mlrun-tpu", "run", "--from-env"],
        )
        for item in _step_exec_env(step, artifact_path):
            op.container.add_env_variable(item)
        return op

    @classmethod
    def run(cls, project, workflow_spec, name="", workflow_handler=None,
            secrets=None, artifact_path=None, namespace=None, source=None,
            args=None, local=False, watch=False) -> _PipelineRunStatus:
        try:
            import kfp
        except ImportError as exc:
            raise ImportError(
                "the kfp engine requires the 'kfp' package; use "
                "engine='local' or engine='remote' instead") from exc

        global _current_context

        handler = workflow_handler or _load_workflow_handler(
            workflow_spec, project)
        # during kfp tracing, run_function emits container ops (engine=kfp
        # in the pipeline context) instead of executing steps
        compile_context = PipelineContext(
            project=project, workflow_name=name, local=False,
            artifact_path=artifact_path or project.spec.artifact_path,
            args=args, engine="kfp")

        def traced_handler(*handler_args, **handler_kwargs):
            global _current_context

            with _context_lock:
                _current_context = compile_context
            try:
                return handler(*handler_args, **handler_kwargs)
            finally:
                with _context_lock:
                    _current_context = None

        client = kfp.Client(namespace=namespace) if namespace else \
            kfp.Client()
        run_result = client.create_run_from_pipeline_func(
            traced_handler, arguments=args or {},
            experiment_name=project.name)
        return _PipelineRunStatus(str(run_result.run_id), cls, project,
                                  workflow=workflow_spec,
                                  state=RunStates.running)


def get_workflow_engine(engine: str = "", local: bool = False):
    if local or engine in ("", "local"):
        return _LocalRunner
    if engine == "remote":
        return _RemoteRunner
    if engine == "kfp":
        return _KFPRunner
    raise ValueError(f"unsupported workflow engine '{engine}'")


def _load_workflow_handler(workflow_spec, project) -> Callable:
    path = workflow_spec.get("path") if isinstance(workflow_spec, dict) \
        else getattr(workflow_spec, "path", "")
    handler_name = (workflow_spec.get("handler")
                    if isinstance(workflow_spec, dict)
                    else getattr(workflow_spec, "handler", "")) or "pipeline"
    if not path:
        raise ValueError("workflow has no code path")
    if project is not None and project.spec.context and not os.path.isabs(path):
        path = os.path.join(project.spec.context, path)
    module_name = os.path.splitext(os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(module_name, path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    if not hasattr(module, handler_name):
        # fall back to any function decorated or named main
        for candidate in ("main", "kfpipeline", "workflow"):
            if hasattr(module, candidate):
                handler_name = candidate
                break
        else:
            raise ValueError(
                f"workflow handler '{handler_name}' not found in {path}")
    return getattr(module, handler_name)


def load_and_run(context, url: str = "", project_name: str = "",
                 workflow_name: str = "", workflow_path: str = "",
                 workflow_arguments: dict | None = None,
                 artifact_path: str = ""):
    """Entry used by the server's workflow-runner job
    (reference pipelines.py:987)."""
    from . import load_project

    project = load_project(context="./", url=url, name=project_name)
    return project.run(
        name=workflow_name, workflow_path=workflow_path,
        arguments=workflow_arguments, artifact_path=artifact_path,
        engine="local")


def wait_for_run_completion(run_id, timeout: float = 3600, project: str = "",
                            expected_statuses: list | None = None) -> str:
    """Poll the service for a workflow run's state."""
    from ..db import get_run_db

    db = get_run_db()
    deadline = time.monotonic() + timeout
    state = RunStates.running
    while time.monotonic() < deadline:
        try:
            resp = db.api_call(
                "GET", f"projects/{project or mlconf.default_project}/"
                f"workflows/{run_id}")
            state = resp.get("state", RunStates.running)
        except Exception:  # noqa: BLE001 - transient api errors tolerated
            pass
        if state in RunStates.terminal_states():
            break
        time.sleep(2)
    if expected_statuses and state not in expected_statuses:
        raise RuntimeError(f"workflow {run_id} ended in state {state}")
    return state
