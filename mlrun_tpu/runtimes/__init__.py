"""Runtime registry (reference analog: mlrun/runtimes/__init__.py:99-112
RuntimeKinds registry)."""

from __future__ import annotations

from ..common.runtimes_constants import RuntimeKinds  # noqa: F401
from .base import BaseRuntime, FunctionMetadata, FunctionSpec, FunctionStatus  # noqa: F401
from .generators import get_generator  # noqa: F401
from .local import HandlerRuntime, LocalRuntime  # noqa: F401


def _registry() -> dict:
    from .daskjob import DaskRuntime
    from .databricks import DatabricksRuntime
    from .kubejob import KubejobRuntime
    from .remote import ApplicationRuntime, RemoteRuntime
    from .serving import ServingRuntime
    from .sparkjob import SparkRuntime
    from .tpujob import TpuJobRuntime

    return {
        RuntimeKinds.local: LocalRuntime,
        "": LocalRuntime,
        RuntimeKinds.handler: HandlerRuntime,
        RuntimeKinds.job: KubejobRuntime,
        RuntimeKinds.tpujob: TpuJobRuntime,
        RuntimeKinds.dask: DaskRuntime,
        RuntimeKinds.spark: SparkRuntime,
        RuntimeKinds.databricks: DatabricksRuntime,
        RuntimeKinds.serving: ServingRuntime,
        RuntimeKinds.remote: RemoteRuntime,
        RuntimeKinds.application: ApplicationRuntime,
    }


def get_runtime_class(kind: str) -> type:
    registry = _registry()
    if kind not in registry:
        raise ValueError(
            f"unsupported runtime kind '{kind}', expected one of "
            f"{sorted(k for k in registry if k)}")
    return registry[kind]


def new_runtime(kind: str, struct: dict | None = None) -> BaseRuntime:
    cls = get_runtime_class(kind)
    obj = cls.from_dict(struct or {})
    obj.kind = kind or obj.kind
    return obj
