"""Serving runtime (reference analog: mlrun/runtimes/nuclio/serving.py:232
ServingRuntime — set_topology :245, add_model :356, deploy :580).

Deployment target is the built-in ASGI graph server (Nuclio replaced); the
graph+models serialize into the function spec exactly like the reference's
SERVING_SPEC_ENV contract, and ``to_mock_server`` gives the offline test path.
"""

from __future__ import annotations

import json
from typing import Optional, Union

from ..common.runtimes_constants import RuntimeKinds
from ..model import ModelObj
from ..serving.server import GraphServer, create_graph_server
from ..serving.states import (
    FlowStep,
    RootFlowStep,
    RouterStep,
    TaskStep,
    graph_root_setter,
)
from ..utils import logger
from .remote import RemoteRuntime, RemoteSpec


class ServingSpec(RemoteSpec):
    _dict_fields = RemoteSpec._dict_fields + [
        "graph", "parameters", "load_mode", "graph_initializer",
        "error_stream", "track_models", "secret_sources",
        "default_content_type",
    ]

    def __init__(self, graph=None, parameters=None, load_mode=None,
                 graph_initializer=None, error_stream=None, track_models=None,
                 secret_sources=None, default_content_type=None, **kwargs):
        super().__init__(**kwargs)
        self._graph = None
        self.graph = graph
        self.parameters = parameters or {}
        self.load_mode = load_mode
        self.graph_initializer = graph_initializer
        self.error_stream = error_stream
        self.track_models = track_models
        self.secret_sources = secret_sources or []
        self.default_content_type = default_content_type

    @property
    def graph(self):
        return self._graph

    @graph.setter
    def graph(self, graph):
        if graph is None:
            self._graph = None
        elif isinstance(graph, dict):
            from ..serving.states import step_from_dict

            self._graph = step_from_dict(graph)
        else:
            self._graph = graph

    def to_dict(self, exclude=None):
        out = super().to_dict(exclude=["graph"])
        if self._graph is not None:
            out["graph"] = self._graph.to_dict()
        return out


class ServingRuntime(RemoteRuntime):
    kind = RuntimeKinds.serving
    _nested_fields = {**RemoteRuntime._nested_fields, "spec": ServingSpec}

    def __init__(self, metadata=None, spec=None, status=None):
        super().__init__(metadata, spec, status)
        if not isinstance(self.spec, ServingSpec):
            self.spec = ServingSpec.from_dict(self.spec.to_dict())

    # -- graph building ----------------------------------------------------
    def set_topology(self, topology: str = "router", class_name=None,
                     engine: str | None = None, exist_ok: bool = False,
                     **class_args) -> Union[RouterStep, RootFlowStep]:
        """Set the graph topology: 'router' or 'flow' (serving.py:245)."""
        if self.spec.graph is not None and not exist_ok:
            raise ValueError("graph topology is already set; pass exist_ok")
        if topology == "router":
            step = RouterStep(class_name=class_name, class_args=class_args)
            root = RootFlowStep()
            step.name = "router"
            root._add_existing("router", step)
            step.responder = True
            self.spec.graph = root
            root._router = step
            return step
        if topology == "flow":
            root = RootFlowStep(engine=engine)
            self.spec.graph = root
            return root
        raise ValueError(f"unsupported topology '{topology}'")

    @property
    def graph(self):
        return self.spec.graph

    def _router(self, router_step: str | None = None) -> RouterStep:
        graph = self.spec.graph
        if graph is None:
            return self.set_topology("router")
        if router_step:
            # an explicitly named router wins — required when a flow
            # carries several routers, validated always (a bad name must
            # error, not silently attach to whichever router exists)
            step = (getattr(graph, "steps", None) or {}).get(router_step)
            if not isinstance(step, RouterStep):
                raise ValueError(
                    f"step {router_step!r} is not a router in the graph")
            return step
        if hasattr(graph, "_router"):
            return graph._router
        if isinstance(graph, RouterStep):
            return graph
        # deserialized graphs (hub:// yaml, db round-trips) lose the
        # transient _router handle set_topology stashed — recover it from
        # a lone router step so add_model works on re-loaded functions
        steps = getattr(graph, "steps", None) or {}
        routers = [step for step in steps.values()
                   if isinstance(step, RouterStep)]
        if len(routers) == 1:
            # NOT cached: a later add_step could introduce a second
            # router, and a stale cached handle would make the ambiguity
            # check order-dependent (or outlive a removed step)
            return routers[0]
        raise ValueError("graph topology is not a router")

    def add_model(self, key: str, model_path: str | None = None,
                  class_name=None, model_url: str | None = None,
                  handler: str | None = None, router_step: str | None = None,
                  **class_args) -> TaskStep:
        """Register a model on the router (serving.py:356)."""
        router = self._router(router_step)
        if model_path:
            class_args = dict(class_args)
            class_args["model_path"] = model_path
        route = TaskStep(class_name or "V2ModelServer", class_args,
                         handler, name=key)
        return router.add_route(key, route)

    def remove_models(self, keys: list[str] | None = None):
        self._router().clear_children(keys)

    def set_tracking(self, stream_path: str | None = None, batch: int | None = None,
                     sample: int | None = None, tracking_policy=None):
        """Enable model-monitoring event tracking (serving.py set_tracking)."""
        self.spec.track_models = True
        if stream_path:
            self.spec.parameters["log_stream"] = stream_path
        return self

    def with_secrets(self, kind: str, source):
        self.spec.secret_sources.append({"kind": kind, "source": source})
        return self

    # -- serving spec / server ---------------------------------------------
    def _get_serving_spec(self) -> dict:
        return {
            "function_uri": self.uri,
            "version": "v2",
            "parameters": self.spec.parameters,
            "graph": self.spec.graph.to_dict() if self.spec.graph else None,
            "load_mode": self.spec.load_mode,
            "verbose": self.verbose,
            "graph_initializer": self.spec.graph_initializer,
            "error_stream": self.spec.error_stream,
            "track_models": self.spec.track_models,
            "secret_sources": self.spec.secret_sources,
            "default_content_type": self.spec.default_content_type,
        }

    def to_mock_server(self, namespace: dict | None = None,
                       current_function="*", track_models: bool = False,
                       **kwargs) -> GraphServer:
        """Create an in-process server for offline testing (the reference's
        fn.to_mock_server, serving.py)."""
        from ..serving.server import GraphContext

        server = GraphServer.from_dict(self._get_serving_spec())
        server.graph = self.spec.graph
        if track_models:
            server.track_models = True
        context = GraphContext(server=server)
        server.init_states(context, namespace=namespace or {}, is_mock=True)
        return server

    def deploy(self, project: str = "", tag: str = "", verbose: bool = False):
        """Serialize graph into env + deploy via the service (serving.py:580)."""
        self.set_env("SERVING_SPEC_ENV",
                     json.dumps(self._get_serving_spec(), default=str))
        return super().deploy(project, tag, verbose)
