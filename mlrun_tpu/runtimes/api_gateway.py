"""API gateway (reference analog: mlrun/runtimes/nuclio/api_gateway.py
APIGateway — routes external traffic to one or two deployed serving
functions with optional canary weights and basic auth)."""

from __future__ import annotations

from typing import Optional

from ..config import mlconf
from ..model import ModelObj
from ..utils import logger, normalize_name


class APIGatewaySpec(ModelObj):
    _dict_fields = ["functions", "canary", "host", "path",
                    "authentication_mode", "username", "description"]

    def __init__(self, functions=None, canary=None, host=None, path=None,
                 authentication_mode=None, username=None, description=None):
        self.functions = functions or []      # 1-2 function uris
        self.canary = canary                  # e.g. [90, 10]
        self.host = host
        self.path = path or "/"
        self.authentication_mode = authentication_mode or "none"
        self.username = username
        self.description = description


class APIGateway(ModelObj):
    kind = "api-gateway"
    _dict_fields = ["kind", "metadata", "spec", "status"]

    def __init__(self, name: str = "", project: str = "",
                 functions=None, canary=None, host: str = "",
                 path: str = "/"):
        from .base import FunctionMetadata, FunctionStatus

        self.metadata = FunctionMetadata(
            name=normalize_name(name) if name else None, project=project)
        self.spec = APIGatewaySpec(
            functions=[f if isinstance(f, str) else f.uri
                       for f in (functions or [])],
            canary=canary, host=host, path=path)
        self.status = FunctionStatus()

    def with_basic_auth(self, username: str, password: str):
        self.spec.authentication_mode = "basicAuth"
        self.spec.username = username
        self._password = password
        return self

    def with_canary(self, functions: list, canary: list[int]):
        if len(functions) != 2 or len(canary) != 2 or sum(canary) != 100:
            raise ValueError(
                "canary needs exactly 2 functions and weights summing to 100")
        self.spec.functions = [f if isinstance(f, str) else f.uri
                               for f in functions]
        self.spec.canary = list(canary)
        return self

    def save(self, db=None):
        if db is None:
            from ..db import get_run_db

            db = get_run_db()
        project = self.metadata.project or mlconf.default_project
        db.api_call(
            "POST", f"projects/{project}/api-gateways/{self.metadata.name}",
            json={"data": self.to_dict()})
        return self

    def invoke_url(self) -> str:
        host = self.spec.host or ""
        return f"http://{host}{self.spec.path}" if host else self.spec.path

    def pick_function(self) -> str:
        """Weighted choice for canary routing (used by the gateway router)."""
        import random

        if not self.spec.functions:
            raise ValueError("api gateway has no functions")
        if self.spec.canary and len(self.spec.functions) == 2:
            return random.choices(
                self.spec.functions, weights=self.spec.canary)[0]
        return self.spec.functions[0]
