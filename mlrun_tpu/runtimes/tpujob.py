"""TPU pod-slice runtime — the distributed-training centerpiece.

Replaces the reference's MPI runtime (mlrun/runtimes/mpijob/abstract.py:23
MPIResourceSpec with NCCL env defaults :89-96, AbstractMPIJobRuntime :98,
MpiRuntimeV1 v1.py:88). Instead of launcher+worker MPIJob CRDs and Horovod,
a ``tpujob`` provisions a GKE JobSet of identical SPMD pods over one or more
TPU slices (see mlrun_tpu/k8s/jobset.py); JAX's collective runtime replaces
mpirun/NCCL, and shardings are declared on the train step via
``mlrun_tpu.parallel`` (XLA emits the ICI/DCN collectives).
"""

from __future__ import annotations

from ..common.runtimes_constants import RuntimeKinds
from ..config import mlconf
from ..k8s.jobset import chips_in_topology, hosts_for_topology
from ..model import RunObject
from ..utils import logger
from .pod import KubeResource, KubeResourceSpec


class TpuJobSpec(KubeResourceSpec):
    _dict_fields = KubeResourceSpec._dict_fields + [
        "accelerator_type", "topology", "num_slices", "chips_per_host",
        "max_restarts", "mesh_shape", "mesh_axes", "elastic",
    ]

    def __init__(self, accelerator_type=None, topology=None, num_slices=None,
                 chips_per_host=None, max_restarts=None, mesh_shape=None,
                 mesh_axes=None, elastic=None, **kwargs):
        super().__init__(**kwargs)
        self.accelerator_type = accelerator_type or mlconf.tpu.default_accelerator
        self.topology = topology or mlconf.tpu.default_topology
        self.num_slices = num_slices or 1
        # None = config default; an explicit 0 is kept so the typed
        # TopologyError fires at JobSet build instead of the bad value
        # silently becoming the default host geometry
        self.chips_per_host = chips_per_host if chips_per_host is not None \
            else mlconf.tpu.chips_per_host
        # restart the whole JobSet on preemption; checkpoint-resume picks up
        self.max_restarts = max_restarts if max_restarts is not None else 3
        self.mesh_shape = mesh_shape
        self.mesh_axes = mesh_axes
        # elastic multi-slice: survive one slice's preemption by
        # resharding onto the survivors instead of a full JobSet restart
        # (docs/fault_tolerance.md "Elastic training")
        self.elastic = bool(elastic)


class TpuJobRuntime(KubeResource):
    kind = RuntimeKinds.tpujob
    _is_remote = True
    _nested_fields = {**KubeResource._nested_fields, "spec": TpuJobSpec}

    def __init__(self, metadata=None, spec=None, status=None):
        super().__init__(metadata, spec, status)
        if not isinstance(self.spec, TpuJobSpec):
            self.spec = TpuJobSpec.from_dict(self.spec.to_dict())

    # -- TPU topology ------------------------------------------------------
    def with_tpu_topology(self, accelerator: str | None = None,
                          topology: str | None = None, num_slices: int = 1,
                          chips_per_host: int | None = None):
        """Declare the slice shape, e.g.
        ``fn.with_tpu_topology("tpu-v5-lite-podslice", "8x8")`` for a v5e-64.
        """
        if accelerator:
            self.spec.accelerator_type = accelerator
        if topology:
            self.spec.topology = topology
        self.spec.num_slices = num_slices
        if chips_per_host:
            self.spec.chips_per_host = chips_per_host
        return self

    def with_mesh(self, shape: dict | None = None, axes: list | None = None):
        """Declare the default logical mesh for the auto-trainer, e.g.
        ``with_mesh({"data": 1, "fsdp": 16, "tensor": 4})``."""
        if shape:
            self.spec.mesh_shape = dict(shape)
        if axes:
            self.spec.mesh_axes = list(axes)
        return self

    def with_elastic(self, elastic: bool = True):
        """Opt the run into elastic multi-slice training: on a slice
        preemption the service submits only a replacement slice while
        the survivors reshard and keep training
        (docs/fault_tolerance.md "Elastic training"). The run's handler
        should pass an :class:`~mlrun_tpu.training.ElasticGuard` to
        ``Trainer.fit`` and a retry policy with ``max_retries`` > 0 (the
        slice-replacement budget)."""
        self.spec.elastic = bool(elastic)
        return self

    def with_preemptible(self, spot: bool = True):
        if spot:
            self.spec.node_selector["cloud.google.com/gke-spot"] = "true"
        else:
            self.spec.node_selector.pop("cloud.google.com/gke-spot", None)
        return self

    @property
    def total_chips(self) -> int:
        return chips_in_topology(self.spec.topology) * self.spec.num_slices

    @property
    def hosts_per_slice(self) -> int:
        return hosts_for_topology(self.spec.topology, self.spec.chips_per_host)

    def full_image_path(self, image: str | None = None) -> str:
        return image or self.spec.image or mlconf.function.tpu_image

    # -- execution ---------------------------------------------------------
    def _run(self, runobj: RunObject, execution) -> dict:
        raise RuntimeError(
            "the tpujob runtime provisions TPU slices via the service — "
            "configure MLT_DBPATH, or pass local=True to execute the handler "
            "in-process on locally visible devices")

    def generate_jobset(self, runobj: RunObject, extra_env: dict | None = None,
                        command: list[str] | None = None) -> dict:
        """Build the JobSet resource for this run (used by the server-side
        runtime handler and asserted by control-plane tests, mirroring the
        reference's MPIJob handler tests)."""
        import json

        from ..k8s.jobset import build_jobset

        env = {
            mlconf.exec_config_env: json.dumps(runobj.to_dict(), default=str),
            "MLT_DBPATH": mlconf.get("dbpath", ""),
        }
        env.update(extra_env or {})
        pod_spec = self.to_pod_spec(
            command=command or ["mlrun-tpu", "run", "--from-env"],
            extra_env=env)
        name = f"{runobj.metadata.name}-{runobj.metadata.uid[:8]}"
        return build_jobset(
            name=name,
            namespace=mlconf.namespace,
            pod_spec=pod_spec,
            accelerator=self.spec.accelerator_type,
            topology=self.spec.topology,
            num_slices=self.spec.num_slices,
            chips_per_host=self.spec.chips_per_host,
            max_restarts=self.spec.max_restarts,
            elastic=bool(getattr(self.spec, "elastic", False)),
            labels={
                "mlrun-tpu/project": runobj.metadata.project,
                "mlrun-tpu/uid": runobj.metadata.uid,
                "mlrun-tpu/name": runobj.metadata.name,
                "mlrun-tpu/class": self.kind,
            },
        )
