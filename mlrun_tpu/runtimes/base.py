"""Runtime base (reference analog: mlrun/runtimes/base.py:171 BaseRuntime,
:96 FunctionSpec; run() delegates to a launcher like :402-410)."""

from __future__ import annotations

import os
from typing import Callable, Optional, Union

from ..common.runtimes_constants import RuntimeKinds
from ..config import mlconf
from ..model import ImageBuilder, ModelObj, Notification, RunObject, RunTemplate, new_task
from ..utils import generate_uid, logger, normalize_name, now_iso, update_in


class FunctionMetadata(ModelObj):
    _dict_fields = ["name", "tag", "hash", "project", "labels", "annotations",
                    "categories", "updated", "credentials"]

    def __init__(self, name=None, tag=None, hash=None, project=None, labels=None,
                 annotations=None, categories=None, updated=None, credentials=None):
        self.name = name
        self.tag = tag
        self.hash = hash
        self.project = project
        self.labels = labels or {}
        self.annotations = annotations or {}
        self.categories = categories or []
        self.updated = updated
        self.credentials = credentials


class FunctionSpec(ModelObj):
    _dict_fields = [
        "command", "args", "image", "mode", "build", "entry_points",
        "description", "workdir", "default_handler", "pythonpath", "env",
        "resources", "replicas", "image_pull_policy", "service_account",
        "node_selector", "priority_class_name", "preemption_mode",
        "state_thresholds",
    ]
    _nested_fields = {"build": ImageBuilder}

    def __init__(self, command=None, args=None, image=None, mode=None, build=None,
                 entry_points=None, description=None, workdir=None,
                 default_handler=None, pythonpath=None, env=None, resources=None,
                 replicas=None, image_pull_policy=None, service_account=None,
                 node_selector=None, priority_class_name=None,
                 preemption_mode=None, state_thresholds=None):
        self.command = command or ""
        self.args = args or []
        self.image = image or ""
        self.mode = mode
        self.build = build or ImageBuilder()
        self.entry_points = entry_points or {}
        self.description = description or ""
        self.workdir = workdir
        self.default_handler = default_handler
        self.pythonpath = pythonpath
        self.env = env or []
        self.resources = resources or {}
        self.replicas = replicas
        self.image_pull_policy = image_pull_policy
        self.service_account = service_account
        self.node_selector = node_selector or {}
        self.priority_class_name = priority_class_name
        self.preemption_mode = preemption_mode
        self.state_thresholds = state_thresholds or {}


class FunctionStatus(ModelObj):
    _dict_fields = ["state", "build_pod", "external_invocation_urls", "address",
                    "nodes"]

    def __init__(self, state=None, build_pod=None, external_invocation_urls=None,
                 address=None, nodes=None):
        self.state = state
        self.build_pod = build_pod
        self.external_invocation_urls = external_invocation_urls or []
        self.address = address
        self.nodes = nodes


class BaseRuntime(ModelObj):
    kind = "base"
    _is_nested = False
    _is_remote = False
    _dict_fields = ["kind", "metadata", "spec", "status"]
    _nested_fields = {"metadata": FunctionMetadata, "spec": FunctionSpec,
                      "status": FunctionStatus}

    def __init__(self, metadata=None, spec=None, status=None):
        self.metadata = metadata or FunctionMetadata()
        self.spec = spec or FunctionSpec()
        self.status = status or FunctionStatus()
        self._db = None
        self._handler: Optional[Callable] = None  # in-process handler (local)
        self.verbose = False
        self._enriched = False

    # -- spec helpers ------------------------------------------------------
    @property
    def uri(self) -> str:
        project = self.metadata.project or mlconf.default_project
        uri = f"{project}/{self.metadata.name}"
        if self.metadata.tag:
            uri += f":{self.metadata.tag}"
        if self.metadata.hash:
            uri += f"@{self.metadata.hash}"
        return uri

    @property
    def is_deployed(self) -> bool:
        return True

    def is_remote(self) -> bool:
        return self._is_remote

    def with_code(self, from_file: str = "", body: str | None = None):
        if from_file:
            with open(from_file) as fp:
                body = fp.read()
        if body:
            self.spec.build.with_source(body)
            self.spec.build.origin_filename = from_file
        return self

    def with_commands(self, commands: list[str],
                      overwrite: bool = False) -> "BaseRuntime":
        """Add image-build shell commands (reference base.py
        with_commands; the kubernetes provider's kaniko build runs them —
        the local overlay build FAILS loudly instead of dropping them)."""
        current = [] if overwrite else list(self.spec.build.commands or [])
        self.spec.build.commands = current + [
            c for c in commands if c not in current]
        return self

    def requires_build(self) -> bool:
        """True when deploy must run an actual build (reference
        base.py requires_build)."""
        build = self.spec.build
        return bool(build.commands or build.requirements
                    or build.source or build.extra)

    def set_db_connection(self, db):
        """Pin the run DB this function talks to (reference
        base.py set_db_connection)."""
        self._db = db

    def store_run(self, runobj: "RunObject"):
        """Persist a run object through the function's DB (reference
        base.py store_run)."""
        self._get_db().store_run(
            runobj.to_dict(), runobj.metadata.uid,
            runobj.metadata.project or mlconf.default_project)

    def prepare_image_for_deploy(self):
        """Resolve the image a deploy will use: an explicit image wins;
        a build spec keeps its target; otherwise the configured default
        (reference base.py prepare_image_for_deploy)."""
        if self.spec.image:
            return
        if self.spec.build.image:
            self.spec.image = self.spec.build.image
        elif not self.requires_build():
            self.spec.image = mlconf.function.default_image

    def clean_build_params(self) -> "BaseRuntime":
        """Drop credentials from the build spec before export/share
        (reference base.py clean_build_params)."""
        self.spec.build.secret = None
        return self

    def with_requirements(self, requirements: list[str]):
        self.spec.build.requirements = list(requirements)
        return self

    def set_env(self, name: str, value) -> "BaseRuntime":
        for item in self.spec.env:
            if item.get("name") == name:
                item["value"] = str(value)
                return self
        self.spec.env.append({"name": name, "value": str(value)})
        return self

    def get_env(self, name: str, default=None):
        for item in self.spec.env:
            if item.get("name") == name:
                return item.get("value")
        return default

    def set_envs(self, env_vars: dict):
        for key, value in env_vars.items():
            self.set_env(key, value)
        return self

    def set_label(self, key, value):
        self.metadata.labels[key] = str(value)
        return self

    def _get_db(self):
        if self._db is None:
            from ..db import get_run_db

            self._db = get_run_db()
        return self._db

    def save(self, tag: str = "", versioned: bool = True) -> str:
        db = self._get_db()
        tag = tag or self.metadata.tag or "latest"
        self.metadata.tag = tag
        self.metadata.updated = now_iso()
        hash_key = db.store_function(
            self.to_dict(), self.metadata.name,
            self.metadata.project or mlconf.default_project,
            tag=tag, versioned=versioned)
        self.metadata.hash = hash_key
        return f"db://{self.uri}"

    def export(self, target: str = "", format: str = "yaml") -> "BaseRuntime":
        target = target or f"function-{self.metadata.name}.yaml"
        body = self.to_yaml() if format == "yaml" else self.to_json()
        from ..datastore import store_manager

        store, path = store_manager.get_or_create_store(target)
        store.put(path, body)
        logger.info("function exported", target=target)
        return self

    # -- run ---------------------------------------------------------------
    def run(self, runspec: Union[RunTemplate, RunObject, dict, None] = None,
            handler: Union[str, Callable, None] = None, name: str = "",
            project: str = "", params: dict | None = None,
            inputs: dict | None = None, out_path: str = "",
            artifact_path: str = "", workdir: str = "", watch: bool = True,
            schedule: str | None = None, hyperparams: dict | None = None,
            hyper_param_options=None, verbose: bool | None = None,
            scrape_metrics: bool | None = None, local: bool = False,
            local_code_path: str | None = None, auto_build: bool = False,
            returns: list | None = None, notifications: list | None = None,
            state_thresholds: dict | None = None, **launcher_kwargs) -> RunObject:
        """Run this function — locally or via the service, depending on the
        runtime kind and configuration (reference runtimes/base.py:314)."""
        from ..launcher.factory import LauncherFactory

        if isinstance(runspec, dict):
            runspec = RunTemplate.from_dict(runspec)
        run = self._create_run_object(runspec)
        if handler is not None:
            run.spec.handler = handler
        run.metadata.name = name or run.metadata.name or self.metadata.name \
            or (handler.__name__ if callable(handler) else "run")
        run.metadata.name = normalize_name(run.metadata.name)
        run.metadata.project = (
            project or run.metadata.project or self.metadata.project
            or mlconf.default_project)
        if params:
            run.spec.parameters = {**(run.spec.parameters or {}), **params}
        if inputs:
            run.spec.inputs = {**(run.spec.inputs or {}), **inputs}
        if hyperparams:
            run.spec.hyperparams = hyperparams
        if hyper_param_options:
            if isinstance(hyper_param_options, dict):
                from ..model import HyperParamOptions

                hyper_param_options = HyperParamOptions.from_dict(
                    hyper_param_options)
            run.spec.hyper_param_options = hyper_param_options
        if returns:
            run.spec.returns = returns
        if notifications:
            run.spec.notifications = [
                n.to_dict() if isinstance(n, Notification) else n
                for n in notifications
            ]
        if state_thresholds:
            run.spec.state_thresholds = state_thresholds
        run.spec.output_path = (
            artifact_path or out_path or run.spec.output_path)
        if workdir:
            self.spec.workdir = workdir
        if verbose is not None:
            self.verbose = verbose
        run.spec.scrape_metrics = (
            scrape_metrics if scrape_metrics is not None
            else run.spec.scrape_metrics)

        launcher = LauncherFactory.create_launcher(
            is_remote=self.is_remote() and not local, local=local)
        return launcher.launch(
            runtime=self, task=run, schedule=schedule, watch=watch,
            auto_build=auto_build, **launcher_kwargs)

    def _create_run_object(self, runspec) -> RunObject:
        if runspec is None:
            return RunObject()
        if isinstance(runspec, RunObject):
            return runspec
        if isinstance(runspec, RunTemplate):
            return RunObject.from_template(runspec)
        raise ValueError(f"unsupported runspec type {type(runspec)}")

    # executed server-side (or in-process for local kinds) by the launcher
    def _run(self, runobj: RunObject, execution) -> dict:
        raise NotImplementedError(
            f"runtime kind '{self.kind}' executes remotely; "
            "submit via the service")

    def _pre_run(self, runobj: RunObject, execution):
        pass

    def _post_run(self, results: dict, execution):
        pass

    # -- pipelines ---------------------------------------------------------
    def as_step(self, runspec: RunTemplate | None = None, handler=None,
                name: str = "", project: str = "", params: dict | None = None,
                inputs: dict | None = None, outputs: list | None = None,
                artifact_path: str = "", image: str = "", **kwargs):
        """Convert to a workflow step (reference base.py:666 — compiled by the
        pipeline engine in projects/pipelines.py)."""
        from ..projects.pipelines import PipelineStep

        return PipelineStep(
            function=self, runspec=runspec, handler=handler, name=name,
            project=project, params=params, inputs=inputs, outputs=outputs,
            artifact_path=artifact_path, image=image, **kwargs)

    def doc(self):
        entry_points = self.spec.entry_points or {}
        print(f"function: {self.metadata.name}")
        print(self.spec.description or "")
        for name, ep in entry_points.items():
            print(f"  handler {name}: {ep.get('doc', '')}")
            for param in ep.get("parameters", []):
                print(f"    {param.get('name')} ({param.get('type', '')})")

    def full_image_path(self, image: str | None = None) -> str:
        return image or self.spec.image or mlconf.function.default_image
