"""Kubernetes pod-based resource spec (reference analog: mlrun/runtimes/pod.py
KubeResource/KubeResourceSpec; with_limits gpu_type='nvidia.com/gpu' at
pod.py:458-476 is replaced by ``google.com/tpu`` chip requests + GKE TPU node
selectors)."""

from __future__ import annotations

from ..config import mlconf
from ..model import ModelObj
from .base import BaseRuntime, FunctionSpec


class KubeResourceSpec(FunctionSpec):
    _dict_fields = FunctionSpec._dict_fields + [
        "volumes", "volume_mounts", "affinity", "tolerations",
        "security_context",
    ]

    def __init__(self, volumes=None, volume_mounts=None, affinity=None,
                 tolerations=None, security_context=None, **kwargs):
        super().__init__(**kwargs)
        self.volumes = volumes or []
        self.volume_mounts = volume_mounts or []
        self.affinity = affinity
        self.tolerations = tolerations or []
        self.security_context = security_context


class KubeResource(BaseRuntime):
    """Base for all pod-creating runtimes."""

    kind = "pod"
    _is_remote = True
    _nested_fields = {**BaseRuntime._nested_fields, "spec": KubeResourceSpec}

    def __init__(self, metadata=None, spec=None, status=None):
        super().__init__(metadata, spec, status)
        if not isinstance(self.spec, KubeResourceSpec):
            self.spec = KubeResourceSpec.from_dict(
                self.spec.to_dict() if isinstance(self.spec, ModelObj)
                else (self.spec or {}))

    # -- resources ---------------------------------------------------------
    def with_requests(self, mem: str | None = None, cpu: str | None = None):
        requests = self.spec.resources.setdefault("requests", {})
        if mem:
            requests["memory"] = mem
        if cpu:
            requests["cpu"] = cpu
        return self

    def with_limits(self, mem: str | None = None, cpu: str | None = None,
                    tpus: int | None = None,
                    tpu_type: str | None = None):
        """Set container limits. ``tpus`` requests TPU chips via
        ``google.com/tpu`` (replacing nvidia.com/gpu in the reference)."""
        limits = self.spec.resources.setdefault("limits", {})
        if mem:
            limits["memory"] = mem
        if cpu:
            limits["cpu"] = cpu
        if tpus is not None:
            limits[tpu_type or mlconf.tpu.resource_name] = tpus
        return self

    def with_tpu(self, chips: int = 4, accelerator: str | None = None,
                 topology: str | None = None):
        """Request TPU chips + GKE node selectors for accelerator/topology."""
        self.with_limits(tpus=chips)
        self.spec.node_selector[mlconf.tpu.accelerator_node_selector] = (
            accelerator or mlconf.tpu.default_accelerator)
        self.spec.node_selector[mlconf.tpu.topology_node_selector] = (
            topology or mlconf.tpu.default_topology)
        return self

    def with_node_selection(self, node_selector: dict | None = None,
                            affinity=None, tolerations=None):
        if node_selector:
            self.spec.node_selector.update(node_selector)
        if affinity is not None:
            self.spec.affinity = affinity
        if tolerations is not None:
            self.spec.tolerations = tolerations
        return self

    def with_priority_class(self, name: str):
        self.spec.priority_class_name = name
        return self

    def with_preemption_mode(self, mode: str):
        # allow | constrain | prevent — on GKE TPU this maps to spot/reserved
        self.spec.preemption_mode = mode
        return self

    def apply(self, modifier):
        """Apply a pod modifier (mount decorators, reference platforms/)."""
        modifier(self)
        return self

    def set_state_thresholds(self, thresholds: dict):
        self.spec.state_thresholds.update(thresholds)
        return self

    # -- pod building (used by server-side runtime handlers & tests) -------
    def _container_env(self, extra_env: dict | None = None) -> list[dict]:
        env = [dict(e) for e in self.spec.env]
        for key, value in (extra_env or {}).items():
            env.append({"name": key, "value": str(value)})
        return env

    def to_pod_spec(self, command: list[str] | None = None,
                    extra_env: dict | None = None) -> dict:
        container = {
            "name": "main",
            "image": self.full_image_path(),
            "env": self._container_env(extra_env),
            "resources": self.spec.resources,
        }
        if command:
            container["command"] = command
        if self.spec.args:
            container["args"] = list(self.spec.args)
        if self.spec.workdir:
            container["workingDir"] = self.spec.workdir
        if self.spec.volume_mounts:
            container["volumeMounts"] = self.spec.volume_mounts
        pod_spec = {
            "containers": [container],
            "restartPolicy": "Never",
        }
        if self.spec.volumes:
            pod_spec["volumes"] = self.spec.volumes
        if self.spec.node_selector:
            pod_spec["nodeSelector"] = dict(self.spec.node_selector)
        if self.spec.tolerations:
            pod_spec["tolerations"] = self.spec.tolerations
        if self.spec.service_account:
            pod_spec["serviceAccountName"] = self.spec.service_account
        if self.spec.priority_class_name:
            pod_spec["priorityClassName"] = self.spec.priority_class_name
        return pod_spec
