"""Spark runtime (reference analog: mlrun/runtimes/sparkjob/spark3job.py:39
Spark3Runtime — spark-operator CRD with driver/executor resources).

On TPU deployments spark remains an orchestration-level (CPU) dataframe
engine. Client-side the runtime builds the SparkApplication CRD for the
spark-operator; local `run(..., local=True)` executes the handler with a
local SparkSession when pyspark is importable.
"""

from __future__ import annotations

from ..common.runtimes_constants import RuntimeKinds
from ..config import mlconf
from ..model import RunObject
from ..utils import logger
from .pod import KubeResource, KubeResourceSpec


class SparkJobSpec(KubeResourceSpec):
    _dict_fields = KubeResourceSpec._dict_fields + [
        "driver_resources", "executor_resources", "executor_replicas",
        "spark_version", "main_class", "spark_conf", "deps",
    ]

    def __init__(self, driver_resources=None, executor_resources=None,
                 executor_replicas=None, spark_version=None, main_class=None,
                 spark_conf=None, deps=None, **kwargs):
        super().__init__(**kwargs)
        self.driver_resources = driver_resources or {
            "requests": {"cpu": "1", "memory": "2g"}}
        self.executor_resources = executor_resources or {
            "requests": {"cpu": "1", "memory": "4g"}}
        self.executor_replicas = executor_replicas or 2
        self.spark_version = spark_version or "3.5.0"
        self.main_class = main_class
        self.spark_conf = spark_conf or {}
        self.deps = deps or {}


class SparkRuntime(KubeResource):
    kind = "spark"
    _is_remote = True
    _nested_fields = {**KubeResource._nested_fields, "spec": SparkJobSpec}

    def __init__(self, metadata=None, spec=None, status=None):
        super().__init__(metadata, spec, status)
        if not isinstance(self.spec, SparkJobSpec):
            self.spec = SparkJobSpec.from_dict(self.spec.to_dict())

    def with_driver_resources(self, mem: str = "", cpu: str = ""):
        requests = self.spec.driver_resources.setdefault("requests", {})
        if mem:
            requests["memory"] = mem
        if cpu:
            requests["cpu"] = cpu
        return self

    def with_executor_resources(self, mem: str = "", cpu: str = "",
                                replicas: int | None = None):
        requests = self.spec.executor_resources.setdefault("requests", {})
        if mem:
            requests["memory"] = mem
        if cpu:
            requests["cpu"] = cpu
        if replicas:
            self.spec.executor_replicas = replicas
        return self

    def generate_spark_application(self, runobj: RunObject) -> dict:
        """Build the sparkoperator.k8s.io CRD (reference spark3job.py
        _get_spark_operator_job analog, asserted by control-plane tests)."""
        import json

        name = f"{runobj.metadata.name}-{runobj.metadata.uid[:8]}"
        return {
            "apiVersion": "sparkoperator.k8s.io/v1beta2",
            "kind": "SparkApplication",
            "metadata": {
                "name": name,
                "namespace": mlconf.namespace,
                "labels": {
                    "mlrun-tpu/project": runobj.metadata.project,
                    "mlrun-tpu/uid": runobj.metadata.uid,
                    "mlrun-tpu/class": self.kind,
                },
            },
            "spec": {
                "type": "Python",
                "sparkVersion": self.spec.spark_version,
                "mode": "cluster",
                "image": self.full_image_path(),
                "mainApplicationFile": self.spec.command or "local:///main.py",
                "sparkConf": self.spec.spark_conf,
                "driver": {
                    "cores": int(float(self.spec.driver_resources
                                       .get("requests", {})
                                       .get("cpu", "1"))),
                    "memory": self.spec.driver_resources
                    .get("requests", {}).get("memory", "2g"),
                    "env": self._container_env({
                        mlconf.exec_config_env: json.dumps(
                            runobj.to_dict(), default=str)}),
                },
                "executor": {
                    "instances": self.spec.executor_replicas,
                    "cores": int(float(self.spec.executor_resources
                                       .get("requests", {})
                                       .get("cpu", "1"))),
                    "memory": self.spec.executor_resources
                    .get("requests", {}).get("memory", "4g"),
                },
            },
        }

    def _run(self, runobj: RunObject, execution) -> dict:
        # local mode: execute with a local SparkSession (gated on pyspark)
        try:
            from pyspark.sql import SparkSession
        except ImportError as exc:
            raise RuntimeError(
                "the spark runtime needs the service + spark-operator, or "
                "pyspark installed for local execution") from exc
        from .local import exec_from_params, load_module

        spark = SparkSession.builder.master("local[*]").appName(
            runobj.metadata.name).getOrCreate()
        try:
            handler = runobj.spec.handler
            if not callable(handler):
                handler = load_module(self.spec.command,
                                      runobj.spec.handler_name or "handler")
            execution.spark = spark
            return exec_from_params(handler, runobj, execution)
        finally:
            spark.stop()
