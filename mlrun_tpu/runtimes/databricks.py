"""Databricks runtime (reference analog: mlrun/runtimes/databricks_job/
databricks_runtime.py — runs a wrapped script on a Databricks cluster).

Gated on the databricks-sdk; builds the run-submit payload client-side so
the control-plane shape is testable without the SDK.
"""

from __future__ import annotations

import base64

from ..config import mlconf
from ..model import RunObject
from ..utils import logger
from .pod import KubeResource, KubeResourceSpec


class DatabricksSpec(KubeResourceSpec):
    _dict_fields = KubeResourceSpec._dict_fields + [
        "cluster_id", "new_cluster_spec", "timeout_minutes",
    ]

    def __init__(self, cluster_id=None, new_cluster_spec=None,
                 timeout_minutes=None, **kwargs):
        super().__init__(**kwargs)
        self.cluster_id = cluster_id
        self.new_cluster_spec = new_cluster_spec or {}
        self.timeout_minutes = timeout_minutes or 60


class DatabricksRuntime(KubeResource):
    kind = "databricks"
    _is_remote = True
    _nested_fields = {**KubeResource._nested_fields, "spec": DatabricksSpec}

    def __init__(self, metadata=None, spec=None, status=None):
        super().__init__(metadata, spec, status)
        if not isinstance(self.spec, DatabricksSpec):
            self.spec = DatabricksSpec.from_dict(self.spec.to_dict())

    def generate_submit_payload(self, runobj: RunObject) -> dict:
        """Build the jobs/runs/submit payload (reference wrapper-script
        contract: the embedded code ships base64 inside the task params)."""
        import json

        build = self.spec.build
        code = build.functionSourceCode if build else None
        task = {
            "task_key": f"{runobj.metadata.name}-{runobj.metadata.uid[:8]}",
            "spark_python_task": {
                "python_file": self.spec.command or "dbfs:/mlrun-tpu/run.py",
                "parameters": [
                    json.dumps({
                        "run_spec": runobj.to_dict(),
                        "handler": runobj.spec.handler_name,
                        "code_b64": code,
                    }, default=str)
                ],
            },
            "timeout_seconds": self.spec.timeout_minutes * 60,
        }
        if self.spec.cluster_id:
            task["existing_cluster_id"] = self.spec.cluster_id
        else:
            task["new_cluster"] = self.spec.new_cluster_spec or {
                "num_workers": 1, "spark_version": "14.3.x-scala2.12",
                "node_type_id": "i3.xlarge"}
        return {"run_name": runobj.metadata.name, "tasks": [task]}

    def _run(self, runobj: RunObject, execution) -> dict:
        try:
            from databricks.sdk import WorkspaceClient  # gated
        except ImportError as exc:
            raise ImportError(
                "the databricks runtime requires the databricks-sdk "
                "package") from exc
        client = WorkspaceClient()
        payload = self.generate_submit_payload(runobj)
        run = client.jobs.submit(**payload).result()
        execution.commit(completed=True)
        return execution.to_dict()
