"""Databricks runtime (reference analog: mlrun/runtimes/databricks_job/
databricks_runtime.py — runs a wrapped script on a Databricks cluster).

Gated on the databricks-sdk; builds the run-submit payload client-side so
the control-plane shape is testable without the SDK.
"""

from __future__ import annotations

from ..model import RunObject
from .pod import KubeResource, KubeResourceSpec


class DatabricksSpec(KubeResourceSpec):
    _dict_fields = KubeResourceSpec._dict_fields + [
        "cluster_id", "new_cluster_spec", "timeout_minutes",
    ]

    def __init__(self, cluster_id=None, new_cluster_spec=None,
                 timeout_minutes=None, **kwargs):
        super().__init__(**kwargs)
        self.cluster_id = cluster_id
        self.new_cluster_spec = new_cluster_spec or {}
        self.timeout_minutes = timeout_minutes or 60


class DatabricksRuntime(KubeResource):
    kind = "databricks"
    # client-side driven like DaskRuntime: _run submits to the Databricks
    # workspace directly (no service-side resource handler involved)
    _is_remote = False
    _nested_fields = {**KubeResource._nested_fields, "spec": DatabricksSpec}

    def __init__(self, metadata=None, spec=None, status=None):
        super().__init__(metadata, spec, status)
        if not isinstance(self.spec, DatabricksSpec):
            self.spec = DatabricksSpec.from_dict(self.spec.to_dict())

    def generate_submit_payload(self, runobj: RunObject) -> dict:
        """Build the jobs/runs/submit payload (reference wrapper-script
        contract: the embedded code ships base64 inside the task params)."""
        import json

        build = self.spec.build
        code = build.functionSourceCode if build else None
        task = {
            "task_key": f"{runobj.metadata.name}-{runobj.metadata.uid[:8]}",
            "spark_python_task": {
                "python_file": self.spec.command or "dbfs:/mlrun-tpu/run.py",
                "parameters": [
                    json.dumps({
                        "run_spec": runobj.to_dict(),
                        "handler": runobj.spec.handler_name,
                        "code_b64": code,
                    }, default=str)
                ],
            },
            "timeout_seconds": self.spec.timeout_minutes * 60,
        }
        if self.spec.cluster_id:
            task["existing_cluster_id"] = self.spec.cluster_id
        else:
            task["new_cluster"] = self.spec.new_cluster_spec or {
                "num_workers": 1, "spark_version": "14.3.x-scala2.12",
                "node_type_id": "i3.xlarge"}
        return {"run_name": runobj.metadata.name, "tasks": [task]}

    def _run(self, runobj: RunObject, execution) -> dict:
        try:
            from databricks.sdk import WorkspaceClient  # gated
            from databricks.sdk.service import jobs as dbx_jobs
        except ImportError as exc:
            raise ImportError(
                "the databricks runtime requires the databricks-sdk "
                "package") from exc
        client = WorkspaceClient()
        payload = self.generate_submit_payload(runobj)
        tasks = []
        for task in payload["tasks"]:
            spark_task = dbx_jobs.SparkPythonTask(
                python_file=task["spark_python_task"]["python_file"],
                parameters=task["spark_python_task"]["parameters"])
            tasks.append(dbx_jobs.SubmitTask(
                task_key=task["task_key"],
                spark_python_task=spark_task,
                existing_cluster_id=task.get("existing_cluster_id"),
                new_cluster=dbx_jobs.ClusterSpec.from_dict(
                    task["new_cluster"]) if "new_cluster" in task else None,
                timeout_seconds=task.get("timeout_seconds")))
        run = client.jobs.submit(run_name=payload["run_name"],
                                 tasks=tasks).result()
        execution.log_result("databricks_run_id", run.run_id)
        if run.run_page_url:
            execution.log_result("databricks_run_url", run.run_page_url)
        state = run.state
        result_state = getattr(state, "result_state", None)
        if result_state is not None and str(result_state) not in (
                "RunResultState.SUCCESS", "SUCCESS"):
            execution.set_state(
                error=f"databricks run ended with {result_state}: "
                      f"{getattr(state, 'state_message', '')}")
        else:
            execution.commit(completed=True)
        return execution.to_dict()
