"""HTTP-triggered function runtimes (reference analog:
mlrun/runtimes/nuclio/function.py:253 RemoteRuntime, nuclio/application/
ApplicationRuntime). Nuclio is replaced by an ASGI graph-server process —
deploys are a service concern; ``invoke`` hits the deployed endpoint.
"""

from __future__ import annotations

import json
from typing import Optional

from ..common.runtimes_constants import RuntimeKinds
from ..model import RunObject
from ..utils import logger
from .pod import KubeResource, KubeResourceSpec


class RemoteSpec(KubeResourceSpec):
    _dict_fields = KubeResourceSpec._dict_fields + [
        "min_replicas", "max_replicas", "function_handler", "base_spec",
        "config",
    ]

    def __init__(self, min_replicas=None, max_replicas=None,
                 function_handler=None, base_spec=None, config=None, **kwargs):
        super().__init__(**kwargs)
        self.min_replicas = min_replicas or 1
        self.max_replicas = max_replicas or 4
        self.function_handler = function_handler
        self.base_spec = base_spec or {}
        self.config = config or {}


class RemoteRuntime(KubeResource):
    kind = RuntimeKinds.remote
    _is_remote = True
    _nested_fields = {**KubeResource._nested_fields, "spec": RemoteSpec}

    def __init__(self, metadata=None, spec=None, status=None):
        super().__init__(metadata, spec, status)
        if not isinstance(self.spec, RemoteSpec):
            self.spec = RemoteSpec.from_dict(self.spec.to_dict())

    def with_http(self, workers: int = 8, port: int = 0, host: str = ""):
        self.spec.config["http"] = {"workers": workers, "port": port,
                                    "host": host}
        return self

    def add_trigger(self, name: str, spec: dict):
        self.spec.config.setdefault("triggers", {})[name] = spec
        return self

    def deploy(self, project: str = "", tag: str = "", verbose: bool = False):
        """Deploy via the service and block until the gateway is live
        (reference function.py:551 — deploy returns an invocable
        function). Raises on a failed deploy with the gateway log tail."""
        db = self._get_db()
        resp = db.api_call(
            "POST", f"projects/{self.metadata.project or 'default'}/"
            f"functions/{self.metadata.name}/deploy",
            json={"function": self.to_dict()})
        data = resp.get("data", resp) if isinstance(resp, dict) else {}
        address = data.get("address", "")
        self.status.address = address
        self.status.state = data.get("state", "ready")
        if address:
            self.status.external_invocation_urls = [address]
        if self.status.state == "error":
            raise RuntimeError(
                f"function deploy failed: {data.get('error', 'unknown')}")
        logger.info("function deployed", address=address,
                    state=self.status.state)
        return address

    def undeploy(self, project: str = ""):
        """Tear the live gateway down (function status flips offline)."""
        db = self._get_db()
        db.api_call(
            "DELETE", f"projects/{self.metadata.project or 'default'}/"
            f"functions/{self.metadata.name}/deploy")
        self.status.address = ""
        self.status.state = "offline"
        self.status.external_invocation_urls = []

    def invoke(self, path: str = "/", body=None, method: str = "",
               headers: dict | None = None, dashboard: str = "",
               force_external_address: bool = False):
        """Call the deployed endpoint (reference function.py:887)."""
        import requests

        address = self.status.address
        if not address:
            raise ValueError("function is not deployed (no address)")
        if not address.startswith("http"):
            address = f"http://{address}"
        method = method or ("POST" if body is not None else "GET")
        kwargs = {}
        if isinstance(body, (dict, list)):
            kwargs["json"] = body
        elif body is not None:
            kwargs["data"] = body
        resp = requests.request(
            method, f"{address.rstrip('/')}/{path.lstrip('/')}",
            headers=headers, timeout=30, **kwargs)
        resp.raise_for_status()
        try:
            return resp.json()
        except ValueError:
            return resp.content

    def _run(self, runobj: RunObject, execution) -> dict:
        raise RuntimeError(
            "remote functions are invoked over http — use deploy() + invoke()")


class ApplicationRuntime(RemoteRuntime):
    """Generic always-on application (reference nuclio/application/)."""

    kind = RuntimeKinds.application

    def with_sidecar(self, name: str, image: str, ports: list | None = None,
                     command: list | None = None):
        self.spec.config.setdefault("sidecars", []).append({
            "name": name, "image": image, "ports": ports or [],
            "command": command or []})
        return self
