"""Dask runtime (reference analog: mlrun/runtimes/daskjob.py:186 DaskCluster).

Client-side ephemeral dask cluster for dataframe-parallel work and as a
hyper-param parallel engine. On TPU deployments this remains an
orchestration-level (CPU) engine; tensor work belongs to tpujob.
"""

from __future__ import annotations

from ..common.runtimes_constants import RuntimeKinds
from ..model import RunObject
from ..utils import logger
from .pod import KubeResource, KubeResourceSpec


class DaskSpec(KubeResourceSpec):
    _dict_fields = KubeResourceSpec._dict_fields + [
        "min_replicas", "max_replicas", "scheduler_timeout",
    ]

    def __init__(self, min_replicas=None, max_replicas=None,
                 scheduler_timeout=None, **kwargs):
        super().__init__(**kwargs)
        self.min_replicas = min_replicas or 0
        self.max_replicas = max_replicas or 4
        self.scheduler_timeout = scheduler_timeout or "60 minutes"


class DaskRuntime(KubeResource):
    kind = RuntimeKinds.dask
    _is_remote = False  # the cluster is remote, but run() drives it client-side
    _nested_fields = {**KubeResource._nested_fields, "spec": DaskSpec}

    def __init__(self, metadata=None, spec=None, status=None):
        super().__init__(metadata, spec, status)
        if not isinstance(self.spec, DaskSpec):
            self.spec = DaskSpec.from_dict(self.spec.to_dict())
        self._cluster = None

    @property
    def client(self):
        """Return a dask client — local cluster if dask is importable."""
        try:
            from dask.distributed import Client, LocalCluster
        except ImportError as exc:
            raise ImportError(
                "dask is not installed in this environment") from exc
        if self._cluster is None:
            self._cluster = LocalCluster(
                n_workers=max(1, self.spec.min_replicas or 1),
                threads_per_worker=2)
        return Client(self._cluster)

    def close(self):
        if self._cluster is not None:
            self._cluster.close()
            self._cluster = None

    def _run(self, runobj: RunObject, execution) -> dict:
        from .local import exec_from_params, load_module

        handler = runobj.spec.handler
        if not callable(handler):
            command = self.spec.command
            if not command:
                raise ValueError("dask runtime needs a handler or command")
            handler = load_module(command, runobj.spec.handler_name or "handler")
        return exec_from_params(handler, runobj, execution)
