"""Dask runtime (reference analog: mlrun/runtimes/daskjob.py:186 DaskCluster
+ the dask-kubernetes deployment flow).

Client-side ephemeral dask cluster for dataframe-parallel work and as a
hyper-param parallel engine. On TPU deployments this remains an
orchestration-level (CPU) engine; tensor work belongs to tpujob. The k8s
deployment path materializes a scheduler Deployment+Service and a worker
Deployment (built here, created through the kubernetes provider) and the
client connects to the scheduler service — no dask-operator dependency.
"""

from __future__ import annotations

import os

from ..config import mlconf
from ..common.runtimes_constants import RuntimeKinds
from ..model import RunObject
from ..utils import logger
from .pod import KubeResource, KubeResourceSpec


class DaskSpec(KubeResourceSpec):
    _dict_fields = KubeResourceSpec._dict_fields + [
        "min_replicas", "max_replicas", "scheduler_timeout",
        "scheduler_address", "worker_resources",
    ]

    def __init__(self, min_replicas=None, max_replicas=None,
                 scheduler_timeout=None, scheduler_address=None,
                 worker_resources=None, **kwargs):
        super().__init__(**kwargs)
        self.min_replicas = min_replicas or 0
        self.max_replicas = max_replicas or 4
        self.scheduler_timeout = scheduler_timeout or "60 minutes"
        # set (or discovered from the k8s service) → client connects remote
        self.scheduler_address = scheduler_address or ""
        self.worker_resources = worker_resources or {}


class DaskRuntime(KubeResource):
    kind = RuntimeKinds.dask
    _is_remote = False  # the cluster is remote, but run() drives it client-side
    _nested_fields = {**KubeResource._nested_fields, "spec": DaskSpec}

    def __init__(self, metadata=None, spec=None, status=None):
        super().__init__(metadata, spec, status)
        if not isinstance(self.spec, DaskSpec):
            self.spec = DaskSpec.from_dict(self.spec.to_dict())
        self._cluster = None
        self._client = None
        self._client_address = ""

    @property
    def client(self):
        """Return a dask client: remote when a scheduler address is set
        (e.g. after deploy_cluster), else a local cluster."""
        try:
            from dask.distributed import Client, LocalCluster
        except ImportError as exc:
            raise ImportError(
                "dask is not installed in this environment") from exc
        # cache per scheduler address: changing spec.scheduler_address (or
        # clearing it) invalidates the cached client instead of returning a
        # stale connection
        address = self.spec.scheduler_address or ""
        if self._client is not None and self._client_address == address:
            return self._client
        if self._client is not None:
            try:
                self._client.close()
            except Exception:  # noqa: BLE001
                pass
            self._client = None
        if address:
            self._client = Client(address)
        else:
            if self._cluster is None:
                self._cluster = LocalCluster(
                    n_workers=max(1, self.spec.min_replicas or 1),
                    threads_per_worker=2)
            self._client = Client(self._cluster)
        self._client_address = address
        return self._client

    def close(self):
        if self._client is not None:
            try:
                self._client.close()
            except Exception:  # noqa: BLE001 - already-dead scheduler
                pass
            self._client = None
        if self._cluster is not None:
            self._cluster.close()
            self._cluster = None

    # -- k8s deployment (reference: the dask-kubernetes cluster flow) -------
    def _cluster_name(self) -> str:
        return f"mlt-dask-{self.metadata.name or 'cluster'}"

    def generate_cluster_resources(self, namespace: str | None = None) -> dict:
        """Build the scheduler Deployment+Service and worker Deployment
        manifests (pure builders — unit-testable without a cluster)."""
        namespace = namespace or mlconf.namespace
        name = self._cluster_name()
        image = self.spec.image or mlconf.function.dask_image
        labels = {"mlrun-tpu/class": "dask", "mlrun-tpu/cluster": name}

        def deployment(component: str, command: list, replicas: int,
                       resources: dict | None = None):
            pod_labels = dict(labels, **{"mlrun-tpu/component": component})
            container = {
                "name": component,
                "image": image,
                "args": command,
                "env": [{"name": k, "value": str(v)}
                        for k, v in (self.spec.env or {}).items()]
                if isinstance(self.spec.env, dict) else (self.spec.env or []),
            }
            if resources:
                container["resources"] = {"limits": resources}
            return {
                "apiVersion": "apps/v1",
                "kind": "Deployment",
                "metadata": {"name": f"{name}-{component}",
                             "namespace": namespace,
                             "labels": labels},
                "spec": {
                    "replicas": replicas,
                    "selector": {"matchLabels": pod_labels},
                    "template": {"metadata": {"labels": pod_labels},
                                 "spec": {"containers": [container]}},
                },
            }

        scheduler = deployment(
            "scheduler", ["dask", "scheduler", "--port", "8786",
                          "--dashboard-address", ":8787"], 1)
        workers = deployment(
            "worker",
            ["dask", "worker", f"tcp://{name}-scheduler:8786"],
            max(1, self.spec.min_replicas or 1),
            resources=self.spec.worker_resources or None)
        service = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": f"{name}-scheduler",
                         "namespace": namespace, "labels": labels},
            "spec": {
                "selector": dict(labels,
                                 **{"mlrun-tpu/component": "scheduler"}),
                "ports": [
                    {"name": "scheduler", "port": 8786,
                     "targetPort": 8786},
                    {"name": "dashboard", "port": 8787,
                     "targetPort": 8787},
                ],
            },
        }
        return {"scheduler": scheduler, "workers": workers,
                "service": service}

    def deploy_cluster(self, namespace: str | None = None) -> str:
        """Create the cluster on kubernetes (gated on the kubernetes
        package) and record the scheduler address; returns it."""
        import kubernetes  # gated import

        if os.environ.get("KUBERNETES_SERVICE_HOST"):
            kubernetes.config.load_incluster_config()
        else:
            kubernetes.config.load_kube_config()
        namespace = namespace or mlconf.namespace
        resources = self.generate_cluster_resources(namespace)
        apps = kubernetes.client.AppsV1Api()
        core = kubernetes.client.CoreV1Api()
        apps.create_namespaced_deployment(namespace, resources["scheduler"])
        apps.create_namespaced_deployment(namespace, resources["workers"])
        core.create_namespaced_service(namespace, resources["service"])
        self.spec.scheduler_address = (
            f"tcp://{self._cluster_name()}-scheduler.{namespace}:8786")
        logger.info("dask cluster deployed",
                    scheduler=self.spec.scheduler_address)
        return self.spec.scheduler_address

    def delete_cluster(self, namespace: str | None = None):
        import kubernetes  # gated import

        namespace = namespace or mlconf.namespace
        name = self._cluster_name()
        apps = kubernetes.client.AppsV1Api()
        core = kubernetes.client.CoreV1Api()
        for component in ("scheduler", "worker"):
            try:
                apps.delete_namespaced_deployment(f"{name}-{component}",
                                                  namespace)
            except kubernetes.client.exceptions.ApiException:
                pass
        try:
            core.delete_namespaced_service(f"{name}-scheduler", namespace)
        except kubernetes.client.exceptions.ApiException:
            pass
        self.spec.scheduler_address = ""

    def _run(self, runobj: RunObject, execution) -> dict:
        from .local import exec_from_params, load_module

        handler = runobj.spec.handler
        if not callable(handler):
            command = self.spec.command
            if not command:
                raise ValueError("dask runtime needs a handler or command")
            handler = load_module(command, runobj.spec.handler_name or "handler")
        return exec_from_params(handler, runobj, execution)
