"""Local runtimes (reference analog: mlrun/runtimes/local.py:199 LocalRuntime,
:172 HandlerRuntime, :423 run_exec, :481 exec_from_params, :74 ParallelRunner).

Executes the handler in-process (or a python file via subprocess with the
``MLT_EXEC_CONFIG`` env contract) and captures results via ``MLClientCtx``.
"""

from __future__ import annotations

import base64
import importlib.util
import io
import json
import os
import socket
import subprocess
import sys
import tempfile
import traceback
from contextlib import redirect_stderr, redirect_stdout
from copy import deepcopy
from typing import Callable, Optional

from ..common.runtimes_constants import RunStates, RuntimeKinds
from ..config import mlconf
from ..execution import MLClientCtx
from ..model import RunObject
from ..package.context_handler import ContextHandler
from ..utils import logger
from .base import BaseRuntime


def load_module(file_name: str, handler_name: str) -> Callable:
    """Import a python file and return the named handler."""
    module_name = os.path.splitext(os.path.basename(file_name))[0]
    spec = importlib.util.spec_from_file_location(module_name, file_name)
    if spec is None:
        raise ImportError(f"cannot import {file_name}")
    module = importlib.util.module_from_spec(spec)
    sys.modules[module_name] = module
    spec.loader.exec_module(module)
    if not hasattr(module, handler_name):
        raise AttributeError(f"handler '{handler_name}' not found in {file_name}")
    return getattr(module, handler_name)


def exec_from_params(handler: Callable, runobj: RunObject, context: MLClientCtx,
                     cwd: str | None = None) -> dict:
    """Run a python handler with a context, capturing stdout into the run log
    (reference local.py:481)."""
    context_handler = ContextHandler()
    kwargs = context_handler.parse_inputs(handler, context, runobj)
    old_dir = os.getcwd()
    stdout_buf = io.StringIO()
    db = context._db
    try:
        if cwd:
            os.chdir(cwd)
        with redirect_stdout(stdout_buf):
            # hook trackers (mlflow import etc.) around the user handler
            from ..track import tracker_manager

            tracker_manager.pre_run(context)
            returned = handler(**kwargs)
            tracker_manager.post_run(context)
        context_handler.package_results(context, returned, runobj.spec.returns)
        context.commit(completed=True)
    except Exception as exc:  # noqa: BLE001 - report user errors on the run
        error_text = traceback.format_exc()
        with redirect_stdout(stdout_buf):
            print(error_text)
        context.set_state(error=str(exc), commit=True)
    finally:
        os.chdir(old_dir)
        text = stdout_buf.getvalue()
        if text:
            print(text, end="")
            if db is not None and context.is_logging_worker():
                try:
                    db.store_log(context._uid, context.project, text.encode())
                except Exception:  # noqa: BLE001 - log loss is non-fatal
                    pass
    return context.to_dict()


def run_exec(cmd: list[str], args: list[str], env: dict | None = None,
             cwd: str | None = None) -> tuple[str, str, int]:
    """Run a command-line step as a subprocess (reference local.py:423)."""
    full_cmd = list(cmd) + list(args or [])
    process = subprocess.run(
        full_cmd, capture_output=True, text=True, cwd=cwd,
        env={**os.environ, **(env or {})},
    )
    return process.stdout, process.stderr, process.returncode


class HandlerRuntime(BaseRuntime):
    """In-process callable execution (reference local.py:172)."""

    kind = RuntimeKinds.handler

    def _run(self, runobj: RunObject, execution: MLClientCtx) -> dict:
        handler = runobj.spec.handler
        if not callable(handler):
            raise ValueError("handler runtime requires a callable handler")
        execution.set_hostname(socket.gethostname())
        return exec_from_params(handler, runobj, execution)


class LocalRuntime(BaseRuntime):
    """Local file/handler execution (reference local.py:199)."""

    kind = RuntimeKinds.local
    _is_remote = False

    def to_job(self, image: str = ""):
        from .kubejob import KubejobRuntime

        job = KubejobRuntime.from_dict(self.to_dict())
        if image:
            job.spec.image = image
        return job

    def _materialize_code(self) -> Optional[str]:
        """Write embedded source (build.functionSourceCode) to a temp file."""
        build = self.spec.build
        if build and build.functionSourceCode:
            source = base64.b64decode(build.functionSourceCode).decode()
            suffix = ".py"
            fname = build.origin_filename or ""
            temp = tempfile.NamedTemporaryFile(
                suffix=suffix, delete=False, mode="w",
                prefix=os.path.splitext(os.path.basename(fname))[0] + "-"
                if fname else "handler-")
            temp.write(source)
            temp.close()
            return temp.name
        return None

    def _run(self, runobj: RunObject, execution: MLClientCtx) -> dict:
        execution.set_hostname(socket.gethostname())
        handler = runobj.spec.handler
        if not callable(handler) and callable(self._handler):
            if not handler or handler == self._handler.__name__:
                handler = self._handler
        if callable(handler):
            return exec_from_params(handler, runobj, execution,
                                    cwd=self.spec.workdir)

        command = self.spec.command
        code_file = self._materialize_code()
        if code_file:
            command = code_file
        if not command:
            raise ValueError("local runtime needs a command or embedded code")

        handler_name = runobj.spec.handler_name or self.spec.default_handler
        if handler_name and command.endswith(".py"):
            fn = load_module(command, handler_name)
            return exec_from_params(fn, runobj, execution,
                                    cwd=self.spec.workdir)

        # no handler: execute the file as a script with the env contract
        env = {
            mlconf.exec_config_env: json.dumps(runobj.to_dict(), default=str),
            "MLT_DBPATH": mlconf.get("dbpath", ""),
        }
        cmd = [sys.executable, command] if command.endswith(".py") else [command]
        stdout, stderr, rc = run_exec(cmd, self.spec.args, env=env,
                                      cwd=self.spec.workdir)
        if stdout:
            print(stdout, end="")
            if execution._db is not None:
                execution._db.store_log(
                    execution._uid, execution.project, stdout.encode())
        if rc != 0:
            execution.set_state(error=stderr[-2000:] or f"exit code {rc}")
        else:
            # the subprocess may have updated the run in the DB itself;
            # reload to pick up its results, else mark completed
            stored = None
            if execution._db is not None:
                stored = execution._db.read_run(
                    execution._uid, execution.project,
                    iter=execution.iteration)
            if stored and stored.get("status", {}).get("results"):
                return stored
            execution.commit(completed=True)
        return execution.to_dict()
