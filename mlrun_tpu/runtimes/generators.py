"""Hyper-param task generators (reference analog: mlrun/runtimes/generators.py:29
get_generator, :111 GridGenerator — fresh implementation)."""

from __future__ import annotations

import itertools
import random
from copy import deepcopy
from typing import Iterator

from ..model import HyperParamOptions, RunObject, RunSpec
from ..utils import get_in

default_max_iterations = 10
default_max_errors = 3


class TaskGenerator:
    def __init__(self, options: HyperParamOptions | None = None):
        self.options = options or HyperParamOptions()

    def generate(self, run: RunObject) -> Iterator[RunObject]:
        raise NotImplementedError

    @property
    def max_errors(self) -> int:
        return self.options.max_errors or default_max_errors

    def use_parallel(self) -> bool:
        return bool(self.options.parallel_runs)

    def eval_stop_condition(self, results: dict) -> bool:
        condition = self.options.stop_condition
        if not condition:
            return False
        from ..utils.safe_eval import safe_eval

        try:
            return bool(safe_eval(condition, results))
        except Exception:  # noqa: BLE001 - bad condition never stops the sweep
            return False

    @staticmethod
    def _child(run: RunObject, params: dict, iteration: int) -> RunObject:
        child = deepcopy(run)
        child.spec.hyperparams = None
        child.spec.hyper_param_options = None
        child.spec.parameters = dict(run.spec.parameters or {})
        child.spec.parameters.update(params)
        child.metadata.iteration = iteration
        return child


class GridGenerator(TaskGenerator):
    """Cartesian product of all hyper-param lists."""

    def generate(self, run: RunObject) -> Iterator[RunObject]:
        hyperparams = run.spec.hyperparams or {}
        keys = list(hyperparams.keys())
        for iteration, values in enumerate(
                itertools.product(*hyperparams.values()), start=1):
            yield self._child(run, dict(zip(keys, values)), iteration)


class ListGenerator(TaskGenerator):
    """Zip of equal-length hyper-param lists."""

    def generate(self, run: RunObject) -> Iterator[RunObject]:
        hyperparams = run.spec.hyperparams or {}
        lengths = {len(v) for v in hyperparams.values()}
        if len(lengths) > 1:
            raise ValueError(
                f"list strategy requires equal-length lists, got {lengths}")
        keys = list(hyperparams.keys())
        for iteration, values in enumerate(zip(*hyperparams.values()), start=1):
            yield self._child(run, dict(zip(keys, values)), iteration)


class RandomGenerator(TaskGenerator):
    """Random sampling from the grid up to max_iterations."""

    def generate(self, run: RunObject) -> Iterator[RunObject]:
        hyperparams = run.spec.hyperparams or {}
        max_iterations = self.options.max_iterations or default_max_iterations
        for iteration in range(1, max_iterations + 1):
            params = {k: random.choice(v) for k, v in hyperparams.items()}
            yield self._child(run, params, iteration)


def load_params_file(run: RunObject) -> dict:
    """Load hyper params from a csv/json param file (options.param_file)."""
    import json

    from ..datastore import store_manager

    url = run.spec.hyper_param_options.param_file
    item = store_manager.object(url=url)
    if url.endswith(".csv"):
        df = item.as_df()
        return {c: df[c].tolist() for c in df.columns}
    return json.loads(item.get(encoding="utf-8"))


def get_generator(spec: RunSpec, execution=None,
                  param_file_secrets=None) -> TaskGenerator | None:
    options = spec.hyper_param_options or HyperParamOptions()
    if not spec.hyperparams and not options.param_file:
        return None
    strategy = options.strategy or "grid"
    generator_cls = {
        "grid": GridGenerator,
        "list": ListGenerator,
        "random": RandomGenerator,
    }.get(strategy)
    if generator_cls is None:
        raise ValueError(f"unsupported hyper-param strategy '{strategy}'")
    return generator_cls(options)


def selector_value(results: dict, selector: str):
    """Parse 'max.accuracy' / 'min.loss' selectors; return (op, key)."""
    if not selector:
        return None, None
    if "." in selector:
        op, key = selector.split(".", 1)
    else:
        op, key = "max", selector
    if op not in ("max", "min"):
        raise ValueError(f"selector op must be max|min, got '{op}'")
    return op, key


def select_best_iteration(iteration_results: list[dict], selector: str) -> int:
    """Return best iteration number given [{iter, results...}] rows."""
    op, key = selector_value({}, selector)
    if not key:
        return 0
    best_iter, best_value = 0, None
    for row in iteration_results:
        results = row.get("results") or {}
        if key not in results:
            continue
        value = results[key]
        better = (
            best_value is None
            or (op == "max" and value > best_value)
            or (op == "min" and value < best_value)
        )
        if better:
            best_iter, best_value = row.get("iter", 0), value
    return best_iter
