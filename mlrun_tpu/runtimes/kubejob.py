"""Kubernetes job runtime (reference analog: mlrun/runtimes/kubejob.py:27
KubejobRuntime — client side; the pod is created by the service's runtime
handler, reference server/api/runtime_handlers/kubejob.py:45)."""

from __future__ import annotations

from ..common.runtimes_constants import RuntimeKinds
from ..model import RunObject
from ..utils import logger
from .pod import KubeResource


class KubejobRuntime(KubeResource):
    kind = RuntimeKinds.job
    _is_remote = True

    @property
    def is_deployed(self) -> bool:
        """True when the function image exists (reference kubejob.py:115)."""
        if self.spec.image:
            return True
        build = self.spec.build
        return not (build and (build.source or build.commands
                               or build.requirements))

    def build_config(self, image: str = "", base_image: str = "",
                     commands: list | None = None, requirements: list | None = None,
                     source: str = ""):
        build = self.spec.build
        build.image = image or build.image
        build.base_image = base_image or build.base_image
        if commands:
            build.commands = (build.commands or []) + list(commands)
        if requirements:
            build.requirements = (build.requirements or []) + list(requirements)
        build.source = source or build.source
        return self

    def deploy(self, watch: bool = True, with_tpu: bool = False,
               skip_deployed: bool = False) -> bool:
        """Request a remote build from the service (reference
        kubejob.py:144; server side is service/builder.py — a venv-cache
        pre-warm locally or a Kaniko pod on kubernetes). With ``watch``
        the call blocks on `/build/status` streaming the build log until
        the build reaches a terminal state."""
        if skip_deployed and self.is_deployed:
            return True
        db = self._get_db()
        resp = db.remote_builder(self, with_tpu=with_tpu)
        status = resp.get("data", {}).get("status", {})
        self.spec.image = status.get("image") or self.spec.image
        state = status.get("state", "ready")
        if watch and state == "deploying":
            state = self._watch_build(db)
        logger.info("function build finished", image=self.spec.image,
                    state=state)
        return state == "ready"

    def _watch_build(self, db, timeout: float = 1800.0) -> str:
        import sys
        import time

        offset = 0
        deadline = time.time() + timeout
        state = "deploying"
        while time.time() < deadline:
            resp = db.get_builder_status(self, offset=offset)
            data = resp.get("data", resp) if isinstance(resp, dict) else {}
            log = data.get("log", "")
            if log:
                sys.stdout.write(log)
                sys.stdout.flush()
            offset = data.get("offset", offset)
            state = data.get("state", state)
            if state in ("ready", "error"):
                self.spec.image = data.get("image") or self.spec.image
                return state
            time.sleep(1.0)
        return state

    def _run(self, runobj: RunObject, execution) -> dict:
        # runs happen server-side; reaching here means misconfiguration
        # (reference kubejob.py:214 raises the same way)
        raise RuntimeError(
            "the job runtime executes on the cluster — configure MLT_DBPATH "
            "to point at the service, or pass local=True to run in-process")
