from .api import (  # noqa: F401
    OfflineVectorResponse,
    OnlineVectorService,
    get_offline_features,
    get_online_feature_service,
    ingest,
    preview,
)
from .feature_set import (  # noqa: F401
    Entity,
    Feature,
    FeatureSet,
    FeatureVector,
)
from .ingestion_service import (  # noqa: F401
    FeatureSetIngestStep,
    ingestion_service_function,
)
from .steps import apply_aggregations, apply_transforms  # noqa: F401
