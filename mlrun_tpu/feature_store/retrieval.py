"""Offline feature-vector merge engines.

Reference analog: mlrun/feature_store/retrieval/base.py:30 (BaseMerger),
local_merger.py (pandas), dask_merger.py, spark_merger.py. The seam is the
same — an engine name selects a merger class that loads each feature set,
joins on shared entity columns, and finalizes (drop columns / indexes) — but
the implementations are fresh:

- ``local``: in-memory pandas joins (reference LocalFeatureMerger).
- ``partitioned``: out-of-core hash-partitioned merge — streams parquet in
  row-group batches, buckets rows by entity-key hash into on-disk
  partitions, then joins partitions concurrently. Scales past RAM on one
  TPU host without any extra dependency (the niche dask fills upstream).
- ``dask``: dask.dataframe joins (gated on the dask package).
- ``spark``: pyspark joins (gated on the pyspark package).
"""

from __future__ import annotations

import os
import tempfile
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import pandas as pd

from ..utils import logger
from .feature_set import FeatureSet, FeatureVector


class BaseMerger:
    """Template: load each feature set → left-join on shared entity columns
    → finalize. Subclasses override the frame type via _load/_join/_collect.
    """

    engine = "base"
    support_online = False

    def __init__(self, vector: FeatureVector, project: str = ""):
        self.vector = vector
        self.project = project
        self._entity_columns: set[str] = set()

    # -- frame ops (subclass seam) ------------------------------------------
    def _load(self, fset: FeatureSet, columns: Optional[list[str]]):
        """Return the engine's frame for a feature set (all or the listed
        columns)."""
        raise NotImplementedError

    def _join(self, left, right, keys: list[str]):
        raise NotImplementedError

    def _collect(self, frame) -> pd.DataFrame:
        """Materialize the engine frame into pandas."""
        return frame

    def _from_pandas(self, df: pd.DataFrame):
        """Wrap caller-provided entity rows into the engine's frame type."""
        return df

    # -- template -----------------------------------------------------------
    def _resolve(self, name: str) -> FeatureSet:
        from .api import _resolve_feature_set

        return _resolve_feature_set(name, project=self.project)

    def merge(self, entity_rows: pd.DataFrame | None = None,
              drop_columns: list | None = None,
              with_indexes: bool = False) -> pd.DataFrame:
        try:
            return self._merge(entity_rows, drop_columns, with_indexes)
        finally:
            self._cleanup()

    def _cleanup(self):
        pass

    def _merge(self, entity_rows, drop_columns, with_indexes) -> pd.DataFrame:
        merged = None
        if entity_rows is not None:
            merged = self._from_pandas(entity_rows)
        for set_name, feature in self.vector.parse_features():
            fset = self._resolve(set_name)
            entities = fset.entity_names
            self._entity_columns.update(entities)
            columns = None if feature == "*" else entities + [feature]
            frame = self._load(fset, columns)
            if merged is None:
                merged = frame
                continue
            join_keys = [c for c in entities if c in self._columns(merged)]
            if not join_keys:
                raise ValueError(
                    f"no common entity columns to join feature set "
                    f"'{set_name}' (entities={entities})")
            merged = self._join(merged, frame, join_keys)
        if merged is None:
            raise ValueError("feature vector has no features")
        if self.vector.spec.label_feature:
            set_name, feature = self.vector.spec.label_feature.rsplit(".", 1)
            fset = self._resolve(set_name)
            self._entity_columns.update(fset.entity_names)
            frame = self._load(fset, fset.entity_names + [feature])
            join_keys = [c for c in fset.entity_names
                         if c in self._columns(merged)]
            merged = self._join(merged, frame, join_keys)
        result = self._collect(merged)
        if drop_columns:
            result = result.drop(columns=[c for c in drop_columns
                                          if c in result.columns])
        if not (with_indexes or self.vector.spec.with_indexes):
            result = result.drop(columns=[c for c in self._entity_columns
                                          if c in result.columns])
        return result

    def _columns(self, frame) -> list[str]:
        return list(frame.columns)


class LocalFeatureMerger(BaseMerger):
    """In-memory pandas joins (reference retrieval/local_merger.py)."""

    engine = "local"

    def _load(self, fset: FeatureSet, columns):
        df = fset.to_dataframe()
        return df if columns is None else df[columns]

    def _join(self, left, right, keys):
        return left.merge(right, on=keys, how="left")


class PartitionedFeatureMerger(BaseMerger):
    """Out-of-core merge: hash-partition every frame by entity key into
    on-disk buckets (streaming parquet row groups), then join buckets
    concurrently and concatenate. Peak memory is O(rows / partitions),
    so vectors larger than RAM merge on a single host."""

    engine = "partitioned"

    def __init__(self, vector, project: str = "", partitions: int = 8,
                 batch_rows: int = 65536):
        super().__init__(vector, project)
        self.partitions = partitions
        self.batch_rows = batch_rows
        self._tmp = tempfile.mkdtemp(prefix="mlt-merge-")

    def _cleanup(self):
        import shutil

        shutil.rmtree(self._tmp, ignore_errors=True)

    # frame markers: ("__pandas__", df) | ("__fset__", (fset, columns)) |
    # ("__dir__", (dir_path, keys_tuple)) — a partition dir remembers the
    # key set its buckets were hashed on, so a later join on different keys
    # re-buckets instead of silently aligning mismatched buckets
    def _from_pandas(self, df: pd.DataFrame):
        return ("__pandas__", df)

    def _load(self, fset: FeatureSet, columns):
        return ("__fset__", (fset, columns))

    def _hash_bucket(self, keys_frame: pd.DataFrame, keys) -> pd.Series:
        buckets = pd.util.hash_pandas_object(
            keys_frame[keys].astype(str).agg("|".join, axis=1), index=False)
        return (buckets % self.partitions).astype(int)

    def _new_dir(self, prefix: str) -> str:
        return tempfile.mkdtemp(prefix=prefix + "-", dir=self._tmp)

    def _partition_frame(self, df: pd.DataFrame, keys, out_dir: str,
                         seq: int):
        """Write one streamed batch into per-bucket part files. Each batch
        appends a NEW file ({bucket}-{seq}.parquet) — no re-read/rewrite of
        accumulated buckets, so total IO stays linear in the data size."""
        buckets = self._hash_bucket(df, keys)
        for bucket, chunk in df.groupby(buckets):
            chunk.to_parquet(
                os.path.join(out_dir, f"{bucket:04d}-{seq:06d}.parquet"),
                index=False)

    def _bucket_frame(self, dir_path: str, bucket: int
                      ) -> pd.DataFrame | None:
        parts = sorted(p for p in os.listdir(dir_path)
                       if p.startswith(f"{bucket:04d}-"))
        if not parts:
            return None
        return pd.concat(
            [pd.read_parquet(os.path.join(dir_path, p)) for p in parts],
            ignore_index=True)

    def _iter_source_batches(self, frame):
        """Yield pandas batches from any frame marker without loading
        single-file parquet sources whole."""
        kind, payload = frame
        if kind == "__pandas__":
            yield payload
            return
        if kind == "__dir__":
            dir_path, _ = payload
            for bucket in range(self.partitions):
                df = self._bucket_frame(dir_path, bucket)
                if df is not None:
                    yield df
            return
        fset, columns = payload
        path = fset._target_path()
        if os.path.isfile(path):
            import pyarrow.parquet as pq

            pf = pq.ParquetFile(path)
            for batch in pf.iter_batches(batch_size=self.batch_rows):
                df = batch.to_pandas()
                yield df if columns is None else df[columns]
            return
        # directory target (e.g. dask-ingested part files) or non-parquet
        df = fset.to_dataframe()
        yield df if columns is None else df[columns]

    def _materialize(self, frame, keys) -> str:
        """Turn a frame marker into a partition dir bucketed on ``keys``."""
        kind, payload = frame
        if kind == "__dir__" and tuple(payload[1]) == tuple(keys):
            return payload[0]
        out_dir = self._new_dir("part")
        for seq, df in enumerate(self._iter_source_batches(frame)):
            self._partition_frame(df, keys, out_dir, seq)
        return out_dir

    def _join(self, left, right, keys):
        left_dir = self._materialize(left, keys)
        right_dir = self._materialize(right, keys)
        out_dir = self._new_dir("join")

        def join_bucket(bucket: int):
            ldf = self._bucket_frame(left_dir, bucket)
            if ldf is None:
                return
            rdf = self._bucket_frame(right_dir, bucket)
            out = ldf if rdf is None else ldf.merge(rdf, on=keys, how="left")
            out.to_parquet(
                os.path.join(out_dir, f"{bucket:04d}-000000.parquet"),
                index=False)

        with ThreadPoolExecutor(max_workers=min(8, self.partitions)) as pool:
            list(pool.map(join_bucket, range(self.partitions)))
        return ("__dir__", (out_dir, tuple(keys)))

    def _collect(self, frame) -> pd.DataFrame:
        kind, payload = frame
        if kind == "__pandas__":
            return payload
        if kind == "__fset__":
            fset, columns = payload
            df = fset.to_dataframe()
            return df if columns is None else df[columns]
        dir_path, _ = payload
        frames = [df for df in (self._bucket_frame(dir_path, b)
                                for b in range(self.partitions))
                  if df is not None]
        return pd.concat(frames, ignore_index=True) if frames else \
            pd.DataFrame()

    def _columns(self, frame) -> list[str]:
        kind, payload = frame
        if kind == "__pandas__":
            return list(payload.columns)
        if kind == "__fset__":
            fset, columns = payload
            if columns is not None:
                return columns
            return list(fset.to_dataframe().columns)
        dir_path, _ = payload
        for name in sorted(os.listdir(dir_path)):
            return list(pd.read_parquet(
                os.path.join(dir_path, name)).columns)
        return []


class DaskFeatureMerger(BaseMerger):
    """dask.dataframe joins (reference retrieval/dask_merger.py); gated on
    the dask package."""

    engine = "dask"

    def __init__(self, vector, project: str = "", npartitions: int = 4):
        super().__init__(vector, project)
        import dask.dataframe as dd  # gated import

        self._dd = dd
        self.npartitions = npartitions

    def _from_pandas(self, df: pd.DataFrame):
        return self._dd.from_pandas(df, npartitions=self.npartitions)

    def _load(self, fset: FeatureSet, columns):
        path = fset._target_path()
        if os.path.exists(path):
            ddf = self._dd.read_parquet(path)
        else:
            ddf = self._dd.from_pandas(fset.to_dataframe(),
                                       npartitions=self.npartitions)
        return ddf if columns is None else ddf[columns]

    def _join(self, left, right, keys):
        return left.merge(right, on=keys, how="left")

    def _collect(self, frame) -> pd.DataFrame:
        return frame.compute()


class SparkFeatureMerger(BaseMerger):
    """pyspark joins (reference retrieval/spark_merger.py); gated on the
    pyspark package."""

    engine = "spark"

    def __init__(self, vector, project: str = "", spark_session=None):
        super().__init__(vector, project)
        if spark_session is None:
            from pyspark.sql import SparkSession  # gated import

            spark_session = SparkSession.builder \
                .appName("mlrun-tpu-merge").getOrCreate()
        self.spark = spark_session

    def _from_pandas(self, df: pd.DataFrame):
        return self.spark.createDataFrame(df)

    def _load(self, fset: FeatureSet, columns):
        path = fset._target_path()
        if os.path.exists(path):
            sdf = self.spark.read.parquet(path)
        else:
            sdf = self.spark.createDataFrame(fset.to_dataframe())
        return sdf if columns is None else sdf.select(columns)

    def _join(self, left, right, keys):
        return left.join(right, on=keys, how="left")

    def _collect(self, frame) -> pd.DataFrame:
        return frame.toPandas()


_MERGERS = {
    "local": LocalFeatureMerger,
    "partitioned": PartitionedFeatureMerger,
    "dask": DaskFeatureMerger,
    "spark": SparkFeatureMerger,
}


def get_merger(engine: str, vector: FeatureVector, project: str = "",
               **kwargs) -> BaseMerger:
    cls = _MERGERS.get(engine or "local")
    if cls is None:
        raise ValueError(
            f"unknown offline merge engine '{engine}' "
            f"(one of {sorted(_MERGERS)})")
    try:
        return cls(vector, project=project, **kwargs)
    except ImportError as exc:
        raise ImportError(
            f"merge engine '{engine}' needs an optional dependency: {exc}"
        ) from exc
