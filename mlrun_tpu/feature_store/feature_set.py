"""Feature store objects (reference analog: mlrun/feature_store/feature_set.py:71
FeatureSet, feature_vector.py:468 FeatureVector, :910 OnlineVectorService)."""

from __future__ import annotations

import os
from typing import Optional

from ..config import mlconf
from ..model import ModelObj
from ..utils import generate_uid, logger, now_iso


class Entity(ModelObj):
    _dict_fields = ["name", "value_type", "labels"]

    def __init__(self, name=None, value_type=None, labels=None):
        self.name = name
        self.value_type = value_type or "str"
        self.labels = labels or {}


class Feature(ModelObj):
    _dict_fields = ["name", "value_type", "labels", "aggregate"]

    def __init__(self, name=None, value_type=None, labels=None, aggregate=None):
        self.name = name
        self.value_type = value_type or "float"
        self.labels = labels or {}
        self.aggregate = aggregate


class FeatureSetSpec(ModelObj):
    _dict_fields = ["entities", "features", "targets", "timestamp_key",
                    "description", "engine", "label_column", "source",
                    "aggregations", "transforms"]

    def __init__(self, entities=None, features=None, targets=None,
                 timestamp_key=None, description=None, engine=None,
                 label_column=None, source=None, aggregations=None,
                 transforms=None):
        self.entities = entities or []
        self.features = features or []
        self.targets = targets or []
        self.timestamp_key = timestamp_key
        self.description = description
        self.engine = engine or "pandas"
        self.label_column = label_column
        self.source = source
        self.aggregations = aggregations or []
        self.transforms = transforms or []


class FeatureSetStatus(ModelObj):
    _dict_fields = ["state", "targets", "stats", "preview"]

    def __init__(self, state=None, targets=None, stats=None, preview=None):
        self.state = state or "created"
        self.targets = targets or []
        self.stats = stats or {}
        self.preview = preview


class FeatureSet(ModelObj):
    kind = "FeatureSet"
    _dict_fields = ["kind", "metadata", "spec", "status"]

    def __init__(self, name: str = "", description: str = "",
                 entities: list | None = None, timestamp_key: str = "",
                 engine: str = "pandas", label_column: str = ""):
        from ..artifacts.base import ArtifactMetadata

        self.metadata = ArtifactMetadata(key=name)
        self.metadata.name = name
        self.spec = FeatureSetSpec(
            entities=[e if isinstance(e, dict) else
                      (e.to_dict() if isinstance(e, Entity)
                       else {"name": e}) for e in (entities or [])],
            timestamp_key=timestamp_key, description=description,
            engine=engine, label_column=label_column)
        self.status = FeatureSetStatus()

    @classmethod
    def from_dict(cls, struct=None, deprecated_fields=None):
        struct = struct or {}
        obj = cls(name=struct.get("metadata", {}).get("name", ""))
        obj.spec = FeatureSetSpec.from_dict(struct.get("spec", {}))
        obj.status = FeatureSetStatus.from_dict(struct.get("status", {}))
        meta = struct.get("metadata", {})
        for key, value in meta.items():
            setattr(obj.metadata, key, value)
        return obj

    def to_dict(self, exclude=None):
        return {
            "kind": self.kind,
            "metadata": {"name": self.name,
                         "project": getattr(self.metadata, "project", None),
                         "tag": getattr(self.metadata, "tag", None),
                         "uid": getattr(self.metadata, "uid", None)},
            "spec": self.spec.to_dict(),
            "status": self.status.to_dict(),
        }

    @property
    def name(self) -> str:
        return getattr(self.metadata, "name", None) or self.metadata.key

    @property
    def uri(self) -> str:
        project = getattr(self.metadata, "project", None) or \
            mlconf.default_project
        return f"store://feature-sets/{project}/{self.name}"

    @property
    def entity_names(self) -> list[str]:
        return [e.get("name") for e in self.spec.entities]

    def add_entity(self, name: str, value_type: str = "str"):
        self.spec.entities.append({"name": name, "value_type": value_type})
        return self

    def add_feature(self, name: str, value_type: str = "float"):
        self.spec.features.append({"name": name, "value_type": value_type})
        return self

    def set_targets(self, targets: list | None = None,
                    with_defaults: bool = True):
        self.spec.targets = targets if targets is not None else (
            ["parquet"] if with_defaults else [])
        return self

    def add_aggregation(self, column: str, operations: list[str],
                        windows: list[str] | None = None,
                        name: str | None = None):
        """Windowed aggregation (reference FeatureSet.add_aggregation):
        produces <name>_<op>_<window> features at ingest."""
        self.spec.aggregations.append({
            "name": name or column, "column": column,
            "operations": list(operations),
            "windows": list(windows) if windows else []})
        return self

    def add_transform_step(self, step):
        """Append a transform step instance or {class_name, class_args};
        instances are stored in serializable dict form so the feature set
        survives the DB roundtrip."""
        from .steps import step_to_dict

        self.spec.transforms.append(step_to_dict(step))
        return self

    def _target_path(self, project: str | None = None) -> str:
        project = project or getattr(self.metadata, "project", None) or \
            mlconf.default_project
        return os.path.join(mlconf.home_dir, "feature-store", project,
                            f"{self.name}.parquet")

    def to_dataframe(self, columns=None):
        import pandas as pd

        path = (self.status.targets[0].get("path")
                if self.status.targets else self._target_path())
        df = pd.read_parquet(path)
        if columns:
            df = df[columns]
        return df

    def save(self, tag: str = "", versioned: bool = True):
        from ..db import get_run_db

        self.metadata.tag = tag or getattr(self.metadata, "tag", None) \
            or "latest"
        get_run_db().store_feature_set(
            self.to_dict(), name=self.name,
            project=getattr(self.metadata, "project", "") or "",
            tag=self.metadata.tag)
        return self


class FeatureVectorSpec(ModelObj):
    _dict_fields = ["features", "label_feature", "description",
                    "with_indexes"]

    def __init__(self, features=None, label_feature=None, description=None,
                 with_indexes=None):
        self.features = features or []  # ["set_name.feature" | "set.*"]
        self.label_feature = label_feature
        self.description = description
        self.with_indexes = with_indexes


class FeatureVector(ModelObj):
    kind = "FeatureVector"
    _dict_fields = ["kind", "metadata", "spec", "status"]

    def __init__(self, name: str = "", features: list | None = None,
                 label_feature: str = "", description: str = "",
                 with_indexes: bool = False):
        from ..artifacts.base import ArtifactMetadata

        self.metadata = ArtifactMetadata(key=name)
        self.metadata.name = name
        self.spec = FeatureVectorSpec(
            features=features or [], label_feature=label_feature,
            description=description, with_indexes=with_indexes)
        self.status = FeatureSetStatus()

    @classmethod
    def from_dict(cls, struct=None, deprecated_fields=None):
        struct = struct or {}
        obj = cls(name=struct.get("metadata", {}).get("name", ""))
        obj.spec = FeatureVectorSpec.from_dict(struct.get("spec", {}))
        meta = struct.get("metadata", {})
        for key, value in meta.items():
            setattr(obj.metadata, key, value)
        return obj

    def to_dict(self, exclude=None):
        return {
            "kind": self.kind,
            "metadata": {"name": self.name,
                         "project": getattr(self.metadata, "project", None),
                         "tag": getattr(self.metadata, "tag", None)},
            "spec": self.spec.to_dict(),
            "status": self.status.to_dict(),
        }

    @property
    def name(self) -> str:
        return getattr(self.metadata, "name", None) or self.metadata.key

    @property
    def uri(self) -> str:
        project = getattr(self.metadata, "project", None) or \
            mlconf.default_project
        return f"store://feature-vectors/{project}/{self.name}"

    def parse_features(self) -> list[tuple[str, str]]:
        """Return [(feature_set_name, feature_or_star)]."""
        out = []
        for ref in self.spec.features:
            if "." not in ref:
                raise ValueError(
                    f"feature reference '{ref}' must be '<set>.<feature>'")
            set_name, feature = ref.rsplit(".", 1)
            out.append((set_name, feature))
        return out

    def save(self, tag: str = "", versioned: bool = True):
        from ..db import get_run_db

        self.metadata.tag = tag or getattr(self.metadata, "tag", None) \
            or "latest"
        get_run_db().store_feature_vector(
            self.to_dict(), name=self.name,
            project=getattr(self.metadata, "project", "") or "",
            tag=self.metadata.tag)
        return self
