"""Feature-set transform steps + aggregations (reference analog:
mlrun/feature_store/steps.py:94-699 transform steps and FeatureSet
aggregations — reduced to the pandas engine).

A feature set may declare a transform graph (map/filter/one-hot/imputer) and
windowed aggregations; ``apply_transforms``/``apply_aggregations`` run them
during ingest.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np
import pandas as pd

from ..utils import logger


class MapValues:
    """Map column values through a dict with optional default
    (reference steps.py MapValues).

    NOTE all step classes keep attribute names == __init__ parameter names:
    serialization stores vars(step) as class_args and reconstructs by
    calling __init__ with them (see step_to_dict).
    """

    def __init__(self, mapping: dict, with_original_features: bool = True,
                 suffix: str = "_mapped"):
        self.mapping = mapping
        self.with_original_features = with_original_features
        self.suffix = suffix

    def do(self, df: pd.DataFrame) -> pd.DataFrame:
        for column, column_map in self.mapping.items():
            if column not in df.columns:
                continue
            default = column_map.get("default")
            target = (f"{column}{self.suffix}"
                      if self.with_original_features else column)
            df[target] = df[column].map(
                {k: v for k, v in column_map.items() if k != "default"})
            if default is not None:
                df[target] = df[target].fillna(default)
        return df


class Imputer:
    """Fill missing values by method or constant (reference steps.py Imputer)."""

    def __init__(self, method: str = "avg", default_value=None,
                 mapping: dict | None = None):
        self.method = method
        self.default_value = default_value
        self.mapping = mapping or {}

    def do(self, df: pd.DataFrame) -> pd.DataFrame:
        for column in df.columns:
            if not df[column].isna().any():
                continue
            value = self.mapping.get(column, self.default_value)
            if value is None and df[column].dtype.kind in "if":
                if self.method == "avg":
                    value = df[column].mean()
                elif self.method == "median":
                    value = df[column].median()
                elif self.method == "mode":
                    modes = df[column].mode()
                    value = modes.iloc[0] if len(modes) else None
            if value is not None and pd.isna(value):
                value = None  # all-NaN column: nothing to impute from
            if value is not None:
                df[column] = df[column].fillna(value)
        return df


class OneHotEncoder:
    """Expand categorical columns (reference steps.py OneHotEncoder)."""

    def __init__(self, mapping: dict):
        self.mapping = mapping  # column -> list of categories

    def do(self, df: pd.DataFrame) -> pd.DataFrame:
        for column, categories in self.mapping.items():
            if column not in df.columns:
                continue
            for category in categories:
                df[f"{column}_{category}"] = (
                    df[column] == category).astype(int)
            df = df.drop(columns=[column])
        return df


class DropFeatures:
    def __init__(self, features: list):
        self.features = features

    def do(self, df: pd.DataFrame) -> pd.DataFrame:
        return df.drop(columns=[c for c in self.features if c in df.columns])


class FilterRows:
    """Keep rows matching a pandas query expression."""

    def __init__(self, query: str):
        self.query = query

    def do(self, df: pd.DataFrame) -> pd.DataFrame:
        return df.query(self.query)


class FeaturesetValidator:
    """Value-range validation; violations are logged (and optionally raise)."""

    def __init__(self, checks: dict | None = None, raise_on_fail: bool = False):
        # checks: column -> {min, max}
        self.checks = checks or {}
        self.raise_on_fail = raise_on_fail

    def do(self, df: pd.DataFrame) -> pd.DataFrame:
        for column, bounds in self.checks.items():
            if column not in df.columns:
                continue
            bad = pd.Series(False, index=df.index)
            if "min" in bounds:
                bad |= df[column] < bounds["min"]
            if "max" in bounds:
                bad |= df[column] > bounds["max"]
            count = int(bad.sum())
            if count:
                message = (f"validation failed: {count} rows of "
                           f"'{column}' outside {bounds}")
                if self.raise_on_fail:
                    raise ValueError(message)
                logger.warning(message)
        return df


_step_classes = {
    "MapValues": MapValues,
    "Imputer": Imputer,
    "OneHotEncoder": OneHotEncoder,
    "DropFeatures": DropFeatures,
    "FilterRows": FilterRows,
    "FeaturesetValidator": FeaturesetValidator,
}


def step_to_dict(step) -> dict:
    """Serializable form {class_name, class_args}. Step classes keep
    attribute names == __init__ parameter names to make this lossless."""
    if isinstance(step, dict):
        return step
    return {"class_name": type(step).__name__,
            "class_args": {k: v for k, v in vars(step).items()
                           if not k.startswith("_")}}


def resolve_step(step):
    if hasattr(step, "do"):
        return step
    if isinstance(step, dict):
        cls = _step_classes.get(step.get("class_name"))
        if cls is None:
            raise ValueError(f"unknown transform step {step}")
        return cls(**step.get("class_args", {}))
    raise ValueError(f"unsupported transform step {step!r}")


def apply_transforms(df: pd.DataFrame, steps: list) -> pd.DataFrame:
    for step in steps or []:
        df = resolve_step(step).do(df)
    return df


_AGG_FUNCS = {
    "sum": "sum", "avg": "mean", "mean": "mean", "min": "min", "max": "max",
    "count": "count", "std": "std", "var": "var", "last": "last",
    "first": "first",
}


def apply_aggregations(df: pd.DataFrame, aggregations: list,
                       entities: list[str], timestamp_key: str | None
                       ) -> pd.DataFrame:
    """Windowed aggregations (reference FeatureSet.add_aggregation):
    each spec {name, column, operations, windows} adds
    ``<name>_<op>_<window>`` columns — a rolling time window per entity when
    a timestamp is set, else a full-history aggregate per entity.
    """
    if not aggregations:
        return df
    if timestamp_key and timestamp_key in df.columns:
        df = df.sort_values(timestamp_key)
    for spec in aggregations:
        name = spec.get("name") or spec["column"]
        column = spec["column"]
        operations = spec.get("operations", ["avg"])
        windows = spec.get("windows", ["1h"]) or [None]
        if column not in df.columns:
            logger.warning("aggregation column missing", column=column)
            continue
        for window in windows:
            for op in operations:
                func = _AGG_FUNCS.get(op)
                if func is None:
                    raise ValueError(f"unsupported aggregation op '{op}'")
                out = f"{name}_{op}_{window}" if window else f"{name}_{op}"
                if timestamp_key and window and timestamp_key in df.columns:
                    def rolling(group):
                        g = group.set_index(timestamp_key)[column]
                        r = getattr(g.rolling(window), func)()
                        r.index = group.index  # align to original rows
                        return r

                    if entities:
                        # manual group loop: groupby.apply can unstack a
                        # returned Series into a frame for single groups
                        parts = [rolling(group) for _, group
                                 in df.groupby(entities)]
                        values = pd.concat(parts)
                    else:
                        values = rolling(df)
                    df[out] = values  # index-aligned assignment
                else:
                    if entities:
                        df[out] = df.groupby(entities)[column].transform(func)
                    else:
                        df[out] = getattr(df[column], func)()
    return df
