"""Real-time ingestion service (reference analog: mlrun/feature_store/api.py
:920 deploy_ingestion_service_v2 — a deployed stream processor that ingests
events into the feature set's targets).

Here the ingestion service is a serving-graph step (``FeatureSetIngestStep``)
that applies the feature set's transform graph per event and writes to the
online KV + appends to the offline parquet; ``ingestion_service_function``
builds a ready-to-deploy serving function around it.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

import pandas as pd

from ..config import mlconf
from ..utils import logger, now_iso
from .feature_set import FeatureSet
from .steps import apply_transforms


class FeatureSetIngestStep:
    """Serving-graph step: event body (dict or list of dicts) → ingest."""

    def __init__(self, context=None, name: str | None = None,
                 feature_set: str = "", project: str = "",
                 flush_every: int = 32, **kwargs):
        from ..datastore.targets import NoSqlTarget

        self.context = context
        self.name = name
        if not feature_set:
            raise ValueError("FeatureSetIngestStep needs a feature_set name")
        from ..db import get_run_db

        struct = get_run_db().get_feature_set(feature_set, project=project)
        self.fset = FeatureSet.from_dict(struct)
        self.entities = self.fset.entity_names
        self.flush_every = flush_every
        self._buffer: list[dict] = []
        self._lock = threading.Lock()
        self._kv = NoSqlTarget()
        self._kv.path = self._kv.default_path(
            project or getattr(self.fset.metadata, "project", None)
            or mlconf.default_project, self.fset.name)

    def do(self, body):
        rows = body if isinstance(body, list) else [body]
        frame = pd.DataFrame(rows)
        frame = apply_transforms(frame, self.fset.spec.transforms)
        # online target: immediate per-event upsert
        if self.entities:
            self._kv.write_dataframe(frame, key_columns=self.entities)
        # offline parquet: buffered appends
        with self._lock:
            self._buffer.extend(frame.to_dict("records"))
            if len(self._buffer) >= self.flush_every:
                self._flush_locked()
        return {"ingested": len(rows), "feature_set": self.fset.name}

    def _flush_locked(self):
        if not self._buffer:
            return
        frame = pd.DataFrame(self._buffer)
        self._buffer = []
        path = self.fset._target_path()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        if os.path.isfile(path):
            frame = pd.concat([pd.read_parquet(path), frame],
                              ignore_index=True)
        if self.entities:
            frame = frame.drop_duplicates(subset=self.entities, keep="last")
        frame.to_parquet(path, index=False)

    def flush(self):
        with self._lock:
            self._flush_locked()

    def get(self, key_values: list) -> Optional[dict]:
        """Online lookup against the KV this service maintains."""
        return self._kv.get(key_values)


def ingestion_service_function(feature_set: FeatureSet | str,
                               name: str = "", project: str = ""):
    """Build a serving function whose graph ingests posted events into the
    feature set (deploy with fn.deploy() or serve via the asgi gateway)."""
    import mlrun_tpu

    if isinstance(feature_set, FeatureSet):
        feature_set.save()
        fset_name = feature_set.name
        project = project or getattr(feature_set.metadata, "project", "") \
            or ""
    else:
        fset_name = feature_set
    fn = mlrun_tpu.new_function(
        name or f"{fset_name}-ingest", kind="serving",
        project=project or mlconf.default_project)
    graph = fn.set_topology("flow")
    graph.to(class_name=FeatureSetIngestStep, name="ingest",
             feature_set=fset_name, project=project).respond()
    return fn
