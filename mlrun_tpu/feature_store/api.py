"""Feature-store operations (reference analog: mlrun/feature_store/api.py —
get_offline_features :99, get_online_feature_service :296, ingest :450;
merge engine analog: retrieval/local_merger.py BaseMerger/LocalFeatureMerger).

Round-1 engine: pandas (the reference's "local" engine). Storey/spark engines
are orchestration-level and out of the TPU hot path.
"""

from __future__ import annotations

import os
from typing import Optional, Union

import pandas as pd

from ..config import mlconf
from ..utils import logger, now_iso
from .feature_set import FeatureSet, FeatureVector


def _resolve_feature_set(ref: Union[str, FeatureSet],
                         project: str = "") -> FeatureSet:
    if isinstance(ref, FeatureSet):
        return ref
    from ..db import get_run_db

    name = ref
    if ref.startswith("store://feature-sets/"):
        body = ref[len("store://feature-sets/"):]
        project, _, name = body.partition("/")
    struct = get_run_db().get_feature_set(name, project=project)
    return FeatureSet.from_dict(struct)


def ingest(featureset: Union[FeatureSet, str], source,
           targets: list | None = None, namespace=None,
           return_df: bool = True, infer_options=None,
           overwrite: bool | None = None) -> pd.DataFrame:
    """Ingest a source into the feature set's targets and register
    stats/schema (reference api.py:450, pandas engine). ``source`` may be a
    DataFrame, url, or a datastore Source object; ``targets`` a list of
    target objects/kind-names (default: offline parquet)."""
    from ..datastore.sources import resolve_source

    fset = _resolve_feature_set(featureset)
    if fset.spec.engine == "dask":
        return _ingest_dask(fset, source, targets=targets,
                            return_df=return_df, overwrite=overwrite)
    source = resolve_source(source).to_dataframe()
    if not isinstance(source, pd.DataFrame):
        raise ValueError("pandas-engine ingest expects a DataFrame or url")

    entities = fset.entity_names
    for entity in entities:
        if entity not in source.columns and source.index.name != entity:
            raise ValueError(f"entity column '{entity}' missing from source")

    # transform graph + windowed aggregations (pandas engine).
    # copy + reset index: never mutate the caller's frame, and rolling
    # assignment needs unique row labels. An entity carried on the index
    # is promoted to a column (the validation above accepted it there).
    keep_index = source.index.name in entities
    source = source.copy().reset_index(drop=not keep_index)
    from .steps import apply_aggregations, apply_transforms

    source = apply_transforms(source, fset.spec.transforms)
    source = apply_aggregations(source, fset.spec.aggregations, entities,
                                fset.spec.timestamp_key)

    # schema inference
    if not fset.spec.features:
        fset.spec.features = [
            {"name": c, "value_type": str(source[c].dtype)}
            for c in source.columns if c not in entities
        ]
    # stats
    try:
        fset.status.stats = {
            c: {
                "count": int(source[c].count()),
                "mean": float(source[c].mean())
                if source[c].dtype.kind in "if" else None,
                "min": source[c].min() if source[c].dtype.kind in "if" else None,
                "max": source[c].max() if source[c].dtype.kind in "if" else None,
            }
            for c in source.columns
        }
    except Exception:  # noqa: BLE001 - stats are best-effort
        pass

    path = fset._target_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    if overwrite is False and os.path.isfile(path):
        existing = pd.read_parquet(path)
        source = pd.concat([existing, source], ignore_index=True)
        if entities:
            source = source.drop_duplicates(subset=entities, keep="last")
    source.to_parquet(path, index=False)
    target_records = [{"name": "parquet", "kind": "parquet",
                       "path": path, "updated": now_iso()}]

    # extra targets (nosql/stream/csv/sql/...) via the targets layer
    from ..datastore.targets import resolve_target

    project = getattr(fset.metadata, "project", "") or \
        mlconf.default_project
    for target in (targets if targets is not None
                   else fset.spec.targets or []):
        if isinstance(target, str) and target == "parquet":
            continue
        target_obj = resolve_target(target)
        namespacer = getattr(target_obj, "set_namespace", None)
        if namespacer:
            # always namespaced — a user-supplied shared url (e.g. one
            # redis for the whole cluster) must not collide row keys
            # across feature sets
            namespacer(project, fset.name)
        if not target_obj.path:
            target_obj.path = target_obj.default_path(project, fset.name)
        target_obj.write_dataframe(source, key_columns=entities,
                                   timestamp_key=fset.spec.timestamp_key)
        target_records.append(target_obj.status_record())

    fset.status.targets = target_records
    fset.status.state = "ready"
    fset.save()
    logger.info("ingested feature set", name=fset.name, rows=len(source),
                path=path)
    return source if return_df else None


def _ingest_dask(fset: FeatureSet, source, targets=None,
                 return_df: bool = True, overwrite: bool | None = None):
    """Dask-engine ingest (reference analog: storey/spark ingest engines;
    here dask.dataframe keeps large ParquetSource/CsvSource ingests
    out-of-core). Gated on the dask package; windowed aggregations need the
    pandas engine. Extra (non-parquet) targets materialize the frame."""
    import dask.dataframe as dd  # gated import

    if fset.spec.aggregations:
        raise ValueError(
            "windowed aggregations are not supported by the dask ingest "
            "engine — use engine='pandas' for this feature set")
    from ..datastore.sources import resolve_source

    src = resolve_source(source)
    path = getattr(src, "path", "") or ""
    if isinstance(source, pd.DataFrame):
        ddf = dd.from_pandas(source, npartitions=4)
    elif path.endswith(".parquet") or path.endswith(".pq"):
        ddf = dd.read_parquet(path)
    elif path.endswith(".csv"):
        ddf = dd.read_csv(path)
    else:
        ddf = dd.from_pandas(src.to_dataframe(), npartitions=4)

    from .steps import apply_transforms

    if fset.spec.transforms:
        meta = apply_transforms(ddf.head(10), fset.spec.transforms)
        ddf = ddf.map_partitions(
            lambda part: apply_transforms(part, fset.spec.transforms),
            meta=meta)

    entities = fset.entity_names
    for entity in entities:
        if entity not in ddf.columns:
            raise ValueError(f"entity column '{entity}' missing from source")
    if not fset.spec.features:
        fset.spec.features = [
            {"name": c, "value_type": str(dtype)}
            for c, dtype in ddf.dtypes.items() if c not in entities
        ]
    out_path = fset._target_path()
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    if overwrite is False and os.path.exists(out_path):
        # append + last-wins dedupe per entity, matching the pandas path
        existing = dd.read_parquet(out_path)
        ddf = dd.concat([existing, ddf])
        if entities:
            ddf = ddf.drop_duplicates(subset=entities, keep="last")
        # collect before rewriting the directory being read
        ddf = dd.from_pandas(ddf.compute(), npartitions=4)
    # a directory of part files (pd.read_parquet loads it transparently)
    ddf.to_parquet(out_path, write_index=False)
    target_records = [{"name": "parquet", "kind": "parquet",
                       "path": out_path, "updated": now_iso()}]

    extra_targets = targets if targets is not None else \
        (fset.spec.targets or [])
    if extra_targets:
        from ..datastore.targets import resolve_target

        project = getattr(fset.metadata, "project", "") or \
            mlconf.default_project
        materialized = ddf.compute()
        for target in extra_targets:
            if isinstance(target, str) and target == "parquet":
                continue
            target_obj = resolve_target(target)
            if not target_obj.path:
                target_obj.path = target_obj.default_path(project, fset.name)
            target_obj.write_dataframe(
                materialized, key_columns=entities,
                timestamp_key=fset.spec.timestamp_key)
            target_records.append(target_obj.status_record())

    fset.status.targets = target_records
    fset.status.state = "ready"
    fset.save()
    logger.info("ingested feature set (dask)", name=fset.name,
                path=out_path)
    return ddf.compute() if return_df else None


def preview(featureset: Union[FeatureSet, str], source, limit: int = 20):
    fset = _resolve_feature_set(featureset)
    if isinstance(source, str):
        from ..datastore import store_manager

        source = store_manager.object(url=source).as_df()
    return source.head(limit)


class OfflineVectorResponse:
    """Result of get_offline_features (reference api.py OfflineVectorResponse)."""

    def __init__(self, df: pd.DataFrame, vector: FeatureVector):
        self._df = df
        self.vector = vector
        self.status = "completed"

    def to_dataframe(self) -> pd.DataFrame:
        return self._df

    def to_parquet(self, target_path: str, **kw):
        os.makedirs(os.path.dirname(target_path) or ".", exist_ok=True)
        self._df.to_parquet(target_path, **kw)
        return target_path

    def to_csv(self, target_path: str, **kw):
        os.makedirs(os.path.dirname(target_path) or ".", exist_ok=True)
        self._df.to_csv(target_path, index=False, **kw)
        return target_path


def _resolve_vector(vector: Union[str, FeatureVector],
                    project: str = "") -> FeatureVector:
    if isinstance(vector, FeatureVector):
        return vector
    from ..db import get_run_db

    name = vector
    if vector.startswith("store://feature-vectors/"):
        body = vector[len("store://feature-vectors/"):]
        project, _, name = body.partition("/")
    struct = get_run_db().get_feature_vector(name, project=project)
    return FeatureVector.from_dict(struct)


def get_offline_features(feature_vector: Union[str, FeatureVector],
                         entity_rows: pd.DataFrame | None = None,
                         target=None, drop_columns: list | None = None,
                         with_indexes: bool = False,
                         engine: str = "local",
                         engine_args: dict | None = None
                         ) -> OfflineVectorResponse:
    """Join the vector's feature sets into one offline dataframe
    (reference api.py:99). ``engine`` selects the merger: local (pandas),
    partitioned (out-of-core single host), dask, spark — see
    retrieval.py (reference analog retrieval/base.py:30)."""
    from .retrieval import get_merger

    vector = _resolve_vector(feature_vector)
    project = getattr(vector.metadata, "project", "") or ""
    merger = get_merger(engine, vector, project=project,
                        **(engine_args or {}))
    merged = merger.merge(entity_rows=entity_rows, drop_columns=drop_columns,
                          with_indexes=with_indexes)
    response = OfflineVectorResponse(merged, vector)
    if target:
        path = target if isinstance(target, str) else getattr(
            target, "path", "")
        if path:
            response.to_parquet(path)
    return response


class OnlineVectorService:
    """Key→features lookup service (reference feature_vector.py:910)."""

    def __init__(self, vector: FeatureVector, impute_policy: dict | None = None):
        self.vector = vector
        self.impute_policy = impute_policy or {}
        self._tables: list[tuple[list[str], pd.DataFrame]] = []
        self._targets: list[tuple] = []  # (entities, wanted, columns, target)
        self._initialize()

    def _initialize(self):
        project = getattr(self.vector.metadata, "project", "") or ""
        by_set: dict[str, list[str]] = {}
        for set_name, feature in self.vector.parse_features():
            by_set.setdefault(set_name, []).append(feature)
        for set_name, wanted in by_set.items():
            fset = _resolve_feature_set(set_name, project=project)
            entities = fset.entity_names
            features = ["*"] if "*" in wanted else wanted
            target = self._online_target(fset)
            if target is not None:
                # key-value lookups ride the ingested ONLINE target
                # (sqlite kv single-host; redis for a shared serving
                # fleet) instead of loading the offline frame in memory.
                # ONE target per feature set: multi-feature vectors do a
                # single row fetch, not one per feature. Known columns
                # seed NaN placeholders when a row is missing so the
                # impute policy fires like the in-memory path.
                columns = (features if "*" not in features
                           else [f["name"] if isinstance(f, dict)
                                 else f.name
                                 for f in fset.spec.features or []])
                self._targets.append((entities, features, columns, target))
                continue
            df = fset.to_dataframe()
            if "*" not in features:
                df = df[entities + features]
            self._tables.append((entities, df.set_index(entities)))

    @staticmethod
    def _online_target(fset):
        from ..datastore.targets import resolve_target

        for record in (getattr(fset.status, "targets", None) or []):
            if record.get("kind") in ("nosql", "redisnosql"):
                target = resolve_target(
                    {"kind": record["kind"],
                     "path": record.get("path", "")})
                if record.get("prefix"):
                    target._prefix = record["prefix"]
                return target
        return None

    @property
    def status(self):
        return "ready"

    def get(self, entity_rows: list[dict], as_list: bool = False):
        """entity_rows: [{entity: value, ...}] → feature dicts (or lists)."""
        out = []
        for row in entity_rows:
            features: dict = {}
            for entities, wanted, columns, target in self._targets:
                try:
                    record = target.get([row[e] for e in entities])
                except KeyError:
                    record = None
                if record:
                    if "*" not in wanted:
                        record = {k: v for k, v in record.items()
                                  if k in wanted}
                    features.update({k: v for k, v in record.items()
                                     if k not in entities})
                else:
                    # missing row: NaN placeholders (like the in-memory
                    # path) so the impute policy below can fill them
                    for col in columns:
                        features.setdefault(col, float("nan"))
            for entities, table in self._tables:
                try:
                    key = tuple(row[e] for e in entities)
                    if len(key) == 1:
                        key = key[0]
                    record = table.loc[key]
                    if isinstance(record, pd.DataFrame):
                        record = record.iloc[-1]
                    features.update(record.to_dict())
                except (KeyError, TypeError):
                    # entity missing from this table → NaN placeholders so
                    # the impute policy can fill them
                    for col in table.columns:
                        features.setdefault(col, float("nan"))
            # imputation
            for key, value in list(features.items()):
                if pd.isna(value):
                    policy = self.impute_policy.get(
                        key, self.impute_policy.get("*"))
                    if policy is not None:
                        features[key] = policy
            out.append(list(features.values()) if as_list else features)
        return out

    def close(self):
        self._tables = []
        for _, _, _, target in self._targets:
            closer = getattr(target, "close", None)
            if closer:
                closer()
        self._targets = []


def get_online_feature_service(feature_vector: Union[str, FeatureVector],
                               impute_policy: dict | None = None,
                               **kwargs) -> OnlineVectorService:
    """Create an online lookup service (reference api.py:296)."""
    vector = _resolve_vector(feature_vector)
    return OnlineVectorService(vector, impute_policy=impute_policy)
