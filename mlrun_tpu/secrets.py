"""Secrets store (reference analog: mlrun/secrets.py SecretsStore).

Sources: inline kv dicts, env vars (optionally prefixed), env files.
"""

from __future__ import annotations

import os


class SecretsStore:
    def __init__(self):
        self._secrets: dict[str, str] = {}
        self._hidden_sources: list[dict] = []

    @classmethod
    def from_list(cls, src_list: list | None) -> "SecretsStore":
        store = cls()
        for source in src_list or []:
            store.add_source(source.get("kind"), source.get("source"))
        return store

    def add_source(self, kind: str, source=None, prefix: str = ""):
        if kind == "inline":
            if not isinstance(source, dict):
                raise ValueError("inline secrets source must be a dict")
            for key, value in source.items():
                self._secrets[prefix + key] = str(value)
        elif kind == "env":
            # source = "KEY1,KEY2" or None for all MLT_SECRET_* vars
            keys = (source or "").split(",") if source else [
                k for k in os.environ if k.startswith("MLT_SECRET_")
            ]
            for key in keys:
                key = key.strip()
                if key and key in os.environ:
                    name = key[len("MLT_SECRET_"):] if key.startswith(
                        "MLT_SECRET_") else key
                    self._secrets[prefix + name] = os.environ[key]
        elif kind == "file":
            with open(source) as fp:
                for line in fp:
                    line = line.strip()
                    if line and not line.startswith("#") and "=" in line:
                        key, value = line.split("=", 1)
                        self._secrets[prefix + key.strip()] = value.strip()
        elif kind == "vault" or kind == "kubernetes":
            # cluster secret stores are resolved server-side; record only
            self._hidden_sources.append({"kind": kind, "source": source})
        else:
            raise ValueError(f"unsupported secrets source kind '{kind}'")

    def get(self, key: str, default: str | None = None):
        if key in self._secrets:
            return self._secrets[key]
        if key in os.environ:
            return os.environ[key]
        # project secrets injected into resources arrive as MLT_SECRET_*
        # env (service runtime_handlers._secret_env)
        return os.environ.get("MLT_SECRET_" + key, default)

    def items(self):
        return self._secrets.items()

    def has(self, key: str) -> bool:
        return key in self._secrets or key in os.environ

    def to_serial(self) -> list[dict]:
        # inline secrets are redacted when serialized back (like the reference's
        # masking in server/api/api/utils.py:221-300)
        return list(self._hidden_sources)


def get_secret_or_env(key: str, secret_provider=None, default: str = "",
                      prefix: str = "") -> str:
    """Resolve a secret by key (reference mlrun/secrets.py:188
    get_secret_or_env — same module path, precedence, and prefix
    separator): explicit provider first, then the PLAIN env var, then
    the injected project-secret env (MLT_SECRET_<key>, key verbatim —
    the exact name service runtime_handlers._secret_env injects)."""
    if prefix:
        key = f"{prefix}_{key}"
    if secret_provider is not None:
        value = secret_provider(key) if callable(secret_provider) \
            else secret_provider.get(key)
        if value:
            return value
    return (os.environ.get(key)
            or os.environ.get("MLT_SECRET_" + key)
            or default)
