"""Notebook/HTML rendering of runs & artifacts (reference analog:
mlrun/render.py — run table HTML, artifact links)."""

from __future__ import annotations

import html
from typing import Optional

_style = """
<style>
.mlt-table { border-collapse: collapse; font-family: monospace; }
.mlt-table th, .mlt-table td {
  border: 1px solid #ccc; padding: 4px 8px; text-align: left; }
.mlt-table th { background: #f0f0f0; }
.mlt-state-completed { color: #0a7d00; }
.mlt-state-error { color: #c00000; }
.mlt-state-running { color: #0050c0; }
</style>
"""


def _cell(value) -> str:
    if value is None:
        return ""
    if isinstance(value, dict):
        return html.escape(", ".join(
            f"{k}={_round(v)}" for k, v in value.items()))
    return html.escape(str(value))


def _round(value):
    if isinstance(value, float):
        return round(value, 4)
    return value


def runs_to_html(runs: list[dict], display: bool = True) -> str:
    """Render a run list to an HTML table."""
    headers = ["uid", "name", "state", "start", "results", "artifacts"]
    rows = []
    for run in runs:
        meta = run.get("metadata", {})
        status = run.get("status", {})
        state = status.get("state", "")
        # states are free-form strings from the DB — never interpolate raw
        state_class = _state_class(state)
        rows.append(
            "<tr>"
            f"<td>{_cell((meta.get('uid') or '')[:12])}</td>"
            f"<td>{_cell(meta.get('name'))}</td>"
            f"<td class='mlt-state-{state_class}'>{_cell(state)}</td>"
            f"<td>{_cell(str(status.get('start_time', ''))[:19])}</td>"
            f"<td>{_cell(status.get('results'))}</td>"
            f"<td>{_cell(list((status.get('artifact_uris') or {})))}</td>"
            "</tr>")
    table = (
        _style + "<table class='mlt-table'><tr>"
        + "".join(f"<th>{h}</th>" for h in headers) + "</tr>"
        + "".join(rows) + "</table>")
    if display:
        _display_html(table)
    return table


def artifacts_to_html(artifacts: list[dict], display: bool = True) -> str:
    headers = ["key", "kind", "tag", "size", "target"]
    rows = []
    for artifact in artifacts:
        meta = artifact.get("metadata", {})
        spec = artifact.get("spec", {})
        rows.append(
            "<tr>"
            f"<td>{_cell(meta.get('key'))}</td>"
            f"<td>{_cell(artifact.get('kind'))}</td>"
            f"<td>{_cell(meta.get('tag'))}</td>"
            f"<td>{_cell(spec.get('size'))}</td>"
            f"<td>{_cell(spec.get('target_path'))}</td>"
            "</tr>")
    table = (
        _style + "<table class='mlt-table'><tr>"
        + "".join(f"<th>{h}</th>" for h in headers) + "</tr>"
        + "".join(rows) + "</table>")
    if display:
        _display_html(table)
    return table


def run_to_html(run: dict, display: bool = True) -> str:
    """Run DETAIL card (reference render.py run_to_html): identity +
    labels/parameters/results tables, artifact links, inline iframes for
    html plot artifacts."""
    meta = run.get("metadata", {})
    spec = run.get("spec", {})
    status = run.get("status", {})
    sections = [_style, "<div class='mlt-run'>"]
    state = status.get("state", "")
    sections.append(
        f"<h3 class='mlt-run-title'>{_cell(meta.get('name'))} "
        f"<span class='mlt-state-{_state_class(state)}'>"
        f"[{_cell(state)}]</span></h3>")
    identity = {
        "uid": meta.get("uid", ""),
        "project": meta.get("project", ""),
        "iteration": meta.get("iteration", 0),
        "start": str(status.get("start_time", ""))[:19],
        "last update": str(status.get("last_update", ""))[:19],
    }
    sections.append(_kv_table(identity))
    for title, mapping in (("labels", meta.get("labels")),
                           ("parameters", spec.get("parameters")),
                           ("results", status.get("results"))):
        if mapping:
            sections.append(f"<h4>{title}</h4>")
            sections.append(_kv_table(mapping))
    error = status.get("error")
    if error:
        sections.append(
            f"<p class='mlt-state-error'>error: {_cell(error)}</p>")
    uris = status.get("artifact_uris") or {}
    if uris:
        sections.append("<h4>artifacts</h4><ul>")
        for key, uri in uris.items():
            sections.append(
                f"<li><a href='{html.escape(str(uri), quote=True)}'>"
                f"{_cell(key)}</a></li>")
        sections.append("</ul>")
    for artifact in status.get("artifacts") or []:
        frame = artifact_to_iframe(artifact)
        if frame:
            sections.append(frame)
    sections.append("</div>")
    content = "".join(sections)
    if display:
        if not _display_html(content):
            return ""
    return content


def artifact_to_iframe(artifact: dict, height: int = 500) -> str:
    """Inline iframe for plot/html artifacts (reference render.py's
    iframe plot embedding); empty string for non-visual kinds."""
    spec = artifact.get("spec", {})
    meta = artifact.get("metadata", {})
    viewer = spec.get("viewer", "")
    fmt = (spec.get("format") or "").lower()
    target = spec.get("target_path", "") or ""
    is_html = viewer == "web-app" or fmt == "html" \
        or target.endswith(".html")
    if not is_html:
        return ""
    body = None
    if target:
        try:
            from .datastore import store_manager

            body = store_manager.object(url=target).get()
        except Exception:  # noqa: BLE001 - unreadable target: no preview
            return ""
    if body is None:
        return ""
    if isinstance(body, bytes):
        body = body.decode(errors="replace")
    return (f"<h4>{_cell(meta.get('key'))}</h4>"
            f"<iframe srcdoc=\"{html.escape(body, quote=True)}\" "
            f"width='100%' height='{int(height)}' frameborder='0'>"
            "</iframe>")


def _kv_table(mapping: dict) -> str:
    rows = "".join(
        f"<tr><th>{_cell(k)}</th><td>{_cell(_round(v))}</td></tr>"
        for k, v in mapping.items())
    return f"<table class='mlt-table'>{rows}</table>"


def _state_class(state) -> str:
    import re

    return re.sub(r"[^a-z0-9-]", "", str(state).lower())[:32]


def _display_html(content: str) -> bool:
    try:
        from IPython.display import HTML, display as ipy_display

        ipy_display(HTML(content))
        return True
    except ImportError:
        return False
