"""Notebook/HTML rendering of runs & artifacts (reference analog:
mlrun/render.py — run table HTML, artifact links)."""

from __future__ import annotations

import html
from typing import Optional

_style = """
<style>
.mlt-table { border-collapse: collapse; font-family: monospace; }
.mlt-table th, .mlt-table td {
  border: 1px solid #ccc; padding: 4px 8px; text-align: left; }
.mlt-table th { background: #f0f0f0; }
.mlt-state-completed { color: #0a7d00; }
.mlt-state-error { color: #c00000; }
.mlt-state-running { color: #0050c0; }
</style>
"""


def _cell(value) -> str:
    if value is None:
        return ""
    if isinstance(value, dict):
        return html.escape(", ".join(
            f"{k}={_round(v)}" for k, v in value.items()))
    return html.escape(str(value))


def _round(value):
    if isinstance(value, float):
        return round(value, 4)
    return value


def runs_to_html(runs: list[dict], display: bool = True) -> str:
    """Render a run list to an HTML table."""
    headers = ["uid", "name", "state", "start", "results", "artifacts"]
    rows = []
    import re

    for run in runs:
        meta = run.get("metadata", {})
        status = run.get("status", {})
        state = status.get("state", "")
        # states are free-form strings from the DB — never interpolate raw
        state_class = re.sub(r"[^a-z0-9-]", "", str(state).lower())[:32]
        rows.append(
            "<tr>"
            f"<td>{_cell((meta.get('uid') or '')[:12])}</td>"
            f"<td>{_cell(meta.get('name'))}</td>"
            f"<td class='mlt-state-{state_class}'>{_cell(state)}</td>"
            f"<td>{_cell(str(status.get('start_time', ''))[:19])}</td>"
            f"<td>{_cell(status.get('results'))}</td>"
            f"<td>{_cell(list((status.get('artifact_uris') or {})))}</td>"
            "</tr>")
    table = (
        _style + "<table class='mlt-table'><tr>"
        + "".join(f"<th>{h}</th>" for h in headers) + "</tr>"
        + "".join(rows) + "</table>")
    if display:
        _display_html(table)
    return table


def artifacts_to_html(artifacts: list[dict], display: bool = True) -> str:
    headers = ["key", "kind", "tag", "size", "target"]
    rows = []
    for artifact in artifacts:
        meta = artifact.get("metadata", {})
        spec = artifact.get("spec", {})
        rows.append(
            "<tr>"
            f"<td>{_cell(meta.get('key'))}</td>"
            f"<td>{_cell(artifact.get('kind'))}</td>"
            f"<td>{_cell(meta.get('tag'))}</td>"
            f"<td>{_cell(spec.get('size'))}</td>"
            f"<td>{_cell(spec.get('target_path'))}</td>"
            "</tr>")
    table = (
        _style + "<table class='mlt-table'><tr>"
        + "".join(f"<th>{h}</th>" for h in headers) + "</tr>"
        + "".join(rows) + "</table>")
    if display:
        _display_html(table)
    return table


def run_to_html(run: dict, display: bool = True) -> str:
    return runs_to_html([run], display=display)


def _display_html(content: str):
    try:
        from IPython.display import HTML, display as ipy_display

        ipy_display(HTML(content))
    except ImportError:
        pass
