"""Pod mount/platform modifiers (reference analog: mlrun/platforms/__init__.py
:20-33 re-exporting mount decorators; impl in
pipeline-adapters/.../mounts.py:67 mount_v3io, :298 mount_pvc, :339
auto_mount — V3IO is replaced by GCS-keyed mounts on TPU deployments)."""

from __future__ import annotations

import os

from ..utils import logger


def mount_pvc(pvc_name: str = "", volume_name: str = "pvc",
              volume_mount_path: str = "/mnt/data"):
    """Mount a persistent volume claim on the runtime's pods."""
    pvc_name = pvc_name or os.environ.get("MLT_PVC_NAME", "")

    def modifier(runtime):
        if not pvc_name:
            raise ValueError("no pvc_name given (or MLT_PVC_NAME set)")
        runtime.spec.volumes.append({
            "name": volume_name,
            "persistentVolumeClaim": {"claimName": pvc_name},
        })
        runtime.spec.volume_mounts.append({
            "name": volume_name, "mountPath": volume_mount_path})
        return runtime

    return modifier


def mount_secret(secret_name: str, mount_path: str = "/secrets",
                 volume_name: str = "secret", items: list | None = None):
    def modifier(runtime):
        volume = {"name": volume_name, "secret": {"secretName": secret_name}}
        if items:
            volume["secret"]["items"] = items
        runtime.spec.volumes.append(volume)
        runtime.spec.volume_mounts.append({
            "name": volume_name, "mountPath": mount_path})
        return runtime

    return modifier


def mount_configmap(configmap_name: str, mount_path: str = "/config",
                    volume_name: str = "configmap"):
    def modifier(runtime):
        runtime.spec.volumes.append({
            "name": volume_name,
            "configMap": {"name": configmap_name},
        })
        runtime.spec.volume_mounts.append({
            "name": volume_name, "mountPath": mount_path})
        return runtime

    return modifier


def mount_gcs_key(secret_name: str = "gcs-credentials",
                  key_file: str = "key.json",
                  env_var: str = "GOOGLE_APPLICATION_CREDENTIALS"):
    """Mount a GCS service-account key + point the standard env at it —
    the TPU-native object-store credential (V3IO access-key analog)."""

    def modifier(runtime):
        mount_path = "/var/secrets/gcs"
        runtime.spec.volumes.append({
            "name": "gcs-key", "secret": {"secretName": secret_name}})
        runtime.spec.volume_mounts.append({
            "name": "gcs-key", "mountPath": mount_path, "readOnly": True})
        runtime.set_env(env_var, f"{mount_path}/{key_file}")
        return runtime

    return modifier


def mount_tmpfs(size: str = "1Gi", mount_path: str = "/dev/shm",
                volume_name: str = "shm"):
    """RAM-backed scratch for host-side data loading."""

    def modifier(runtime):
        runtime.spec.volumes.append({
            "name": volume_name,
            "emptyDir": {"medium": "Memory", "sizeLimit": size},
        })
        runtime.spec.volume_mounts.append({
            "name": volume_name, "mountPath": mount_path})
        return runtime

    return modifier


def auto_mount(pvc_name: str = "", volume_mount_path: str = "/mnt/data"):
    """Pick a mount from the environment (reference mounts.py:339)."""
    if pvc_name or os.environ.get("MLT_PVC_NAME"):
        return mount_pvc(pvc_name, volume_mount_path=volume_mount_path)
    if os.environ.get("GOOGLE_APPLICATION_CREDENTIALS"):
        return mount_gcs_key()

    def noop(runtime):
        logger.warning("auto_mount found nothing to mount")
        return runtime

    return noop
