"""Error hierarchy (reference analog: mlrun/errors.py — the subset the
SDK surface raises/catches; HTTP mapping mirrors the reference's
err_to_status_code convention)."""

from __future__ import annotations


class MLRunBaseError(Exception):
    """Root of the framework's error hierarchy."""


class MLRunInvalidArgumentError(MLRunBaseError, ValueError):
    """Bad user input (maps to HTTP 400)."""


class MLRunNotFoundError(MLRunBaseError, KeyError):
    """Requested object does not exist (maps to HTTP 404)."""


class MLRunConflictError(MLRunBaseError):
    """State conflict, e.g. resource already exists (HTTP 409)."""


class MLRunTimeoutError(MLRunBaseError, TimeoutError):
    """Deadline exceeded waiting on a run/deploy/build."""


class MLRunRuntimeError(MLRunBaseError, RuntimeError):
    """Execution-side failure."""
