"""Pipeline parallelism — GPipe-style stages over a ``pipe`` mesh axis.

SURVEY.md §2.4: the reference has NO pipeline parallelism; this adds it
TPU-natively (cf. PAPERS.md MPMD pipeline-parallel reference, implemented
here as SPMD collective pipelining): the stacked llama layer tree
``[L, ...]`` is split into P stages sharded over the ``pipe`` axis via
``shard_map``; microbatch activations rotate stage→stage with
``jax.lax.ppermute`` (ICI/DCN neighbor transfers) while every stage computes
its slice — the classic fill/drain schedule with M microbatches and P-1
bubble steps. Differentiable end-to-end (ppermute has a transpose rule), so
``jax.grad`` of the pipelined loss just works.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..models.llama import LlamaConfig, _layer_body
from ..ops.norms import rms_norm
from ..ops.rotary import rope_table
from .compat import shard_map


def split_layers_for_stages(layers: dict, n_stages: int) -> dict:
    """[L, ...] stacked layer tree -> [P, L/P, ...]."""

    def reshape(leaf):
        if leaf.shape[0] % n_stages:
            raise ValueError(
                f"n_layers {leaf.shape[0]} not divisible by "
                f"{n_stages} stages")
        return leaf.reshape(n_stages, leaf.shape[0] // n_stages,
                            *leaf.shape[1:])

    return jax.tree_util.tree_map(reshape, layers)


def make_pipeline_forward(config: LlamaConfig, mesh: Mesh,
                          num_microbatches: int,
                          pipe_axis: str = "pipe",
                          batch_axis: str | None = None):
    """Build fn(params, tokens) -> logits with layers pipelined over
    ``pipe_axis``. ``params["layers"]`` must be pre-split via
    split_layers_for_stages(mesh.shape[pipe_axis]).

    Batch must divide into ``num_microbatches``. Embedding/unembedding run
    replicated outside the pipelined region (they are cheap relative to the
    decoder at scale; sharding them rides the other mesh axes).

    ``batch_axis`` composes data parallelism with the pipeline: each
    microbatch's batch dim is sharded over that mesh axis inside the
    pipelined region (stage weights stay replicated across it), so a
    ``data x pipe`` mesh runs D independent pipelines in lockstep.
    """
    n_stages = mesh.shape[pipe_axis]

    def stage_fn(stage_layers, x, cos, sin):
        """Run this stage's L/P layers (scan over the local stack)."""

        def body(carry, lp):
            return _layer_body(config, carry, lp, cos, sin, None), None

        out, _ = jax.lax.scan(body, x, stage_layers)
        return out

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(pipe_axis), P(None, batch_axis), P(), P()),
        out_specs=P(None, batch_axis), check_vma=False)
    def pipelined_decoder(stage_layers, x_micro, cos, sin):
        """x_micro: [M, mb, S, E] (replicated); stage_layers carries the
        leading [1, L/P, ...] shard of this device's stage."""
        stage_layers = jax.tree_util.tree_map(lambda a: a[0], stage_layers)
        idx = jax.lax.axis_index(pipe_axis)
        m_total = x_micro.shape[0]
        mb_shape = x_micro.shape[1:]
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        state = jnp.zeros(mb_shape, x_micro.dtype)
        outputs = jnp.zeros_like(x_micro)

        for t in range(m_total + n_stages - 1):
            # stage 0 injects microbatch t during the fill phase
            if t < m_total:
                state = jnp.where(idx == 0, x_micro[t], state)
            state = stage_fn(stage_layers, state, cos, sin)
            out_t = t - (n_stages - 1)
            if out_t >= 0:
                # the last stage just finished microbatch out_t
                outputs = outputs.at[out_t].set(
                    jnp.where(idx == n_stages - 1, state, outputs[out_t]))
            if t < m_total + n_stages - 2:
                state = jax.lax.ppermute(state, pipe_axis, perm)

        # replicate results: only the last stage holds real outputs
        outputs = jnp.where(idx == n_stages - 1, outputs, 0.0)
        return jax.lax.psum(outputs, pipe_axis)

    def forward(params, tokens):
        b, s = tokens.shape
        if b % num_microbatches:
            raise ValueError(
                f"batch {b} not divisible by {num_microbatches} microbatches")
        mb = b // num_microbatches
        if batch_axis and mb % mesh.shape[batch_axis]:
            raise ValueError(
                f"microbatch size {mb} (batch {b} / {num_microbatches} "
                f"microbatches) must divide over the '{batch_axis}' mesh "
                f"axis ({mesh.shape[batch_axis]}); grow the batch or "
                "shrink the data axis")
        x = params["embedding"][tokens].astype(config.dtype)
        cos, sin = rope_table(jnp.arange(s), config.head_dim,
                              config.rope_theta)
        x_micro = x.reshape(num_microbatches, mb, s, -1)
        hidden = pipelined_decoder(params["layers"], x_micro, cos, sin)
        hidden = hidden.reshape(b, s, -1)
        hidden = rms_norm(hidden, params["final_norm_scale"],
                          config.norm_eps)
        head = params.get("lm_head")
        if head is None:
            head = params["embedding"].T
        return jnp.einsum("bse,ev->bsv", hidden, head,
                          preferred_element_type=jnp.float32)

    return forward


def pipeline_loss_fn(config: LlamaConfig, mesh: Mesh,
                     num_microbatches: int, pipe_axis: str = "pipe",
                     batch_axis: str | None = None):
    """Cross-entropy over the pipelined forward (for train steps)."""
    forward = make_pipeline_forward(config, mesh, num_microbatches,
                                    pipe_axis, batch_axis=batch_axis)

    def loss(params, tokens, targets):
        logits = forward(params, tokens)
        log_probs = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(
            log_probs, targets[..., None], axis=-1)[..., 0]
        loss_value = jnp.mean(nll)
        accuracy = jnp.mean(jnp.argmax(logits, -1) == targets)
        return loss_value, {"loss": loss_value, "accuracy": accuracy}

    return loss
