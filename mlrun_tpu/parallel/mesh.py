"""Device mesh construction over ICI/DCN.

This is the substrate that replaces the reference's MPI/NCCL world
(mlrun/runtimes/mpijob/abstract.py:89-96 NCCL env defaults; Horovod init in
frameworks/pytorch/mlrun_interface.py:561-566): instead of ranks + explicit
allreduce, we build a ``jax.sharding.Mesh`` whose axes map onto the TPU
interconnect — ICI within a pod-slice, DCN across slices — and let XLA emit
the collectives from sharding annotations.

Mesh axis convention (configurable, cf. config.tpu.mesh):
  data   — pure data parallelism (usually across slices / DCN)
  fsdp   — fully-sharded data parallel (params sharded, ICI)
  tensor — tensor/model parallelism (ICI, innermost = fastest axis)
  seq    — optional sequence/context parallelism axis for ring attention
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DEFAULT_AXES = ("data", "fsdp", "tensor")


@dataclass(frozen=True)
class MeshConfig:
    """Declarative logical mesh description."""

    shape: dict  # axis name -> size; -1 for "fill with remaining devices"
    num_slices: int = 1

    @property
    def axis_names(self) -> tuple[str, ...]:
        return tuple(self.shape.keys())

    def resolve(self, n_devices: int) -> dict:
        """Resolve -1 axes against the available device count."""
        shape = dict(self.shape)
        known = 1
        fill_axis = None
        for axis, size in shape.items():
            if size == -1:
                if fill_axis is not None:
                    raise ValueError("only one mesh axis may be -1")
                fill_axis = axis
            else:
                known *= size
        if fill_axis is not None:
            if n_devices % known:
                raise ValueError(
                    f"cannot fill axis '{fill_axis}': {n_devices} devices "
                    f"not divisible by {known}")
            shape[fill_axis] = n_devices // known
            known *= shape[fill_axis]
        if known != n_devices:
            raise ValueError(
                f"mesh shape {shape} needs {known} devices, have {n_devices}")
        return shape


def make_mesh(shape: dict | None = None, devices=None,
              num_slices: int | None = None,
              axis_names: Sequence[str] | None = None) -> Mesh:
    """Build a Mesh.

    - single slice: ``jax.make_mesh`` (toroidal-aware device order)
    - multi slice: hybrid ICI×DCN mesh via
      ``jax.experimental.mesh_utils.create_hybrid_device_mesh`` — the FIRST
      axis (conventionally ``data``) spans slices over DCN, the rest ride ICI.
    """
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if shape is None:
        axis_names = tuple(axis_names or DEFAULT_AXES)
        # default: everything on fsdp
        shape = {name: 1 for name in axis_names}
        shape[axis_names[1] if len(axis_names) > 1 else axis_names[0]] = n
    config = MeshConfig(shape)
    explicit = [s for s in shape.values() if s != -1]
    product = int(np.prod(explicit)) if explicit else 0
    if -1 not in shape.values() and 0 < product < n:
        # smaller explicit mesh than available devices → use a prefix
        devices = list(devices)[:product]
        n = product
    resolved = config.resolve(n)
    names = tuple(resolved.keys())
    sizes = tuple(resolved.values())

    num_slices = num_slices or _detect_num_slices(devices)
    # Auto axis types: we annotate params/data in/out shardings and let
    # GSPMD propagate + insert collectives (jax 0.9 defaults to Explicit,
    # which demands per-op sharding types instead). jax builds that
    # predate AxisType are Auto-only — the kwarg is simply omitted.
    try:
        from jax.sharding import AxisType

        mesh_kwargs = {"axis_types": (AxisType.Auto,) * len(names)}
    except ImportError:
        mesh_kwargs = {}
    if num_slices > 1:
        from jax.experimental.mesh_utils import create_hybrid_device_mesh

        if sizes[0] % num_slices:
            raise ValueError(
                f"first (DCN) axis size {sizes[0]} must be divisible by "
                f"num_slices {num_slices}")
        dcn = (num_slices,) + (1,) * (len(sizes) - 1)
        ici = (sizes[0] // num_slices,) + sizes[1:]
        try:
            device_array = create_hybrid_device_mesh(
                ici, dcn, devices=devices, allow_split_physical_axes=True)
        except (ValueError, AttributeError, KeyError):
            if any(getattr(d, "slice_index", None) is not None
                   for d in devices):
                # real multi-slice hardware: this is a genuine topology/
                # declaration error — degrading to an arbitrary device
                # order would silently misalign the DCN axis
                raise
            # CPU/virtual devices carry no slice_index/DCN topology
            # (the MLT_NUM_SLICES override and elastic tests run here):
            # contiguous device blocks stand in for slices — correct
            # semantics, just without the DCN-aware device ordering
            device_array = np.asarray(devices).reshape(sizes)
        return Mesh(device_array, names, **mesh_kwargs)
    try:
        return jax.make_mesh(sizes, names, devices=devices, **mesh_kwargs)
    except TypeError:
        # older signature without devices/axis_types kwargs
        device_array = np.asarray(devices).reshape(sizes)
        return Mesh(device_array, names, **mesh_kwargs)


def _detect_num_slices(devices) -> int:
    """Slice count of a device set. ``MLT_NUM_SLICES`` overrides (virtual
    multi-slice on CPU — the elastic tests' backbone); otherwise the
    devices' ``slice_index`` attribute, with an explicit CPU/virtual
    fallback: a backend without slice topology reports 1 slice, never
    raises."""
    env = os.environ.get("MLT_NUM_SLICES", "")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass  # a malformed override degrades to detection
    try:
        slice_ids = {getattr(d, "slice_index", 0) or 0 for d in devices}
    except Exception:  # noqa: BLE001 - attribute probing on exotic
        return 1       # backends (virtual/plugin devices) must not raise
    return max(1, len(slice_ids))


def refit_shape(shape: dict, n_devices: int,
                prefer_axis: str | None = None) -> dict:
    """Refit a resolved mesh shape onto a new device count by rescaling
    ONE axis — ``prefer_axis`` first, then declaration order (the first
    axis is conventionally the DCN/data axis that spans slices, so a
    slice loss shrinks it). This is the elastic trainer's mesh-shrink/
    grow rule: survivors of a slice preemption rebuild their mesh with
    ``make_mesh(refit_shape(old_shape, len(survivors)), survivors)``.
    Raises ValueError when no single axis rescales evenly."""
    order = ([prefer_axis] if prefer_axis in shape else []) + list(shape)
    for axis in order:
        trial = dict(shape)
        trial[axis] = -1
        try:
            return MeshConfig(trial).resolve(n_devices)
        except ValueError:
            continue
    raise ValueError(
        f"cannot refit mesh shape {shape} onto {n_devices} devices: no "
        "single axis rescales evenly")


def local_mesh(n: int | None = None, axis_names: Sequence[str] = ("data",)
               ) -> Mesh:
    """A 1-axis mesh over local devices (tests / single host)."""
    devices = jax.devices()
    n = n or len(devices)
    return make_mesh({axis_names[0]: n}, devices=devices[:n])


def mesh_shape_for_topology(topology: str, chips_per_host: int = 4,
                            num_slices: int = 1,
                            model_parallel: int = 1) -> dict:
    """Suggest a (data, fsdp, tensor) shape for a TPU topology string."""
    dims = [int(d) for d in topology.lower().split("x")]
    chips = int(np.prod(dims))
    total = chips * num_slices
    if total % model_parallel:
        raise ValueError(
            f"{total} chips not divisible by tensor={model_parallel}")
    return {"data": num_slices, "fsdp": total // num_slices // model_parallel,
            "tensor": model_parallel}


def initialize_distributed(coordinator_address: str | None = None,
                           num_processes: int | None = None,
                           process_id: int | None = None):
    """Multi-host init (replaces hvd.init/mpirun; on GKE JobSet the TPU env
    supplies everything and bare ``jax.distributed.initialize()`` works)."""
    import os

    # NOTE: decide from env only — jax.process_count() would initialize the
    # XLA backend and make jax.distributed.initialize() fail afterwards
    multi_host = bool(
        coordinator_address
        or "MEGASCALE_COORDINATOR_ADDRESS" in os.environ
        or (os.environ.get("TPU_WORKER_HOSTNAMES", "").count(",") >= 1))
    if not multi_host:
        return
    from jax._src import distributed as _dist

    if getattr(_dist.global_state, "client", None) is not None:
        return  # already initialized
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes, process_id=process_id)


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))


def mesh_info(mesh: Mesh) -> dict:
    return {
        "axis_names": list(mesh.axis_names),
        "shape": dict(mesh.shape),
        "n_devices": int(np.prod(list(mesh.shape.values()))),
        "devices": str(mesh.devices.ravel()[0].platform),
    }
