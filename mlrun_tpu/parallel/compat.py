"""Compatibility shims for jax API drift (mirrors the AxisType shim in
parallel/mesh.py).

``shard_map`` moved from ``jax.experimental.shard_map`` (positional mesh,
``check_rep``, partial-manual via ``auto=``) to ``jax.shard_map``
(keyword-only, ``check_vma``, partial-manual via ``axis_names=``). The
repo is written against the new calling convention; this module adapts it
onto whichever implementation the pinned jax build ships, so the
context/pipeline-parallel paths run on both.
"""

from __future__ import annotations

import functools

import jax

try:  # legacy home (jax < 0.6); removed in newer builds
    from jax.experimental.shard_map import shard_map as _legacy_shard_map
except Exception:  # noqa: BLE001
    _legacy_shard_map = None


def shard_map(f=None, *, mesh, in_specs, out_specs, check_vma=True,
              axis_names=None):
    """``jax.shard_map``-compatible wrapper.

    ``axis_names`` is the set of MANUAL axes (new-API semantics); on the
    legacy implementation it maps to ``auto = mesh.axis_names - axis_names``
    and ``check_vma`` maps to ``check_rep``. Usable directly or as a
    ``functools.partial`` decorator (both call styles appear in ops/ and
    parallel/)."""
    if f is None:
        return functools.partial(shard_map, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=check_vma,
                                 axis_names=axis_names)
    native = getattr(jax, "shard_map", None)
    if native is not None:
        kwargs = {} if axis_names is None else {"axis_names": axis_names}
        return native(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=check_vma, **kwargs)
    if _legacy_shard_map is None:  # pragma: no cover - no impl at all
        raise NotImplementedError(
            "this jax build has neither jax.shard_map nor "
            "jax.experimental.shard_map")
    kwargs = {}
    if axis_names is not None:
        kwargs["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
    return _legacy_shard_map(f, mesh, in_specs, out_specs,
                             check_rep=check_vma, **kwargs)
