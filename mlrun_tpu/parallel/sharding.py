"""Sharding rules: logical parameter axes → mesh axes.

t5x-style logical-axis rules (cf. SNIPPETS.md §1 public t5x partitioning
pattern): every parameter pytree leaf is matched by path against a rule list
and gets a PartitionSpec. XLA then inserts all ICI/DCN collectives — there is
no hand-written allreduce anywhere in the framework.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Optional, Sequence

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# default rules for the transformer parameter tree produced by
# mlrun_tpu.models.llama (path → spec); first match wins.
# Conventions: embed dim sharded on "tensor" for attention/mlp in/out,
# fsdp shards the other (large) dim so every big matrix is fully sharded.
DEFAULT_RULES: list[tuple[str, tuple]] = [
    # lora adapters [layers, in, rank] / [layers, rank, out] — MUST precede
    # the projection rules (paths look like "wq/lora_a"); rank stays
    # unsharded so any rank works on any mesh
    (r".*lora_a.*", (None, "fsdp", None)),
    (r".*lora_b.*", (None, None, "tensor")),
    (r".*scaling.*", ()),
    # token embedding [vocab, embed] — shard vocab on fsdp, embed on tensor
    (r".*embedding.*", ("fsdp", "tensor")),
    # attention projections, stacked over layers: [layers, embed, heads*head_dim]
    (r".*(wq|wk|wv).*", (None, "fsdp", "tensor")),
    # attention output [layers, heads*head_dim, embed]
    (r".*wo.*", (None, "tensor", "fsdp")),
    # mlp in/gate [layers, embed, mlp]
    (r".*(w_gate|w_up).*", (None, "fsdp", "tensor")),
    # mlp out [layers, mlp, embed]
    (r".*w_down.*", (None, "tensor", "fsdp")),
    # norms / scales / biases — replicated
    (r".*(norm|scale|bias).*", ()),
    # final head [embed, vocab]
    (r".*lm_head.*", ("tensor", "fsdp")),
]


def path_str(path) -> str:
    parts = []
    for entry in path:
        if hasattr(entry, "key"):
            parts.append(str(entry.key))
        elif hasattr(entry, "idx"):
            parts.append(str(entry.idx))
        elif hasattr(entry, "name"):
            parts.append(str(entry.name))
        else:
            parts.append(str(entry))
    return "/".join(parts)


def spec_for_path(path: str, rules: Sequence[tuple] | None = None,
                  ndim: int | None = None) -> PartitionSpec:
    rules = rules if rules is not None else DEFAULT_RULES
    for pattern, spec in rules:
        if re.match(pattern, path, flags=re.IGNORECASE):
            spec = tuple(spec)
            if ndim is not None:
                if len(spec) > ndim:
                    # drop leading axes that don't exist (unstacked params)
                    spec = spec[len(spec) - ndim:]
                elif len(spec) < ndim:
                    spec = spec + (None,) * (ndim - len(spec))
            return PartitionSpec(*spec)
    return PartitionSpec()  # replicate by default


def _filter_spec_to_mesh(spec: PartitionSpec, mesh: Mesh) -> PartitionSpec:
    """Drop axis names the mesh doesn't have (e.g. no 'tensor' on a pure-fsdp
    mesh) so the same rules work on any mesh."""
    out = []
    for entry in spec:
        if entry is None:
            out.append(None)
        elif isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a in mesh.axis_names
                         and mesh.shape[a] > 1)
            out.append(kept if kept else None)
        else:
            out.append(entry if entry in mesh.axis_names
                       and mesh.shape[entry] > 1 else None)
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


def tree_shardings(tree: Any, mesh: Mesh,
                   rules: Sequence[tuple] | None = None) -> Any:
    """Map a pytree to NamedShardings using the rules."""

    def leaf_sharding(path, leaf):
        ndim = getattr(leaf, "ndim", None)
        spec = spec_for_path(path_str(path), rules, ndim=ndim)
        spec = _filter_spec_to_mesh(spec, mesh)
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(leaf_sharding, tree)


def tree_pspecs(tree: Any, mesh: Mesh,
                rules: Sequence[tuple] | None = None) -> Any:
    def leaf_spec(path, leaf):
        ndim = getattr(leaf, "ndim", None)
        spec = spec_for_path(path_str(path), rules, ndim=ndim)
        return _filter_spec_to_mesh(spec, mesh)

    return jax.tree_util.tree_map_with_path(leaf_spec, tree)


def shard_pytree(tree: Any, mesh: Mesh,
                 rules: Sequence[tuple] | None = None) -> Any:
    """Place a host pytree onto the mesh with rule-derived shardings."""
    shardings = tree_shardings(tree, mesh, rules)
    return jax.tree_util.tree_map(jax.device_put, tree, shardings)


def batch_spec(mesh: Mesh, seq_axis: str | None = None) -> PartitionSpec:
    """Sharding for [batch, seq, ...] data: batch over all data-ish axes,
    optionally sequence over the seq axis (context parallelism)."""
    data_axes = tuple(a for a in ("data", "fsdp") if a in mesh.axis_names
                      and mesh.shape[a] > 1)
    batch_axes = data_axes if data_axes else None
    if seq_axis and seq_axis in mesh.axis_names and mesh.shape[seq_axis] > 1:
        return PartitionSpec(batch_axes, seq_axis)
    return PartitionSpec(batch_axes)


def batch_sharding(mesh: Mesh, seq_axis: str | None = None) -> NamedSharding:
    return NamedSharding(mesh, batch_spec(mesh, seq_axis))


class ShardingRules:
    """User-extensible rule table attached to a trainer."""

    def __init__(self, rules: Sequence[tuple] | None = None):
        self.rules = list(rules if rules is not None else DEFAULT_RULES)

    def add(self, pattern: str, spec: tuple, first: bool = True):
        if first:
            self.rules.insert(0, (pattern, spec))
        else:
            self.rules.append((pattern, spec))
        return self

    def shardings(self, tree, mesh: Mesh):
        return tree_shardings(tree, mesh, self.rules)

    def pspecs(self, tree, mesh: Mesh):
        return tree_pspecs(tree, mesh, self.rules)
