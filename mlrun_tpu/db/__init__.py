"""Run-DB factory (reference analog: mlrun/db/__init__.py get_run_db)."""

from __future__ import annotations

import threading

from ..config import mlconf
from .base import (  # noqa: F401
    RunDBError,
    RunDBInterface,
    sql_dialect_for_dsn,
)
from .nopdb import NopDB  # noqa: F401
from .sqlitedb import SQLiteRunDB  # noqa: F401

_run_db = None
_lock = threading.Lock()


def get_run_db(url: str = "", secrets: dict | None = None,
               force_reconnect: bool = False) -> RunDBInterface:
    """Return the process-wide run DB: HTTP client if a dbpath is configured,
    otherwise the embedded sqlite DB."""
    global _run_db
    url = url or mlconf.get("dbpath", "")
    with _lock:
        if _run_db is not None and not force_reconnect:
            return _run_db
        if url.startswith("http"):
            from .httpdb import HTTPRunDB

            _run_db = HTTPRunDB(url).connect(secrets)
        elif url == "nop":
            _run_db = NopDB()
        elif sql_dialect_for_dsn(url):
            # server-grade shared store for clusterized deployments
            from .sqldb import SQLServerRunDB

            _run_db = SQLServerRunDB(url)
        else:
            _run_db = SQLiteRunDB(url if url.endswith(".sqlite") else "")
        return _run_db


def set_run_db(db: RunDBInterface):
    """Inject a DB instance (tests use this to install RunDBMock)."""
    global _run_db
    with _lock:
        _run_db = db
