"""HTTP run-DB client (reference analog: mlrun/db/httpdb.py:78 HTTPRunDB —
retrying session :366, full REST surface :685+).

Talks to the aiohttp service (mlrun_tpu/service). Paths mirror the reference's
``/api/v1`` REST contract.
"""

from __future__ import annotations

import json
import time
from typing import Optional
from urllib.parse import quote

import requests
import requests.adapters

from ..chaos import fire as chaos_fire
from ..config import mlconf
from ..utils import logger
from .base import RunDBError, RunDBInterface


class HTTPRunDB(RunDBInterface):
    kind = "http"

    def __init__(self, url: str, token: str = ""):
        self.base_url = url.rstrip("/")
        self.user = mlconf.httpdb.user
        self.token = token or mlconf.httpdb.token
        self._session: Optional[requests.Session] = None
        self.server_version = ""

    def __repr__(self):
        return f"HTTPRunDB({self.base_url})"

    # -- plumbing ----------------------------------------------------------
    @property
    def session(self) -> requests.Session:
        if self._session is None:
            session = requests.Session()
            retry = requests.adapters.Retry(
                total=mlconf.httpdb.retries,
                backoff_factor=mlconf.httpdb.retry_backoff,
                status_forcelist=[500, 502, 503, 504],
                allowed_methods=["GET", "PUT", "DELETE", "POST"],
            )
            adapter = requests.adapters.HTTPAdapter(max_retries=retry)
            session.mount("http://", adapter)
            session.mount("https://", adapter)
            self._session = session
        return self._session

    def api_call(self, method: str, path: str, error: str | None = None,
                 params: dict | None = None, body=None, json_body=None,
                 timeout: float | None = None, json: dict | None = None,
                 raw: bool = False):
        url = f"{self.base_url}{mlconf.api_base_path}/{path.lstrip('/')}"
        headers = {}
        if self.token:
            headers["Authorization"] = f"Bearer {self.token}"
        try:
            # chaos fault point: an injected requests.RequestException
            # simulates a dead/5xx-ing control plane after client retries
            chaos_fire("httpdb.request", method=method, path=path, url=url)
            resp = self.session.request(
                method, url, params=params, data=body,
                json=json_body if json_body is not None else json,
                headers=headers, timeout=timeout or mlconf.httpdb.timeout)
        except requests.RequestException as exc:
            raise RunDBError(
                f"{error or 'api call failed'}: {method} {url}: {exc}") from exc
        if not resp.ok:
            detail = ""
            try:
                detail = resp.json().get("detail", resp.text)
            except ValueError:
                detail = resp.text
            raise RunDBError(
                f"{error or 'api call failed'}: {method} {url} "
                f"[{resp.status_code}]: {detail}")
        if raw:
            return resp.content
        if resp.content:
            try:
                return resp.json()
            except ValueError:
                return resp.content
        return {}

    def connect(self, secrets=None):
        try:
            resp = self.api_call("GET", "client-spec", "connect failed")
            spec = resp or {}
            self.server_version = spec.get("version", "")
            overrides = spec.get("config_overrides") or {}
            if overrides:
                mlconf.update(overrides)
        except RunDBError as exc:
            logger.warning("could not fetch client spec", error=str(exc))
        return self

    @staticmethod
    def _path(project: str, kind: str, *parts) -> str:
        project = project or mlconf.default_project
        tail = "/".join(quote(str(p), safe="") for p in parts if p is not None)
        return f"projects/{project}/{kind}" + (f"/{tail}" if tail else "")

    # -- runs --------------------------------------------------------------
    def store_run(self, struct, uid, project="", iter=0):
        self.api_call("POST", self._path(project, "runs", uid),
                      "store run", params={"iter": iter}, json_body=struct)

    def update_run(self, updates, uid, project="", iter=0):
        self.api_call("PATCH", self._path(project, "runs", uid),
                      "update run", params={"iter": iter}, json_body=updates)

    def read_run(self, uid, project="", iter=0):
        resp = self.api_call("GET", self._path(project, "runs", uid),
                             "read run", params={"iter": iter})
        return resp.get("data")

    def list_runs(self, name="", uid=None, project="", labels=None, state="",
                  sort=True, last=0, iter=False, start_time_from=None,
                  start_time_to=None):
        params = {"name": name, "state": state, "last": last,
                  "iter": int(iter)}
        if uid:
            params["uid"] = uid
        if labels:
            params["label"] = labels if isinstance(labels, list) else [
                f"{k}={v}" for k, v in labels.items()]
        resp = self.api_call("GET", self._path(project, "runs"), "list runs",
                             params=params)
        return resp.get("runs", [])

    @staticmethod
    def _encode_list_filters(filters: dict) -> dict:
        """Map pythonic filter kwargs onto the server's query encoding
        (same mapping list_runs/list_artifacts use inline)."""
        params = dict(filters)
        labels = params.pop("labels", None)
        if labels:
            params["label"] = labels if isinstance(labels, list) else [
                f"{k}={v}" for k, v in labels.items()]
        if "iter" in params:
            params["iter"] = int(bool(params["iter"]))
        return {k: v for k, v in params.items() if v not in (None, "")}

    def paginated_list_runs(self, project="", page_size=20, page_token="",
                            **filters) -> tuple[list, str | None]:
        """Token-paginated listing (reference httpdb.py:304). Returns
        (runs, next_token); pass next_token back until it is None."""
        params = self._encode_list_filters(filters)
        params["page_size"] = page_size
        if page_token:
            params["page_token"] = page_token
        resp = self.api_call("GET", self._path(project, "runs"),
                             "list runs", params=params)
        return (resp.get("runs", []),
                (resp.get("pagination") or {}).get("page_token"))

    def paginated_list_artifacts(self, project="", page_size=20,
                                 page_token="", **filters
                                 ) -> tuple[list, str | None]:
        params = self._encode_list_filters(filters)
        params["page_size"] = page_size
        if page_token:
            params["page_token"] = page_token
        resp = self.api_call("GET", self._path(project, "artifacts"),
                             "list artifacts", params=params)
        return (resp.get("artifacts", []),
                (resp.get("pagination") or {}).get("page_token"))

    def del_run(self, uid, project="", iter=0):
        self.api_call("DELETE", self._path(project, "runs", uid), "del run",
                      params={"iter": iter})

    def abort_run(self, uid, project="", iter=0, status_text=""):
        self.api_call("POST", self._path(project, "runs", uid) + "/abort",
                      "abort run", json_body={"status_text": status_text})

    # -- logs --------------------------------------------------------------
    def store_log(self, uid, project="", body=b"", append=True):
        if isinstance(body, str):
            body = body.encode()
        self.api_call("POST", self._path(project, "logs", uid), "store log",
                      params={"append": int(append)}, body=body)

    def get_log(self, uid, project="", offset=0, size=-1):
        url = f"{self.base_url}{mlconf.api_base_path}/" + self._path(
            project, "logs", uid)
        resp = self.session.get(
            url, params={"offset": offset, "size": size},
            timeout=mlconf.httpdb.timeout)
        if not resp.ok:
            raise RunDBError(f"get log failed [{resp.status_code}]")
        state = resp.headers.get("x-mlt-run-state", "unknown")
        return state, resp.content

    # -- artifacts ---------------------------------------------------------
    def store_artifact(self, key, artifact, uid=None, iter=None, tag="",
                       project="", tree=None):
        self.api_call(
            "POST", self._path(project, "artifacts", key), "store artifact",
            params={"uid": uid, "iter": iter, "tag": tag, "tree": tree},
            json_body=artifact)

    def read_artifact(self, key, tag=None, iter=None, project="", tree=None,
                      uid=None):
        resp = self.api_call(
            "GET", self._path(project, "artifacts", key), "read artifact",
            params={"tag": tag, "iter": iter, "tree": tree, "uid": uid})
        return resp.get("data")

    def list_artifacts(self, name="", project="", tag=None, labels=None,
                       since=None, until=None, kind=None, category=None,
                       tree=None):
        params = {"name": name, "tag": tag, "kind": kind, "tree": tree}
        if labels:
            params["label"] = labels if isinstance(labels, list) else [
                f"{k}={v}" for k, v in labels.items()]
        resp = self.api_call("GET", self._path(project, "artifacts"),
                             "list artifacts", params=params)
        return resp.get("artifacts", [])

    def del_artifact(self, key, tag=None, project="", uid=None):
        self.api_call("DELETE", self._path(project, "artifacts", key),
                      "del artifact", params={"tag": tag, "uid": uid})

    # -- functions ---------------------------------------------------------
    def store_function(self, function, name, project="", tag="",
                       versioned=False):
        resp = self.api_call(
            "POST", self._path(project, "functions", name), "store function",
            params={"tag": tag, "versioned": int(versioned)},
            json_body=function)
        return resp.get("hash_key", "")

    def get_function(self, name, project="", tag="", hash_key=""):
        resp = self.api_call(
            "GET", self._path(project, "functions", name), "get function",
            params={"tag": tag, "hash_key": hash_key})
        return resp.get("func")

    def list_functions(self, name="", project="", tag="", labels=None):
        params = {"name": name, "tag": tag}
        if labels:
            params["label"] = labels if isinstance(labels, list) else [
                f"{k}={v}" for k, v in labels.items()]
        resp = self.api_call("GET", self._path(project, "functions"),
                             "list functions", params=params)
        return resp.get("funcs", [])

    def delete_function(self, name, project=""):
        self.api_call("DELETE", self._path(project, "functions", name),
                      "delete function")

    # -- projects ----------------------------------------------------------
    def store_project(self, name, project):
        resp = self.api_call("POST", f"projects/{name}", "store project",
                             json_body=project)
        return resp.get("data", project)

    def get_project(self, name):
        try:
            resp = self.api_call("GET", f"projects/{name}", "get project")
        except RunDBError as exc:
            if "[404]" in str(exc):
                return None
            raise
        return resp.get("data")

    def list_projects(self, owner=None, labels=None, state=None):
        resp = self.api_call("GET", "projects", "list projects",
                             params={"state": state})
        return resp.get("projects", [])

    def delete_project(self, name, deletion_strategy="restricted"):
        self.api_call("DELETE", f"projects/{name}", "delete project",
                      params={"deletion_strategy": deletion_strategy})

    # -- schedules ---------------------------------------------------------
    def store_schedule(self, project, name, schedule):
        self.api_call("POST", self._path(project, "schedules", name),
                      "store schedule", json_body=schedule)

    def get_schedule(self, project, name):
        resp = self.api_call("GET", self._path(project, "schedules", name),
                             "get schedule")
        return resp.get("data")

    def list_schedules(self, project=""):
        resp = self.api_call("GET", self._path(project, "schedules"),
                             "list schedules")
        return resp.get("schedules", [])

    def delete_schedule(self, project, name):
        self.api_call("DELETE", self._path(project, "schedules", name),
                      "delete schedule")

    # -- feature store ------------------------------------------------------
    def store_feature_set(self, feature_set, name=None, project="", tag=None,
                          uid=None, versioned=True):
        name = name or feature_set.get("metadata", {}).get("name")
        resp = self.api_call(
            "POST", self._path(project, "feature-sets", name),
            "store feature set", params={"tag": tag, "uid": uid},
            json_body=feature_set)
        return resp.get("uid", "")

    def get_feature_set(self, name, project="", tag=None, uid=None):
        resp = self.api_call(
            "GET", self._path(project, "feature-sets", name),
            "get feature set", params={"tag": tag, "uid": uid})
        return resp.get("data")

    def list_feature_sets(self, project="", name="", tag=None, labels=None):
        resp = self.api_call("GET", self._path(project, "feature-sets"),
                             "list feature sets",
                             params={"name": name, "tag": tag})
        return resp.get("feature_sets", [])

    def delete_feature_set(self, name, project="", tag=None, uid=None):
        self.api_call("DELETE", self._path(project, "feature-sets", name),
                      "delete feature set")

    def store_feature_vector(self, feature_vector, name=None, project="",
                             tag=None, uid=None, versioned=True):
        name = name or feature_vector.get("metadata", {}).get("name")
        resp = self.api_call(
            "POST", self._path(project, "feature-vectors", name),
            "store feature vector", params={"tag": tag, "uid": uid},
            json_body=feature_vector)
        return resp.get("uid", "")

    def get_feature_vector(self, name, project="", tag=None, uid=None):
        resp = self.api_call(
            "GET", self._path(project, "feature-vectors", name),
            "get feature vector", params={"tag": tag, "uid": uid})
        return resp.get("data")

    def list_feature_vectors(self, project="", name="", tag=None, labels=None):
        resp = self.api_call("GET", self._path(project, "feature-vectors"),
                             "list feature vectors",
                             params={"name": name, "tag": tag})
        return resp.get("feature_vectors", [])

    def delete_feature_vector(self, name, project="", tag=None, uid=None):
        self.api_call("DELETE", self._path(project, "feature-vectors", name),
                      "delete feature vector")

    # -- model endpoints ----------------------------------------------------
    def store_model_endpoint(self, project, endpoint_id, endpoint):
        self.api_call("POST",
                      self._path(project, "model-endpoints", endpoint_id),
                      "store model endpoint", json_body=endpoint)

    def get_model_endpoint(self, project, endpoint_id):
        resp = self.api_call(
            "GET", self._path(project, "model-endpoints", endpoint_id),
            "get model endpoint")
        return resp.get("data")

    def list_model_endpoints(self, project="", model="", function="", state=""):
        resp = self.api_call(
            "GET", self._path(project, "model-endpoints"),
            "list model endpoints",
            params={"model": model, "function": function, "state": state})
        return resp.get("endpoints", [])

    def delete_model_endpoint(self, project, endpoint_id):
        self.api_call("DELETE",
                      self._path(project, "model-endpoints", endpoint_id),
                      "delete model endpoint")

    def get_model_endpoint_metrics(self, project, endpoint_id, name="",
                                   start: float = 0, end=None,
                                   max_points: int = 1000) -> list[dict]:
        """Metric time-series for an endpoint (reference: model-endpoint
        metric-values API over the TSDB layer)."""
        params = {"name": name, "start": start,
                  "max_points": max_points}
        if end is not None:
            params["end"] = end
        resp = self.api_call(
            "GET",
            self._path(project, "model-endpoints", endpoint_id, "metrics"),
            "endpoint metrics", params=params)
        return resp.get("series", [])

    def list_model_endpoint_metric_names(self, project,
                                         endpoint_id) -> list[str]:
        resp = self.api_call(
            "GET",
            self._path(project, "model-endpoints", endpoint_id, "metrics"),
            "endpoint metric names", params={"names_only": "true"})
        return resp.get("metrics", [])

    def list_background_tasks(self, project=""):
        resp = self.api_call(
            "GET", self._path(project, "background-tasks"),
            "list background tasks")
        return resp.get("background_tasks", [])

    # -- tags (reference mlrun/db/httpdb.py:2722 tag_objects) ---------------
    def tag_objects(self, project, tag, identifiers, kind="artifact"):
        """Apply ``tag`` to the identified objects (artifact key[/uid])."""
        resp = self.api_call(
            "POST", self._path(project, "tags", tag), "tag objects",
            json_body={"kind": kind, "identifiers": identifiers})
        return resp.get("tagged", 0)

    def delete_objects_tag(self, project, tag, identifiers,
                           kind="artifact"):
        resp = self.api_call(
            "DELETE", self._path(project, "tags", tag), "untag objects",
            json_body={"kind": kind, "identifiers": identifiers})
        return resp.get("removed", 0)

    # -- files --------------------------------------------------------------
    def get_file(self, path, project="", size=None, offset=0) -> bytes:
        """Read a file through the service's datastore (server-side
        credentials/profiles apply)."""
        params = {"path": path, "offset": str(offset)}
        if size:
            params["size"] = str(size)
        return self.api_call("GET", self._path(project, "files"),
                             "get file", params=params, raw=True)

    def get_filestat(self, path, project=""):
        return self.api_call("GET", self._path(project, "filestat"),
                             "stat file", params={"path": path})

    # -- hub admin ----------------------------------------------------------
    def store_hub_source(self, name, source: dict, order: int = -1):
        resp = self.api_call("PUT", f"hub/sources/{name}",
                             "store hub source",
                             json_body={"source": source, "order": order})
        return resp.get("data")

    def list_hub_sources(self):
        return self.api_call("GET", "hub/sources",
                             "list hub sources").get("sources", [])

    def get_hub_source(self, name):
        return self.api_call("GET", f"hub/sources/{name}",
                             "get hub source").get("data")

    def delete_hub_source(self, name):
        self.api_call("DELETE", f"hub/sources/{name}", "delete hub source")

    def get_hub_catalog(self, source_name: str):
        return self.api_call(
            "GET", f"hub/sources/{source_name}/items",
            "hub catalog").get("catalog", [])

    def get_hub_item(self, source_name: str, item: str):
        return self.api_call(
            "GET", f"hub/sources/{source_name}/items/{item}",
            "hub item").get("data")

    # -- alerts -------------------------------------------------------------
    def store_alert_config(self, name, config, project=""):
        self.api_call("POST", self._path(project, "alerts", name),
                      "store alert", json_body=config)

    def silence_alert(self, name, minutes: float, project=""):
        """Silence an alert for ``minutes`` (0 clears the window)."""
        resp = self.api_call(
            "POST", self._path(project, "alerts", name) + "/silence",
            "silence alert", json_body={"minutes": minutes})
        return resp.get("data")

    def get_alert_config(self, name, project=""):
        resp = self.api_call("GET", self._path(project, "alerts", name),
                             "get alert")
        return resp.get("data")

    def list_alert_configs(self, project=""):
        resp = self.api_call("GET", self._path(project, "alerts"),
                             "list alerts")
        return resp.get("alerts", [])

    def delete_alert_config(self, name, project=""):
        self.api_call("DELETE", self._path(project, "alerts", name),
                      "delete alert")

    def emit_event(self, kind, event, project=""):
        self.api_call("POST", self._path(project, "events", kind),
                      "emit event", json_body=event)

    # -- project secrets (reference mlrun/db/httpdb.py:3034-3232; values
    # are write-only over HTTP — list returns key names only) --------------
    def create_project_secrets(self, project: str, secrets: dict,
                               provider: str = "kubernetes"):
        self.api_call(
            "POST", self._path(project, "secrets"), "store secrets",
            json_body={"provider": provider, "secrets": secrets})

    # same operation under the server-side store's name, so code written
    # against either db implementation (e.g. notification masking) works
    store_project_secrets = create_project_secrets

    def list_project_secret_keys(self, project: str,
                                 provider: str = "kubernetes") -> list[str]:
        resp = self.api_call(
            "GET", self._path(project, "secret-keys"), "list secret keys",
            params={"provider": provider})
        return resp.get("secret_keys", [])

    def delete_project_secrets(self, project: str,
                               secrets: list | None = None,
                               provider: str = "kubernetes"):
        if secrets is not None and not secrets:
            return  # an empty key list deletes nothing (None deletes all)
        params: dict = {"provider": provider}
        if secrets is not None:
            params["secret"] = secrets
        self.api_call("DELETE", self._path(project, "secrets"),
                      "delete secrets", params=params)

    # -- datastore profiles -------------------------------------------------
    def store_datastore_profile(self, profile: dict, project: str = "",
                                private: dict | None = None):
        self.api_call(
            "PUT",
            self._path(project, "datastore-profiles", profile["name"]),
            "store datastore profile",
            json_body={"profile": profile, "private": private})

    def get_datastore_profile(self, name: str, project: str = ""
                              ) -> dict | None:
        try:
            resp = self.api_call(
                "GET", self._path(project, "datastore-profiles", name),
                "get datastore profile")
        except RunDBError as exc:
            if "not found" in str(exc):
                return None  # same missing-profile contract as SQLiteRunDB
            raise
        return resp.get("data")

    def list_datastore_profiles(self, project: str = "") -> list[dict]:
        resp = self.api_call(
            "GET", self._path(project, "datastore-profiles"),
            "list datastore profiles")
        return resp.get("datastore_profiles", [])

    def delete_datastore_profile(self, name: str, project: str = ""):
        self.api_call(
            "DELETE", self._path(project, "datastore-profiles", name),
            "delete datastore profile")

    # -- submit / build -----------------------------------------------------
    def submit_job(self, runspec: dict, schedule=None) -> dict:
        body = dict(runspec)
        if schedule:
            body["schedule"] = schedule
        return self.api_call("POST", "submit_job", "submit job",
                             json_body=body,
                             timeout=max(mlconf.httpdb.timeout, 120))

    def submit_pipeline(self, project, pipeline, arguments=None,
                        experiment=None, run=None, namespace=None,
                        artifact_path=None, ops=None) -> str:
        resp = self.api_call(
            "POST", self._path(project, "workflows") + "/submit",
            "submit pipeline",
            json_body={"pipeline": pipeline, "arguments": arguments or {},
                       "artifact_path": artifact_path})
        return resp.get("id", "")

    def list_pipelines(self, project: str = "*") -> dict:
        """Reference: mlrun/db/httpdb.py submit/list pipelines proxy."""
        return self.api_call(
            "GET", self._path(project or "*", "pipelines"), "list pipelines")

    def get_pipeline(self, run_id: str, project: str = "*") -> dict:
        return self.api_call(
            "GET", self._path(project or "*", "pipelines", run_id),
            "get pipeline")

    def list_runtime_resources(self, project: str = "*",
                               kind: str = "") -> list[dict]:
        """Reference: mlrun/db/httpdb.py list_runtime_resources — grouped
        per-kind cluster resources for a project ('*' = all)."""
        params = {"kind": kind} if kind else None
        resp = self.api_call(
            "GET", self._path(project or "*", "runtime-resources"),
            "list runtime resources", params=params)
        return resp.get("runtime_resources", [])

    def delete_runtime_resources(self, project: str = "*", kind: str = "",
                                 object_id: str = "",
                                 force: bool = False) -> list[dict]:
        params = {}
        if kind:
            params["kind"] = kind
        if object_id:
            params["object-id"] = object_id
        if force:
            params["force"] = "true"
        resp = self.api_call(
            "DELETE", self._path(project or "*", "runtime-resources"),
            "delete runtime resources", params=params or None)
        return resp.get("deleted", [])

    def remote_builder(self, func, with_tpu: bool = False) -> dict:
        return self.api_call(
            "POST", "build/function", "remote build",
            json_body={"function": func.to_dict(), "with_tpu": with_tpu})

    def get_builder_status(self, func, offset=0, logs=True):
        # tag must travel: a function deployed as `mytag` keeps its build
        # status under that tag — omitting it made the server default to
        # `latest` and 404 polls on non-latest deploys (ADVICE r3/r4)
        return self.api_call(
            "GET", "build/status", "build status",
            params={"name": func.metadata.name,
                    "project": func.metadata.project, "offset": offset,
                    "tag": getattr(func.metadata, "tag", "") or "latest"})

    def get_background_task(self, name: str, project: str = ""):
        resp = self.api_call("GET",
                             self._path(project, "background-tasks", name),
                             "get background task")
        return resp.get("data")

    def trigger_migrations(self):
        return self.api_call("POST", "operations/migrations",
                             "trigger migrations")

    def get_log_size(self, uid, project=""):
        resp = self.api_call("GET",
                             self._path(project, "logs", uid) + "/size",
                             "get log size")
        return resp.get("size", 0)

    def verify_authorization(self, *args, **kwargs):
        return True
