"""Embedded SQLite run DB.

Reference analog: server/api/db/sqldb (SQLAlchemy models+query layer,
server/api/db/sqldb/models.py:195-700, db.py). Fresh implementation on stdlib
``sqlite3`` with JSON bodies — the same class backs both the client's local mode
(no service configured) and the aiohttp service, mirroring how the reference's
SQLDB is shared by the api layer.

Logs are stored as files under ``<home>/logs/<project>/<uid>`` like the
reference's file-target log collection (server/log-collector streams pod logs
into files; server.go:731).
"""

from __future__ import annotations

import json
import os
import sqlite3
import threading
from typing import Optional

from ..common.runtimes_constants import RunStates
from ..config import mlconf
from ..utils import generate_uid, get_in, now_iso, update_in
from .base import RunDBError, RunDBInterface

_SCHEMA = """
CREATE TABLE IF NOT EXISTS runs (
    project TEXT NOT NULL, uid TEXT NOT NULL, iteration INTEGER NOT NULL DEFAULT 0,
    name TEXT, state TEXT, start_time TEXT, last_update TEXT, body TEXT,
    PRIMARY KEY (project, uid, iteration)
);
CREATE TABLE IF NOT EXISTS artifacts (
    project TEXT NOT NULL, key TEXT NOT NULL, uid TEXT NOT NULL,
    tree TEXT, iteration INTEGER DEFAULT 0, tag TEXT, kind TEXT,
    updated TEXT, body TEXT,
    PRIMARY KEY (project, key, uid)
);
CREATE TABLE IF NOT EXISTS functions (
    project TEXT NOT NULL, name TEXT NOT NULL, tag TEXT NOT NULL DEFAULT 'latest',
    hash_key TEXT, updated TEXT, body TEXT,
    PRIMARY KEY (project, name, tag)
);
CREATE TABLE IF NOT EXISTS function_versions (
    project TEXT NOT NULL, name TEXT NOT NULL, hash_key TEXT NOT NULL,
    updated TEXT, body TEXT,
    PRIMARY KEY (project, name, hash_key)
);
CREATE TABLE IF NOT EXISTS projects (
    name TEXT PRIMARY KEY, state TEXT, created TEXT, body TEXT
);
CREATE TABLE IF NOT EXISTS schedules (
    project TEXT NOT NULL, name TEXT NOT NULL, kind TEXT,
    cron TEXT, next_run_time TEXT, body TEXT,
    PRIMARY KEY (project, name)
);
CREATE TABLE IF NOT EXISTS feature_sets (
    project TEXT NOT NULL, name TEXT NOT NULL, tag TEXT NOT NULL DEFAULT 'latest',
    uid TEXT, updated TEXT, body TEXT,
    PRIMARY KEY (project, name, tag)
);
CREATE TABLE IF NOT EXISTS feature_vectors (
    project TEXT NOT NULL, name TEXT NOT NULL, tag TEXT NOT NULL DEFAULT 'latest',
    uid TEXT, updated TEXT, body TEXT,
    PRIMARY KEY (project, name, tag)
);
CREATE TABLE IF NOT EXISTS model_endpoints (
    project TEXT NOT NULL, uid TEXT NOT NULL, model TEXT, function TEXT,
    state TEXT, updated TEXT, body TEXT,
    PRIMARY KEY (project, uid)
);
CREATE TABLE IF NOT EXISTS background_tasks (
    project TEXT NOT NULL DEFAULT '', name TEXT NOT NULL, state TEXT,
    created TEXT, updated TEXT, body TEXT,
    PRIMARY KEY (project, name)
);
CREATE TABLE IF NOT EXISTS alert_configs (
    project TEXT NOT NULL, name TEXT NOT NULL, body TEXT,
    PRIMARY KEY (project, name)
);
CREATE TABLE IF NOT EXISTS events (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    project TEXT, kind TEXT, created TEXT, body TEXT
);
CREATE TABLE IF NOT EXISTS hub_sources (
    name TEXT PRIMARY KEY, idx INTEGER, body TEXT
);
CREATE TABLE IF NOT EXISTS runtime_resources (
    project TEXT NOT NULL, uid TEXT NOT NULL, kind TEXT,
    resource_id TEXT, started REAL, tag TEXT NOT NULL DEFAULT '',
    PRIMARY KEY (project, uid)
);
CREATE TABLE IF NOT EXISTS project_secrets (
    project TEXT NOT NULL, provider TEXT NOT NULL DEFAULT 'kubernetes',
    name TEXT NOT NULL, value TEXT,
    PRIMARY KEY (project, provider, name)
);
CREATE TABLE IF NOT EXISTS pagination_cache (
    token TEXT PRIMARY KEY, method TEXT, filters TEXT,
    next_offset INTEGER, created TEXT
);
CREATE TABLE IF NOT EXISTS datastore_profiles (
    project TEXT NOT NULL, name TEXT NOT NULL, type TEXT, body TEXT,
    PRIMARY KEY (project, name)
);
CREATE TABLE IF NOT EXISTS artifact_tags (
    project TEXT NOT NULL, key TEXT NOT NULL, tag TEXT NOT NULL,
    uid TEXT NOT NULL,
    PRIMARY KEY (project, key, tag)
);
CREATE INDEX IF NOT EXISTS idx_runs_project_state ON runs (project, state);
CREATE INDEX IF NOT EXISTS idx_artifacts_proj_key ON artifacts (project, key);
"""

# Schema versioning via PRAGMA user_version (reference analog: the 29
# Alembic migrations under server/api/migrations/). A fresh DB is created
# at SCHEMA_VERSION; an existing DB replays only the missing migrations in
# order. Version 1 is the round-1 pre-versioning schema (user_version 0
# with a populated sqlite_master).
SCHEMA_VERSION = 8

_MIGRATIONS: dict[int, str] = {
    2: """
CREATE TABLE IF NOT EXISTS runtime_resources (
    project TEXT NOT NULL, uid TEXT NOT NULL, kind TEXT,
    resource_id TEXT, started REAL,
    PRIMARY KEY (project, uid)
);
""",
    3: """
CREATE TABLE IF NOT EXISTS project_secrets (
    project TEXT NOT NULL, provider TEXT NOT NULL DEFAULT 'kubernetes',
    name TEXT NOT NULL, value TEXT,
    PRIMARY KEY (project, provider, name)
);
""",
    4: """
CREATE TABLE IF NOT EXISTS pagination_cache (
    token TEXT PRIMARY KEY, method TEXT, filters TEXT,
    next_offset INTEGER, created TEXT
);
""",
    5: """
CREATE TABLE IF NOT EXISTS datastore_profiles (
    project TEXT NOT NULL, name TEXT NOT NULL, type TEXT, body TEXT,
    PRIMARY KEY (project, name)
);
""",
    6: """
CREATE TABLE IF NOT EXISTS hub_sources (
    name TEXT PRIMARY KEY, idx INTEGER NOT NULL DEFAULT 0, body TEXT
);
""",
    7: """
CREATE TABLE IF NOT EXISTS artifact_tags (
    project TEXT NOT NULL, key TEXT NOT NULL, tag TEXT NOT NULL,
    uid TEXT NOT NULL,
    PRIMARY KEY (project, key, tag)
);
""",
    8: """
ALTER TABLE runtime_resources ADD COLUMN tag TEXT NOT NULL DEFAULT '';
""",
}


def _labels_match(body: dict, labels) -> bool:
    if not labels:
        return True
    have = get_in(body, "metadata.labels", {}) or {}
    items = labels.items() if isinstance(labels, dict) else [
        tuple(lbl.split("=", 1)) if "=" in lbl else (lbl, None) for lbl in labels
    ]
    for key, value in items:
        if key not in have:
            return False
        if value is not None and str(have[key]) != str(value):
            return False
    return True


class SQLiteRunDB(RunDBInterface):
    kind = "sqlite"

    def __init__(self, dsn: str = "", logs_dir: str = ""):
        self.dsn = dsn or mlconf.resolve_local_db_path()
        self.logs_dir = logs_dir or os.path.join(mlconf.home_dir, "logs")
        self._local = threading.local()
        self._log_collector = None
        self._log_collector_checked = False
        self._init_schema()

    def _get_log_collector(self):
        """Native mlt-logd client when MLT_LOG_COLLECTOR is configured
        (falls back to direct file IO)."""
        if not self._log_collector_checked:
            self._log_collector_checked = True
            if os.environ.get("MLT_LOG_COLLECTOR"):
                from ..utils.log_collector import LogCollectorClient

                client = LogCollectorClient()
                if client.ping():
                    self._log_collector = client
        return self._log_collector

    # -- plumbing ----------------------------------------------------------
    @property
    def _conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self.dsn, timeout=30)
            conn.row_factory = sqlite3.Row
            conn.execute("PRAGMA journal_mode=WAL")
            conn.execute("PRAGMA busy_timeout=30000")
            self._local.conn = conn
        return conn

    def _init_schema(self):
        conn = self._conn
        version = conn.execute("PRAGMA user_version").fetchone()[0]
        if version == 0:
            populated = conn.execute(
                "SELECT name FROM sqlite_master "
                "WHERE type='table' AND name='runs'").fetchone()
            if populated:
                version = 1  # pre-versioning (round-1) deployment
            else:
                conn.executescript(_SCHEMA)
                conn.execute(f"PRAGMA user_version={SCHEMA_VERSION}")
                conn.commit()
                return
        if version > SCHEMA_VERSION:
            raise RunDBError(
                f"database schema version {version} is newer than this "
                f"build supports ({SCHEMA_VERSION})")
        for target in range(version + 1, SCHEMA_VERSION + 1):
            conn.executescript(_MIGRATIONS[target])
            conn.execute(f"PRAGMA user_version={target}")
            conn.commit()

    @property
    def schema_version(self) -> int:
        return self._conn.execute("PRAGMA user_version").fetchone()[0]

    def _execute(self, sql: str, params: tuple = ()):
        cur = self._conn.execute(sql, params)
        self._conn.commit()
        return cur

    def _query(self, sql: str, params: tuple = ()) -> list[sqlite3.Row]:
        return self._conn.execute(sql, params).fetchall()

    @staticmethod
    def _project_or_default(project: str) -> str:
        return project or mlconf.default_project

    # -- runs --------------------------------------------------------------
    def store_run(self, struct: dict, uid: str, project: str = "", iter: int = 0):
        project = self._project_or_default(project)
        self._execute(
            "INSERT OR REPLACE INTO runs "
            "(project, uid, iteration, name, state, start_time, last_update, body) "
            "VALUES (?,?,?,?,?,?,?,?)",
            (
                project, uid, iter,
                get_in(struct, "metadata.name", ""),
                get_in(struct, "status.state", RunStates.created),
                get_in(struct, "status.start_time", now_iso()),
                now_iso(), json.dumps(struct, default=str),
            ),
        )

    def update_run(self, updates: dict, uid: str, project: str = "", iter: int = 0):
        project = self._project_or_default(project)
        run = self.read_run(uid, project, iter)
        if run is None:
            raise RunDBError(f"run {project}/{uid} not found")
        for key, value in updates.items():
            update_in(run, key, value)
        update_in(run, "status.last_update", now_iso())
        self.store_run(run, uid, project, iter)
        return run

    def read_run(self, uid: str, project: str = "", iter: int = 0) -> Optional[dict]:
        project = self._project_or_default(project)
        rows = self._query(
            "SELECT body FROM runs WHERE project=? AND uid=? AND iteration=?",
            (project, uid, iter),
        )
        if not rows:
            return None
        return json.loads(rows[0]["body"])

    def list_runs(self, name="", uid=None, project="", labels=None, state="",
                  sort=True, last=0, iter=False, start_time_from=None,
                  start_time_to=None) -> list:
        project = self._project_or_default(project)
        sql = "SELECT body FROM runs WHERE project=?"
        params: list = [project]
        if name:
            sql += " AND name LIKE ?"
            params.append(f"%{name}%")
        if uid:
            uids = uid if isinstance(uid, (list, tuple)) else [uid]
            sql += f" AND uid IN ({','.join('?' * len(uids))})"
            params.extend(uids)
        if state:
            sql += " AND state=?"
            params.append(state)
        if not iter:
            sql += " AND iteration=0"
        if start_time_from:
            sql += " AND start_time>=?"
            params.append(str(start_time_from))
        if start_time_to:
            sql += " AND start_time<=?"
            params.append(str(start_time_to))
        if sort:
            sql += " ORDER BY start_time DESC"
        if last:
            sql += f" LIMIT {int(last)}"
        rows = self._query(sql, tuple(params))
        out = [json.loads(r["body"]) for r in rows]
        return [r for r in out if _labels_match(r, labels)]

    def del_run(self, uid: str, project: str = "", iter: int = 0):
        project = self._project_or_default(project)
        self._execute("DELETE FROM runs WHERE project=? AND uid=? AND iteration=?",
                      (project, uid, iter))

    def del_runs(self, name="", project="", labels=None, state="", days_ago=0):
        for run in self.list_runs(name=name, project=project, labels=labels,
                                  state=state, iter=True):
            self.del_run(get_in(run, "metadata.uid"), project,
                         get_in(run, "metadata.iteration", 0))

    # -- token pagination (reference analog: pagination_cache in
    # server/api/db/sqldb/models.py + paginated list calls in
    # mlrun/db/httpdb.py:304). A token is an opaque handle to a cached
    # (method, filters, position); the same token advances in place on
    # each page and is dropped when the listing is exhausted. ---------------
    _PAGE_TOKEN_TTL_SECONDS = 3600

    def paginated_list(self, method: str, page_size: int = 20,
                       page_token: str = "", **filters
                       ) -> tuple[list, Optional[str]]:
        """Page through any list_* method with an opaque token. Returns
        (items, next_token); next_token is None on the last page.

        Positioning is offset-based over a re-executed query (the filters
        travel with the token), matching the reference's pagination-cache
        semantics: rows inserted/deleted mid-walk can shift later pages.
        """
        import secrets as pysecrets
        from datetime import datetime, timedelta, timezone

        page_size = max(1, int(page_size))
        now = datetime.now(timezone.utc)
        self._execute(
            "DELETE FROM pagination_cache WHERE created < ?",
            ((now - timedelta(
                seconds=self._PAGE_TOKEN_TTL_SECONDS)).isoformat(),))
        if page_token:
            rows = self._query(
                "SELECT method, filters, next_offset FROM pagination_cache "
                "WHERE token=?", (page_token,))
            if not rows:
                raise RunDBError(f"invalid or expired page token "
                                 f"'{page_token}'")
            if rows[0]["method"] != method:
                raise RunDBError(
                    f"page token was issued for {rows[0]['method']!r}, "
                    f"not {method!r}")
            filters = json.loads(rows[0]["filters"])
            offset = int(rows[0]["next_offset"])
        else:
            offset = 0
        if not method.startswith("list_") or not hasattr(self, method):
            raise RunDBError(f"unknown list method '{method}'")
        items = getattr(self, method)(**filters)
        page = items[offset:offset + page_size]
        next_offset = offset + page_size
        if next_offset >= len(items):
            if page_token:
                self._execute("DELETE FROM pagination_cache WHERE token=?",
                              (page_token,))
            return page, None
        token = page_token or pysecrets.token_urlsafe(16)
        self._execute(
            "INSERT OR REPLACE INTO pagination_cache "
            "(token, method, filters, next_offset, created) "
            "VALUES (?,?,?,?,?)",
            (token, method, json.dumps(filters), next_offset,
             now.isoformat()))
        return page, token

    # -- runtime resources (durable handler state; reference recovers by
    # listing cluster resources per label selector, base.py:65 — here the
    # mapping survives service restarts in the DB and is reconciled against
    # the provider on startup) ---------------------------------------------
    def store_runtime_resource(self, uid: str, project: str, kind: str,
                               resource_id: str, started: float,
                               tag: str = ""):
        project = self._project_or_default(project)
        self._execute(
            "INSERT OR REPLACE INTO runtime_resources "
            "(project, uid, kind, resource_id, started, tag) "
            "VALUES (?,?,?,?,?,?)",
            (project, uid, kind, resource_id, started, tag or ""))

    def list_runtime_resources(self, kind: str = "") -> list[dict]:
        sql = ("SELECT project, uid, kind, resource_id, started, tag "
               "FROM runtime_resources")
        params: tuple = ()
        if kind:
            sql += " WHERE kind=?"
            params = (kind,)
        return [dict(row) for row in self._query(sql, params)]

    def del_runtime_resource(self, uid: str, project: str = ""):
        project = self._project_or_default(project)
        self._execute(
            "DELETE FROM runtime_resources WHERE project=? AND uid=?",
            (project, uid))

    # -- project secrets (reference: mlrun/db/httpdb.py:3034-3232 client +
    # k8s-secret store server-side; here a DB-backed store whose VALUES are
    # only readable server-side — the HTTP surface exposes keys alone) -----
    def store_project_secrets(self, project: str, secrets: dict,
                              provider: str = "kubernetes"):
        project = self._project_or_default(project)
        for name, value in (secrets or {}).items():
            self._execute(
                "INSERT OR REPLACE INTO project_secrets "
                "(project, provider, name, value) VALUES (?,?,?,?)",
                (project, provider, name, str(value)))

    def list_project_secret_keys(self, project: str,
                                 provider: str = "kubernetes") -> list[str]:
        project = self._project_or_default(project)
        rows = self._query(
            "SELECT name FROM project_secrets WHERE project=? AND provider=? "
            "ORDER BY name", (project, provider))
        return [row["name"] for row in rows]

    def get_project_secrets(self, project: str, keys: list | None = None,
                            provider: str = "kubernetes") -> dict:
        """Server-side only: returns secret VALUES (never exposed over the
        REST list surface)."""
        project = self._project_or_default(project)
        rows = self._query(
            "SELECT name, value FROM project_secrets "
            "WHERE project=? AND provider=?", (project, provider))
        out = {row["name"]: row["value"] for row in rows}
        if keys is not None:
            out = {k: v for k, v in out.items() if k in keys}
        return out

    def delete_project_secrets(self, project: str, keys: list | None = None,
                               provider: str = "kubernetes"):
        project = self._project_or_default(project)
        if keys is None:
            self._execute(
                "DELETE FROM project_secrets WHERE project=? AND provider=?",
                (project, provider))
            return
        for key in keys:
            self._execute(
                "DELETE FROM project_secrets "
                "WHERE project=? AND provider=? AND name=?",
                (project, provider, key))

    # -- datastore profiles (reference datastore_profile.py server side:
    # public part in the DB, private part in project secrets) --------------
    # -- hub sources (reference analog: server/api/api/endpoints/hub.py
    # source CRUD + catalog; backed here by the hub_sources table) ----------
    def store_hub_source(self, name: str, source: dict, order: int = -1):
        if order < 0:
            existing = self._query(
                "SELECT idx FROM hub_sources WHERE name=?", (name,))
            if existing:
                # update in place keeps the source's position
                order = int(existing[0]["idx"])
            else:
                row = self._query(
                    "SELECT COALESCE(MAX(idx), -1) AS m FROM hub_sources")
                order = int(row[0]["m"]) + 1
        source = dict(source, name=name)
        self._execute(
            "INSERT OR REPLACE INTO hub_sources (name, idx, body) "
            "VALUES (?,?,?)", (name, order, json.dumps(source)))

    def get_hub_source(self, name: str) -> Optional[dict]:
        rows = self._query("SELECT body FROM hub_sources WHERE name=?",
                           (name,))
        return json.loads(rows[0]["body"]) if rows else None

    def list_hub_sources(self) -> list[dict]:
        rows = self._query("SELECT body FROM hub_sources ORDER BY idx")
        return [json.loads(row["body"]) for row in rows]

    def delete_hub_source(self, name: str):
        self._execute("DELETE FROM hub_sources WHERE name=?", (name,))

    # -- tags (reference analog: server/api/api/endpoints/tags.py —
    # overwrite/append/delete a tag on a set of artifact identifiers) ------
    def tag_artifacts(self, project: str, tag: str,
                      identifiers: list[dict]) -> int:
        """Apply ``tag`` to each identified artifact version (key + uid).
        Tags are ADDITIVE through the artifact_tags side table: one uid
        per (project, key) holds a given tag, but tagging never disturbs
        other tags — in particular the 'latest' pointer managed by
        store_artifact. Returns how many versions were tagged."""
        project = self._project_or_default(project)
        tagged = 0
        for ident in identifiers:
            key, uid = ident.get("key"), ident.get("uid")
            if not key:
                continue
            rows = self._query(
                "SELECT uid FROM artifacts WHERE project=? AND key=? "
                + ("AND uid=?" if uid else
                   "ORDER BY updated DESC LIMIT 1"),
                (project, key, uid) if uid else (project, key))
            if not rows:
                continue
            self._execute(
                "INSERT OR REPLACE INTO artifact_tags "
                "(project, key, tag, uid) VALUES (?,?,?,?)",
                (project, key, tag, rows[0]["uid"]))
            tagged += 1
        return tagged

    def _clear_artifact_tag(self, project: str, key: str, tag: str):
        """Clear ``tag`` from every holder, keeping body metadata.tag in
        sync with the tag column (a stale body would claim a tag the row
        no longer owns)."""
        rows = self._query(
            "SELECT uid, body FROM artifacts WHERE project=? AND key=? "
            "AND tag=?", (project, key, tag))
        for row in rows:
            body = json.loads(row["body"])
            update_in(body, "metadata.tag", "")
            self._execute(
                "UPDATE artifacts SET tag='', body=? WHERE project=? "
                "AND key=? AND uid=?",
                (json.dumps(body), project, key, row["uid"]))

    def untag_artifacts(self, project: str, tag: str,
                        identifiers: list[dict]) -> int:
        """Remove ``tag`` from the identified artifacts: side-table rows
        AND a matching column tag (set by store_artifact) are both
        cleared, with body metadata kept in sync."""
        project = self._project_or_default(project)
        removed = 0
        for ident in identifiers:
            key = ident.get("key")
            uid = ident.get("uid")
            if not key:
                continue
            where = "project=? AND key=? AND tag=?"
            args = [project, key, tag]
            if uid:
                where += " AND uid=?"
                args.append(uid)
            cursor = self._execute(
                f"DELETE FROM artifact_tags WHERE {where}", tuple(args))
            removed += cursor.rowcount if cursor is not None else 0
            rows = self._query(
                f"SELECT uid, body FROM artifacts WHERE {where}",
                tuple(args))
            for row in rows:
                body = json.loads(row["body"])
                update_in(body, "metadata.tag", "")
                self._execute(
                    "UPDATE artifacts SET tag='', body=? WHERE project=? "
                    "AND key=? AND uid=?",
                    (json.dumps(body), project, key, row["uid"]))
            removed += len(rows)
        return removed

    def store_datastore_profile(self, profile: dict, project: str = "",
                                private: dict | None = None):
        project = self._project_or_default(project)
        name = profile["name"]
        self._execute(
            "INSERT OR REPLACE INTO datastore_profiles "
            "(project, name, type, body) VALUES (?,?,?,?)",
            (project, name, profile.get("type", "basic"),
             json.dumps(profile)))
        from ..datastore.profiles import PROFILE_SECRET_PREFIX

        if private:
            self.store_project_secrets(
                project, {PROFILE_SECRET_PREFIX + name:
                          json.dumps(private)})
        else:
            # a re-store without a private part is a credential
            # rotation/clear — never leave stale secrets behind
            self.delete_project_secrets(
                project, keys=[PROFILE_SECRET_PREFIX + name])

    def get_datastore_profile(self, name: str, project: str = ""
                              ) -> Optional[dict]:
        project = self._project_or_default(project)
        rows = self._query(
            "SELECT body FROM datastore_profiles WHERE project=? AND name=?",
            (project, name))
        return json.loads(rows[0]["body"]) if rows else None

    def list_datastore_profiles(self, project: str = "") -> list[dict]:
        project = self._project_or_default(project)
        rows = self._query(
            "SELECT body FROM datastore_profiles WHERE project=? "
            "ORDER BY name", (project,))
        return [json.loads(row["body"]) for row in rows]

    def delete_datastore_profile(self, name: str, project: str = ""):
        project = self._project_or_default(project)
        self._execute(
            "DELETE FROM datastore_profiles WHERE project=? AND name=?",
            (project, name))
        from ..datastore.profiles import PROFILE_SECRET_PREFIX

        self.delete_project_secrets(project,
                                    keys=[PROFILE_SECRET_PREFIX + name])

    # -- logs --------------------------------------------------------------
    def _log_path(self, project: str, uid: str) -> str:
        path = os.path.join(self.logs_dir, self._project_or_default(project), uid)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        return path

    def store_log(self, uid: str, project: str = "", body: bytes = b"",
                  append: bool = True):
        if isinstance(body, str):
            body = body.encode()
        collector = self._get_log_collector()
        if collector is not None and append:
            try:
                collector.append(self._project_or_default(project), uid, body)
                return
            except (OSError, RuntimeError):
                self._log_collector = None
        mode = "ab" if append else "wb"
        with open(self._log_path(project, uid), mode) as fp:
            fp.write(body)

    def get_log(self, uid: str, project: str = "", offset: int = 0,
                size: int = -1) -> tuple[str, bytes]:
        run = self.read_run(uid, project)
        state = get_in(run or {}, "status.state", RunStates.unknown)
        collector = self._get_log_collector()
        if collector is not None:
            try:
                return state, collector.get_log(
                    self._project_or_default(project), uid, offset, size)
            except (OSError, RuntimeError):
                self._log_collector = None
        path = self._log_path(project, uid)
        if not os.path.isfile(path):
            return state, b""
        with open(path, "rb") as fp:
            fp.seek(offset)
            data = fp.read(size if size > 0 else -1)
        return state, data

    def get_log_size(self, uid: str, project: str = "") -> int:
        collector = self._get_log_collector()
        if collector is not None:
            try:
                return collector.get_log_size(
                    self._project_or_default(project), uid)
            except (OSError, RuntimeError):
                self._log_collector = None
        path = self._log_path(project, uid)
        return os.path.getsize(path) if os.path.isfile(path) else 0

    # -- artifacts ---------------------------------------------------------
    def store_artifact(self, key, artifact: dict, uid=None, iter=None, tag="",
                       project="", tree=None):
        project = self._project_or_default(project)
        uid = uid or get_in(artifact, "metadata.uid") or generate_uid()
        tag = tag or get_in(artifact, "metadata.tag") or "latest"
        update_in(artifact, "metadata.tag", tag)
        update_in(artifact, "metadata.uid", uid)
        update_in(artifact, "metadata.project", project)
        # only one uid per (project,key) may own a tag (bodies of prior
        # holders are re-synced so they stop claiming the tag); a fresh
        # store also supersedes any side-table assignment of the same tag
        self._clear_artifact_tag(project, key, tag)
        self._execute(
            "DELETE FROM artifact_tags WHERE project=? AND key=? AND tag=?",
            (project, key, tag))
        self._execute(
            "INSERT OR REPLACE INTO artifacts "
            "(project, key, uid, tree, iteration, tag, kind, updated, body) "
            "VALUES (?,?,?,?,?,?,?,?,?)",
            (
                project, key, uid, tree or get_in(artifact, "metadata.tree"),
                iter or get_in(artifact, "metadata.iter", 0), tag,
                artifact.get("kind", "artifact"), now_iso(),
                json.dumps(artifact, default=str),
            ),
        )

    def read_artifact(self, key, tag=None, iter=None, project="", tree=None,
                      uid=None) -> Optional[dict]:
        project = self._project_or_default(project)
        sql = "SELECT body FROM artifacts WHERE project=? AND key=?"
        params: list = [project, key]
        if iter is not None and not uid:
            # iteration addressing applies in EVERY resolution mode
            # (store://...#iter without @tree must not fall through to
            # whichever iteration last claimed the tag)
            sql += " AND iteration=?"
            params.append(iter)
        if uid:
            sql += " AND uid=?"
            params.append(uid)
        elif tree:
            sql += " AND tree=?"
            params.append(tree)
        elif iter is not None:
            # iteration addressing (store://...#N): the newest producer's
            # iteration N. The iteration WINS over any tag part — hyper-run
            # children don't carry the parent's tag, and the tag side-table
            # maps a tag to ONE uid, which can't coexist with an explicit
            # iteration filter
            pass
        else:
            wanted = tag or "latest"
            side = self._query(
                "SELECT uid FROM artifact_tags WHERE project=? AND key=? "
                "AND tag=?", (project, key, wanted))
            side_uid = side[0]["uid"] if side else None
            if side_uid:
                stale = not self._query(
                    "SELECT 1 FROM artifacts WHERE project=? AND key=? "
                    "AND uid=?", (project, key, side_uid))
                if stale:
                    # the tagged version was deleted — drop the stale row
                    # and resolve through the tag column instead
                    self._execute(
                        "DELETE FROM artifact_tags WHERE project=? AND "
                        "key=? AND tag=?", (project, key, wanted))
                    side_uid = None
            if side_uid:
                sql += " AND uid=?"
                params.append(side_uid)
            else:
                sql += " AND tag=?"
                params.append(wanted)
        sql += " ORDER BY updated DESC LIMIT 1"
        rows = self._query(sql, tuple(params))
        if not rows:
            raise RunDBError(f"artifact {project}/{key} (tag={tag}) not found")
        body = json.loads(rows[0]["body"])
        if tag:
            # a side-table tag is a view: report the tag it was read by
            update_in(body, "metadata.tag", tag)
        return body

    def list_artifacts(self, name="", project="", tag=None, labels=None,
                       since=None, until=None, kind=None, category=None,
                       tree=None) -> list:
        project = self._project_or_default(project)
        sql = "SELECT body FROM artifacts WHERE project=?"
        params: list = [project]
        if name:
            sql += " AND key LIKE ?"
            params.append(f"%{name}%")
        if tag and tag != "*":
            sql += (" AND (tag=? OR uid IN (SELECT uid FROM artifact_tags "
                    "WHERE project=? AND key=artifacts.key AND tag=?))")
            params.extend([tag, project, tag])
        if kind:
            sql += " AND kind=?"
            params.append(kind)
        if tree:
            sql += " AND tree=?"
            params.append(tree)
        sql += " ORDER BY updated DESC"
        rows = self._query(sql, tuple(params))
        out = [json.loads(r["body"]) for r in rows]
        return [a for a in out if _labels_match(a, labels)]

    def del_artifact(self, key, tag=None, project="", uid=None):
        project = self._project_or_default(project)
        sql = "DELETE FROM artifacts WHERE project=? AND key=?"
        params: list = [project, key]
        if uid:
            sql += " AND uid=?"
            params.append(uid)
        elif tag:
            sql += " AND tag=?"
            params.append(tag)
        self._execute(sql, tuple(params))
        # side-table rows must not outlive their versions
        if uid:
            self._execute(
                "DELETE FROM artifact_tags WHERE project=? AND key=? "
                "AND uid=?", (project, key, uid))
        elif tag:
            self._execute(
                "DELETE FROM artifact_tags WHERE project=? AND key=? "
                "AND tag=?", (project, key, tag))
        else:
            self._execute(
                "DELETE FROM artifact_tags WHERE project=? AND key=?",
                (project, key))

    # -- functions ---------------------------------------------------------
    def store_function(self, function: dict, name, project="", tag="",
                       versioned=False) -> str:
        import hashlib

        project = self._project_or_default(project)
        tag = tag or get_in(function, "metadata.tag") or "latest"
        body = json.dumps(function, default=str)
        hash_key = hashlib.sha1(body.encode()).hexdigest()
        update_in(function, "metadata.hash", hash_key)
        update_in(function, "metadata.project", project)
        body = json.dumps(function, default=str)
        self._execute(
            "INSERT OR REPLACE INTO functions "
            "(project, name, tag, hash_key, updated, body) VALUES (?,?,?,?,?,?)",
            (project, name, tag, hash_key, now_iso(), body),
        )
        if versioned:
            self._execute(
                "INSERT OR REPLACE INTO function_versions "
                "(project, name, hash_key, updated, body) VALUES (?,?,?,?,?)",
                (project, name, hash_key, now_iso(), body),
            )
        return hash_key

    def get_function(self, name, project="", tag="", hash_key="") -> dict:
        project = self._project_or_default(project)
        if hash_key:
            rows = self._query(
                "SELECT body FROM function_versions WHERE project=? AND name=? "
                "AND hash_key=?", (project, name, hash_key))
        else:
            rows = self._query(
                "SELECT body FROM functions WHERE project=? AND name=? AND tag=?",
                (project, name, tag or "latest"))
        if not rows:
            raise RunDBError(f"function {project}/{name}:{tag or hash_key} not found")
        return json.loads(rows[0]["body"])

    def list_functions(self, name="", project="", tag="", labels=None) -> list:
        project = self._project_or_default(project)
        sql = "SELECT body FROM functions WHERE project=?"
        params: list = [project]
        if name:
            sql += " AND name LIKE ?"
            params.append(f"%{name}%")
        if tag:
            sql += " AND tag=?"
            params.append(tag)
        rows = self._query(sql, tuple(params))
        out = [json.loads(r["body"]) for r in rows]
        return [f for f in out if _labels_match(f, labels)]

    def delete_function(self, name, project=""):
        project = self._project_or_default(project)
        self._execute("DELETE FROM functions WHERE project=? AND name=?",
                      (project, name))
        self._execute("DELETE FROM function_versions WHERE project=? AND name=?",
                      (project, name))

    # -- projects ----------------------------------------------------------
    def store_project(self, name: str, project: dict) -> dict:
        update_in(project, "metadata.name", name)
        state = get_in(project, "status.state", "online")
        created = get_in(project, "metadata.created", now_iso())
        update_in(project, "metadata.created", created)
        self._execute(
            "INSERT OR REPLACE INTO projects (name, state, created, body) "
            "VALUES (?,?,?,?)",
            (name, state, created, json.dumps(project, default=str)),
        )
        return project

    def get_project(self, name: str) -> Optional[dict]:
        rows = self._query("SELECT body FROM projects WHERE name=?", (name,))
        return json.loads(rows[0]["body"]) if rows else None

    def list_projects(self, owner=None, labels=None, state=None) -> list:
        sql = "SELECT body FROM projects"
        params: tuple = ()
        if state:
            sql += " WHERE state=?"
            params = (state,)
        rows = self._query(sql, params)
        out = [json.loads(r["body"]) for r in rows]
        return [p for p in out if _labels_match(p, labels)]

    def delete_project(self, name: str, deletion_strategy: str = "restricted"):
        if deletion_strategy == "restricted":
            runs = self._query(
                "SELECT COUNT(*) AS c FROM runs WHERE project=?", (name,))
            if runs[0]["c"]:
                raise RunDBError(
                    f"project {name} has runs; use deletion_strategy='cascade'")
        for table in ("runs", "artifacts", "functions", "function_versions",
                      "schedules", "feature_sets", "feature_vectors",
                      "model_endpoints", "alert_configs"):
            self._execute(f"DELETE FROM {table} WHERE project=?", (name,))
        self._execute("DELETE FROM projects WHERE name=?", (name,))

    # -- schedules ---------------------------------------------------------
    def store_schedule(self, project: str, name: str, schedule: dict):
        project = self._project_or_default(project)
        self._execute(
            "INSERT OR REPLACE INTO schedules "
            "(project, name, kind, cron, next_run_time, body) VALUES (?,?,?,?,?,?)",
            (project, name, schedule.get("kind", "job"),
             schedule.get("cron_trigger", ""), schedule.get("next_run_time"),
             json.dumps(schedule, default=str)),
        )

    def get_schedule(self, project: str, name: str) -> Optional[dict]:
        rows = self._query(
            "SELECT body FROM schedules WHERE project=? AND name=?",
            (self._project_or_default(project), name))
        if not rows:
            raise RunDBError(f"schedule {project}/{name} not found")
        return json.loads(rows[0]["body"])

    def list_schedules(self, project: str = "") -> list:
        if project and project != "*":
            rows = self._query("SELECT body FROM schedules WHERE project=?",
                               (self._project_or_default(project),))
        else:
            rows = self._query("SELECT body FROM schedules")
        return [json.loads(r["body"]) for r in rows]

    def delete_schedule(self, project: str, name: str):
        self._execute("DELETE FROM schedules WHERE project=? AND name=?",
                      (self._project_or_default(project), name))

    # -- feature store ------------------------------------------------------
    def _store_versioned(self, table: str, obj: dict, name, project, tag, uid):
        project = self._project_or_default(project)
        name = name or get_in(obj, "metadata.name")
        tag = tag or get_in(obj, "metadata.tag") or "latest"
        uid = uid or get_in(obj, "metadata.uid") or generate_uid()
        update_in(obj, "metadata.uid", uid)
        update_in(obj, "metadata.project", project)
        self._execute(
            f"INSERT OR REPLACE INTO {table} "
            "(project, name, tag, uid, updated, body) VALUES (?,?,?,?,?,?)",
            (project, name, tag, uid, now_iso(), json.dumps(obj, default=str)),
        )
        return uid

    def _get_versioned(self, table: str, name, project, tag, uid):
        project = self._project_or_default(project)
        if uid:
            rows = self._query(
                f"SELECT body FROM {table} WHERE project=? AND name=? AND uid=?",
                (project, name, uid))
        else:
            rows = self._query(
                f"SELECT body FROM {table} WHERE project=? AND name=? AND tag=?",
                (project, name, tag or "latest"))
        if not rows:
            raise RunDBError(f"{table} {project}/{name} not found")
        return json.loads(rows[0]["body"])

    def _list_versioned(self, table: str, project, name, tag, labels):
        project = self._project_or_default(project)
        sql = f"SELECT body FROM {table} WHERE project=?"
        params: list = [project]
        if name:
            sql += " AND name LIKE ?"
            params.append(f"%{name}%")
        if tag:
            sql += " AND tag=?"
            params.append(tag)
        rows = self._query(sql, tuple(params))
        out = [json.loads(r["body"]) for r in rows]
        return [o for o in out if _labels_match(o, labels)]

    def store_feature_set(self, feature_set, name=None, project="", tag=None,
                          uid=None, versioned=True):
        return self._store_versioned("feature_sets", feature_set, name, project,
                                     tag, uid)

    def get_feature_set(self, name, project="", tag=None, uid=None):
        return self._get_versioned("feature_sets", name, project, tag, uid)

    def list_feature_sets(self, project="", name="", tag=None, labels=None):
        return self._list_versioned("feature_sets", project, name, tag, labels)

    def delete_feature_set(self, name, project="", tag=None, uid=None):
        self._execute("DELETE FROM feature_sets WHERE project=? AND name=?",
                      (self._project_or_default(project), name))

    def store_feature_vector(self, feature_vector, name=None, project="",
                             tag=None, uid=None, versioned=True):
        return self._store_versioned("feature_vectors", feature_vector, name,
                                     project, tag, uid)

    def get_feature_vector(self, name, project="", tag=None, uid=None):
        return self._get_versioned("feature_vectors", name, project, tag, uid)

    def list_feature_vectors(self, project="", name="", tag=None, labels=None):
        return self._list_versioned("feature_vectors", project, name, tag, labels)

    def delete_feature_vector(self, name, project="", tag=None, uid=None):
        self._execute("DELETE FROM feature_vectors WHERE project=? AND name=?",
                      (self._project_or_default(project), name))

    # -- model endpoints ----------------------------------------------------
    def store_model_endpoint(self, project, endpoint_id, endpoint: dict):
        project = self._project_or_default(project)
        self._execute(
            "INSERT OR REPLACE INTO model_endpoints "
            "(project, uid, model, function, state, updated, body) "
            "VALUES (?,?,?,?,?,?,?)",
            (project, endpoint_id, endpoint.get("model_uri", ""),
             endpoint.get("function_uri", ""), endpoint.get("state", "ready"),
             now_iso(), json.dumps(endpoint, default=str)),
        )

    def get_model_endpoint(self, project, endpoint_id) -> dict:
        rows = self._query(
            "SELECT body FROM model_endpoints WHERE project=? AND uid=?",
            (self._project_or_default(project), endpoint_id))
        if not rows:
            raise RunDBError(f"model endpoint {endpoint_id} not found")
        return json.loads(rows[0]["body"])

    def list_model_endpoints(self, project="", model="", function="",
                             state="") -> list:
        project = self._project_or_default(project)
        sql = "SELECT body FROM model_endpoints WHERE project=?"
        params: list = [project]
        if model:
            sql += " AND model LIKE ?"
            params.append(f"%{model}%")
        if function:
            sql += " AND function LIKE ?"
            params.append(f"%{function}%")
        if state:
            sql += " AND state=?"
            params.append(state)
        rows = self._query(sql, tuple(params))
        return [json.loads(r["body"]) for r in rows]

    def delete_model_endpoint(self, project, endpoint_id):
        self._execute("DELETE FROM model_endpoints WHERE project=? AND uid=?",
                      (self._project_or_default(project), endpoint_id))

    # -- background tasks ---------------------------------------------------
    def store_background_task(self, name: str, state: str, project: str = "",
                              body: dict | None = None):
        self._execute(
            "INSERT OR REPLACE INTO background_tasks "
            "(project, name, state, created, updated, body) VALUES (?,?,?,?,?,?)",
            (project, name, state, now_iso(), now_iso(),
             json.dumps(body or {}, default=str)),
        )

    def list_background_tasks(self, project: str = "") -> list[dict]:
        project = self._project_or_default(project)
        rows = self._query(
            "SELECT name, state, body FROM background_tasks WHERE project=? "
            "ORDER BY name", (project,))
        out = []
        for row in rows:
            body = json.loads(row["body"]) if row["body"] else {}
            body.update({"name": row["name"], "state": row["state"]})
            out.append(body)
        return out

    def get_background_task(self, name: str, project: str = "") -> Optional[dict]:
        rows = self._query(
            "SELECT state, body FROM background_tasks WHERE project=? AND name=?",
            (project, name))
        if not rows:
            return None
        out = json.loads(rows[0]["body"])
        out["state"] = rows[0]["state"]
        out["name"] = name
        return out

    # -- alerts / events ----------------------------------------------------
    def store_alert_config(self, name, config: dict, project=""):
        self._execute(
            "INSERT OR REPLACE INTO alert_configs (project, name, body) "
            "VALUES (?,?,?)",
            (self._project_or_default(project), name,
             json.dumps(config, default=str)),
        )

    def get_alert_config(self, name, project="") -> dict:
        rows = self._query(
            "SELECT body FROM alert_configs WHERE project=? AND name=?",
            (self._project_or_default(project), name))
        if not rows:
            raise RunDBError(f"alert config {name} not found")
        return json.loads(rows[0]["body"])

    def list_alert_configs(self, project="") -> list:
        rows = self._query("SELECT body FROM alert_configs WHERE project=?",
                           (self._project_or_default(project),))
        return [json.loads(r["body"]) for r in rows]

    def delete_alert_config(self, name, project=""):
        self._execute("DELETE FROM alert_configs WHERE project=? AND name=?",
                      (self._project_or_default(project), name))

    def emit_event(self, kind: str, event: dict, project: str = ""):
        self._execute(
            "INSERT INTO events (project, kind, created, body) VALUES (?,?,?,?)",
            (self._project_or_default(project), kind, now_iso(),
             json.dumps(event, default=str)),
        )

    def list_events(self, project: str = "", kind: str = "", since=None) -> list:
        sql = "SELECT kind, created, body FROM events WHERE project=?"
        params: list = [self._project_or_default(project)]
        if kind:
            sql += " AND kind=?"
            params.append(kind)
        if since:
            sql += " AND created>=?"
            params.append(str(since))
        rows = self._query(sql + " ORDER BY id", tuple(params))
        return [
            {"kind": r["kind"], "created": r["created"], **json.loads(r["body"])}
            for r in rows
        ]

    # -- submit (local mode: run in-process) --------------------------------
    def submit_job(self, runspec, schedule=None) -> dict:
        raise RunDBError(
            "submit_job requires a remote service (set MLT_DBPATH); in local "
            "mode runs execute in-process via the local launcher")
