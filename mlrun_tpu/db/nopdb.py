"""No-op DB used when nothing is configured (reference analog: mlrun/db/nopdb.py)."""

from __future__ import annotations

from ..utils import logger
from .base import RunDBInterface


class NopDB(RunDBInterface):
    kind = "nop"

    def __init__(self, url: str = ""):
        self.url = url
        self._warned = False

    def _warn(self):
        if not self._warned:
            logger.warning(
                "no run db configured — results will not be persisted "
                "(set MLT_DBPATH or use the default local sqlite db)")
            self._warned = True

    def store_run(self, struct, uid, project="", iter=0):
        self._warn()

    def update_run(self, updates, uid, project="", iter=0):
        self._warn()

    def read_run(self, uid, project="", iter=0):
        self._warn()
        return {}

    def list_runs(self, *args, **kwargs):
        return []

    def del_run(self, uid, project="", iter=0):
        pass

    def store_log(self, uid, project="", body=b"", append=True):
        pass

    def get_log(self, uid, project="", offset=0, size=-1):
        return "unknown", b""

    def store_artifact(self, key, artifact, uid=None, iter=None, tag="",
                       project="", tree=None):
        self._warn()

    def read_artifact(self, key, tag=None, iter=None, project="", tree=None,
                      uid=None):
        self._warn()
        return {}

    def list_artifacts(self, *args, **kwargs):
        return []

    def del_artifact(self, key, tag=None, project="", uid=None):
        pass

    def store_function(self, function, name, project="", tag="", versioned=False):
        self._warn()
        return ""

    def get_function(self, name, project="", tag="", hash_key=""):
        self._warn()
        return {}

    def list_functions(self, *args, **kwargs):
        return []

    def delete_function(self, name, project=""):
        pass

    def store_project(self, name, project):
        self._warn()
        return project

    def get_project(self, name):
        return None

    def list_projects(self, *args, **kwargs):
        return []

    def delete_project(self, name, deletion_strategy="restricted"):
        pass
